"""FaunaDB suite — the reference's largest (3,649 LoC across 14
namespaces at `faunadb/src/jepsen/faunadb/`).

FaunaDB is a temporal, strict-serializable document store driven over
HTTP by a JSON-serialized query AST (`fauna_query.py` builds it; the
reference goes through the official JVM driver instead,
`faunadb/src/jepsen/faunadb/client.clj:45-60`). This module provides:

  * the wire client + error classification (`client.clj:355-418`)
  * topology modeling (`topology.clj`)
  * workloads: register, bank, bank-index, g2, set, pages, monotonic,
    multimonotonic, internal (one module each in the reference)
  * the replica-aware nemesis menu (`nemesis.clj`): inter/intra-replica
    and single-node partitions, kill/stop, clock skew, topology churn
  * cluster automation (`auto.clj`) and the runner/CLI (`runner.clj`)

One deliberate upgrade over the reference: multimonotonic's read-skew
checker is implemented (per-key successor edges + SCC), where the
reference's is a stub that always passes
(`multimonotonic.clj:read-skew-checker` returns `{:valid? true}`).
"""

from __future__ import annotations

import http.client
import itertools
import json
import socket
import threading
import time as _time
from base64 import b64encode

from .. import checker, cli, client as jclient, control, db as jdb
from .. import generator as gen, independent, models
from ..checker import timeline
from ..control import util as cutil
from ..checker.linear import linearizable
from ..nemesis import (Nemesis, compose as n_compose, f_map as n_fmap,
                       timeout as n_timeout)
from ..nemesis import partition as npart
from ..nemesis import time as ntime
from ..os_ import debian
from ..plot import Plot, write as plot_write
from ..workloads import adya, bank as bankw
from . import fauna_query as q

FAUNA_PORT = 8443
ROOT_KEY = "secret"


# ---------------------------------------------------------------------------
# Wire client (`client.clj`)
# ---------------------------------------------------------------------------

class FaunaError(Exception):
    """An error response from FaunaDB: HTTP status + the first error
    object's code/description."""

    def __init__(self, status: int, code: str, description: str):
        super().__init__(f"{status} {code}: {description}")
        self.status = status
        self.code = code
        self.description = description

    @property
    def unavailable(self) -> bool:
        return self.status == 503 or self.code == "unavailable"

    @property
    def internal(self) -> bool:
        return self.status == 500 or self.code == "internal server error"

    @property
    def bad_request(self) -> bool:
        return self.status == 400

    @property
    def not_found(self) -> bool:
        return self.status == 404 or self.code == "instance not found"


class FaunaConn:
    """One HTTP connection speaking the JSON query protocol. `query`
    POSTs a serialized expression and returns the decoded resource
    (`client.clj:146-180`). linearized=True models the reference's
    `linearized-client` (`client.clj:56-59`), which routes through the
    linearized endpoint for single-key strict serializability."""

    def __init__(self, node: str, port: int = FAUNA_PORT,
                 secret: str = ROOT_KEY, timeout_s: float = 10.0,
                 linearized: bool = False):
        self.node, self.port = node, port
        self.timeout_s = timeout_s
        self.linearized = linearized
        self._auth = "Basic " + b64encode(f"{secret}:".encode()).decode()
        self._http = http.client.HTTPConnection(node, port,
                                                timeout=timeout_s)

    def query(self, expr):
        body = json.dumps(expr).encode()
        headers = {"Authorization": self._auth,
                   "Content-Type": "application/json",
                   "X-FaunaDB-API-Version": "2.1"}
        if self.linearized:
            headers["X-Linearized"] = "true"
        try:
            self._http.request("POST", "/", body=body, headers=headers)
            resp = self._http.getresponse()
            data = resp.read()
        except Exception:
            # a failed exchange leaves the HTTP pipeline desynced
            self._http.close()
            raise
        if resp.status != 200:
            try:
                err = json.loads(data)["errors"][0]
            except Exception:  # noqa: BLE001 — non-JSON error body
                err = {"code": "unknown", "description": data.decode(
                    errors="replace")}
            raise FaunaError(resp.status, err.get("code", "unknown"),
                             err.get("description", ""))
        return _decode(json.loads(data)["resource"])

    def close(self):
        self._http.close()


def _decode(v):
    """Unwrap FaunaDB wire-format special values — {"@ts": ...}
    timestamps, {"@ref": ...} refs, {"@obj": ...} escaped objects —
    the decoding the reference gets from the JVM driver's Value tree
    (`client.clj:115-141`). Plain JSON (and the test fake's output)
    passes through unchanged."""
    if isinstance(v, dict):
        if len(v) == 1:
            if "@ts" in v:
                return v["@ts"]
            if "@ref" in v:
                return _decode(v["@ref"])
            if "@obj" in v:
                return _decode(v["@obj"])
        return {k: _decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode(x) for x in v]
    return v


def connect(test: dict, node: str, linearized: bool = False) -> FaunaConn:
    fn = test.get("fauna-conn-fn")
    if fn is not None:
        return fn(node, linearized)
    return FaunaConn(node, linearized=linearized)


def query_all(conn: FaunaConn, set_expr, size: int = 1024) -> list:
    """Exhaust a paginated set (`client.clj:216-257`)."""
    out = []
    after = None
    while True:
        page = conn.query(q.paginate(set_expr, size=size, after=after))
        out.extend(page.get("data", []))
        after = page.get("after")
        if after is None:
            return out


def upsert_by_ref(r, params: dict):
    """update-or-create (`client.clj:259-266`)."""
    return q.if_(q.exists(r), q.update(r, params), q.create(r, params))


def upsert_class(conn: FaunaConn, params: dict) -> None:
    """Idempotent class creation (`client.clj:276-301`)."""
    conn.query(q.when(q.not_(q.exists(q.class_(params["name"]))),
                      q.create_class(params)))


def upsert_index(conn: FaunaConn, params: dict) -> None:
    conn.query(q.when(q.not_(q.exists(q.index(params["name"]))),
                      q.create_index(params)))


def wait_for_index(conn: FaunaConn, idx, timeout_s: float = 60.0,
                   poll_s: float = 0.5) -> None:
    """Poll the index's active flag (`client.clj:419-441`)."""
    deadline = _time.monotonic() + timeout_s
    while True:
        res = conn.query(q.get(idx))
        if res.get("active"):
            return
        if _time.monotonic() > deadline:
            raise TimeoutError(f"index {idx} never became active")
        _time.sleep(poll_s)


def with_retry(thunk, tries: int = 5, sleep_s: float = 0.2):
    """Setup-time retry on unavailability (`client.clj:355-373`)."""
    while True:
        try:
            return thunk()
        except (FaunaError, ConnectionError, OSError) as e:
            definite = isinstance(e, FaunaError) and not e.unavailable
            tries -= 1
            if definite or tries <= 0:
                raise
            _time.sleep(sleep_s)


def with_errors(op: dict, idempotent: frozenset, thunk,
                pause_s: float = 1.0) -> dict:
    """Run thunk, mapping Fauna/network failures to :fail / :info per
    the reference's taxonomy (`client.clj:375-418`)."""
    crash = "fail" if op["f"] in idempotent else "info"
    try:
        return thunk()
    except FaunaError as e:
        if e.unavailable:
            return {**op, "type": crash,
                    "error": ["unavailable", e.description]}
        if e.internal:
            if "UninitializedException" in e.description:
                return {**op, "type": "fail", "error": "repo-uninitialized"}
            if "Transaction Coordinator is shut down" in e.description:
                return {**op, "type": "fail",
                        "error": "transaction-coordinator-shut-down"}
            return {**op, "type": crash,
                    "error": ["internal-exception", e.description]}
        if "No configured replica" in e.description:
            return {**op, "type": "fail", "error": "no-configured-replica"}
        raise
    except ConnectionRefusedError as e:
        _time.sleep(pause_s)  # we won't reconnect quickly; breathe
        return {**op, "type": "fail", "error": ["connect", str(e)]}
    except (socket.timeout, TimeoutError) as e:
        return {**op, "type": crash, "error": ["timeout", str(e)]}
    except (ConnectionError, OSError) as e:
        if "Connection refused" in str(e):
            _time.sleep(pause_s)
            return {**op, "type": "fail", "error": "connection-refused"}
        return {**op, "type": crash, "error": ["io", str(e)]}


class _FaunaClient(jclient.Client):
    """Shared open/close. Subclasses set `linearized` when they need
    the linearized endpoint."""

    linearized = False

    def __init__(self):
        self.conn: FaunaConn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.conn = connect(test, node, linearized=self.linearized)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _pause_s(self, test) -> float:
        return test.get("fauna-conn-retry-delay", 1.0)


# ---------------------------------------------------------------------------
# register (`register.clj`)
# ---------------------------------------------------------------------------

REGISTER_CLASS = "test"


def _r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def _w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rng.randrange(5)}


def _cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [gen.rng.randrange(5), gen.rng.randrange(5)]}


class AtomicClient(_FaunaClient):
    """Keyed CAS register over instances of class "test"
    (`register.clj:21-63`)."""

    linearized = True

    def setup(self, test):
        with_retry(lambda: upsert_class(self.conn,
                                        {"name": REGISTER_CLASS}))

    def invoke(self, test, op):
        def body():
            k, val = op["value"]
            r = q.ref(REGISTER_CLASS, k)
            if op["f"] == "read":
                v = self.conn.query(q.if_(q.exists(r), q.get(r), None))
                reg = (v or {}).get("data", {}).get("register") \
                    if isinstance(v, dict) else None
                return {**op, "type": "ok",
                        "value": independent.ktuple(k, reg),
                        "write-ts": (v or {}).get("ts")
                        if isinstance(v, dict) else None}
            if op["f"] == "write":
                res = self.conn.query(q.if_(
                    q.exists(r),
                    q.update(r, {"data": {"register": val}}),
                    q.create(r, {"data": {"register": val}})))
                return {**op, "type": "ok", "write-ts": res.get("ts")}
            # cas (`register.clj:48-60`)
            expected, new = val
            res = self.conn.query(q.if_(
                q.exists(r),
                q.let({"reg": q.select(["data", "register"], q.get(r))},
                      q.if_(q.eq(expected, q.var("reg")),
                            q.update(r, {"data": {"register": new}}),
                            False)),
                False))
            out = {**op, "type": "ok" if res else "fail"}
            if res:
                out["write-ts"] = res.get("ts")
            return out
        return with_errors(op, frozenset({"read"}), body,
                           self._pause_s(test))


def register_workload(opts: dict) -> dict:
    """Independent keyed CAS registers (`register.clj:65-84`)."""
    n = max(1, len(opts.get("nodes", [])) or 5)

    def fgen(k):
        return gen.limit(
            opts.get("ops-per-key", 100),
            gen.stagger(opts.get("register-stagger", 0.1), gen.delay(
                opts.get("register-delay", 0.5),
                gen.reserve(n, gen.mix([_w, _cas, _cas]), _r))))

    return {
        "client": AtomicClient(),
        "generator": independent.concurrent_generator(
            2 * n, itertools.count(), fgen),
        "checker": independent.checker(checker.compose({
            "timeline": timeline.html(),
            # nil-initial register: instances don't exist until the
            # first write creates them (reference `(model/cas-register
            # 0)` is wrong about Fauna's initial state; reads of a
            # never-written key return nil here)
            "linearizable": linearizable(models.cas_register()),
        })),
    }


# ---------------------------------------------------------------------------
# bank (`bank.clj`)
# ---------------------------------------------------------------------------

ACCOUNTS_CLASS = "accounts"
BANK_IDX = "all_accounts"

_NEGATIVE_ABORT = "balance would go negative"


class BankClient(_FaunaClient):
    """Transactional transfers across account instances
    (`bank.clj:70-135`). `fixed-instances` writes zero balances instead
    of deleting emptied accounts; `at-query` wraps reads in temporal
    `at` snapshots."""

    def setup(self, test):
        def go():
            upsert_class(self.conn, {"name": ACCOUNTS_CLASS})
            self._create_accounts(test)
        with_retry(go)

    def _create_accounts(self, test):
        accounts = test.get("accounts", list(range(8)))
        r0 = q.ref(ACCOUNTS_CLASS, accounts[0])
        self.conn.query(q.when(
            q.not_(q.exists(r0)),
            q.create(r0, {"data": {"balance":
                                   test.get("total-amount", 100)}})))
        if test.get("fixed-instances"):
            self.conn.query(q.do(*[
                upsert_by_ref(q.ref(ACCOUNTS_CLASS, a),
                              {"data": {"balance": 0}})
                for a in accounts[1:]]))

    def _read_expr(self, test):
        return [q.when(q.exists(q.ref(ACCOUNTS_CLASS, i)),
                       [i, q.select(["data", "balance"],
                                    q.get(q.ref(ACCOUNTS_CLASS, i)))])
                for i in test.get("accounts", list(range(8)))]

    def _wrapped(self, test, op, thunk):
        def body():
            try:
                return thunk()
            except FaunaError as e:
                if e.bad_request and _NEGATIVE_ABORT in e.description:
                    return {**op, "type": "fail", "error": "negative"}
                raise
        return with_errors(op, frozenset({"read"}), body,
                           self._pause_s(test))

    def invoke(self, test, op):
        if op["f"] == "read":
            def read():
                expr = self._read_expr(test)
                if test.get("at-query"):
                    ts_res = self.conn.query(
                        [q.NOW, q.at(q.NOW, expr)])
                else:
                    ts_res = self.conn.query([None, expr])
                ts, res = ts_res
                balances = {pair[0]: pair[1] for pair in res
                            if isinstance(pair, list)}
                return {**op, "type": "ok", "value": balances,
                        "ts": str(ts)}
            return self._wrapped(test, op, read)

        def transfer():
            v = op["value"]
            frm, to, amount = v["from"], v["to"], v["amount"]
            fr = q.ref(ACCOUNTS_CLASS, frm)
            tr = q.ref(ACCOUNTS_CLASS, to)
            debit = q.let(
                {"a": q.subtract(
                    q.if_(q.exists(fr),
                          q.select(["data", "balance"], q.get(fr)), 0),
                    amount)},
                q.cond(
                    q.lt(q.var("a"), 0), q.abort(_NEGATIVE_ABORT),
                    q.and_(q.eq(q.var("a"), 0),
                           not test.get("fixed-instances")),
                    q.delete(fr),
                    q.update(fr, {"data": {"balance": q.var("a")}})))
            credit = q.if_(
                q.exists(tr),
                q.let({"b": q.add(q.select(["data", "balance"],
                                           q.get(tr)), amount)},
                      q.update(tr, {"data": {"balance": q.var("b")}})),
                q.create(tr, {"data": {"balance": amount}}))
            self.conn.query(q.do(debit, credit))
            return {**op, "type": "ok"}
        return self._wrapped(test, op, transfer)


class IndexBankClient(BankClient):
    """Bank variant reading through an index (`bank.clj:138-176`)."""

    def setup(self, test):
        def go():
            upsert_class(self.conn, {"name": ACCOUNTS_CLASS})
            upsert_index(self.conn, {
                "name": BANK_IDX,
                "source": q.class_(ACCOUNTS_CLASS),
                "active": True,
                "serialized": bool(test.get("serialized-indices")),
                "values": [{"field": ["ref"]},
                           {"field": ["data", "balance"]}]})
            wait_for_index(self.conn, q.index(BANK_IDX))
            self._create_accounts(test)
        with_retry(go)

    def invoke(self, test, op):
        if op["f"] != "read":
            return super().invoke(test, op)

        def read():
            rows = query_all(self.conn, q.match(q.index(BANK_IDX)))
            balances = {int(ref["id"]): bal for ref, bal in rows}
            return {**op, "type": "ok", "value": balances}
        return self._wrapped(test, op, read)


def bank_workload(opts: dict) -> dict:
    """`bank.clj:178-183`: the shared bank test with a 1/10 delay."""
    w = bankw.test()
    return {**w, "client": BankClient(),
            "generator": gen.delay(opts.get("bank-delay", 0.1),
                                   w["generator"])}


def bank_index_workload(opts: dict) -> dict:
    w = bankw.test()
    return {**w, "client": IndexBankClient(),
            "generator": gen.delay(opts.get("bank-delay", 0.1),
                                   w["generator"])}


# ---------------------------------------------------------------------------
# g2 (`g2.clj`)
# ---------------------------------------------------------------------------

class G2Client(_FaunaClient):
    """Anti-dependency-cycle probe: insert into class a or b only when
    the *other* class's index shows no row for this key
    (`g2.clj:37-70`)."""

    def setup(self, test):
        def go():
            serialized = bool(test.get("serialized-indices", True))
            for name in ("a", "b"):
                upsert_class(self.conn, {"name": name})
                upsert_index(self.conn, {
                    "name": f"{name}-index",
                    "source": q.class_(name),
                    "active": True,
                    "serialized": serialized,
                    "terms": [{"field": ["data", "key"]}]})
            wait_for_index(self.conn, q.index("a-index"))
            wait_for_index(self.conn, q.index("b-index"))
        with_retry(go)

    def invoke(self, test, op):
        def body():
            k, (a_id, b_id) = op["value"]
            ins_id = a_id if a_id is not None else b_id
            cls = "a" if a_id is not None else "b"
            other_idx = q.index("b-index" if a_id is not None
                                else "a-index")
            res = self.conn.query(
                q.when(q.not_(q.non_empty(q.paginate(
                    q.match(other_idx, k), size=1))),
                       q.create(q.ref(cls, ins_id),
                                {"data": {"key": k}})))
            return {**op, "type": "ok" if res else "fail"}
        return with_errors(op, frozenset(), body, self._pause_s(test))


def g2_workload(opts: dict) -> dict:
    return {"client": G2Client(),
            "generator": adya.g2_gen(),
            "checker": adya.g2_checker()}


# ---------------------------------------------------------------------------
# set (`set.clj`)
# ---------------------------------------------------------------------------

ELEMENTS_CLASS = "elements"
SIDE_EFFECTS_CLASS = "side-effects"
SET_IDX = "all-elements"


class SetClient(_FaunaClient):
    """Insert-only set read back through an index; `strong-read`
    smuggles a write into the read txn to force strict serializability
    (`set.clj:19-63`)."""

    linearized = True

    def setup(self, test):
        def go():
            upsert_class(self.conn, {"name": ELEMENTS_CLASS})
            upsert_class(self.conn, {"name": SIDE_EFFECTS_CLASS})
            upsert_index(self.conn, {
                "name": SET_IDX,
                "source": q.class_(ELEMENTS_CLASS),
                "active": True,
                "serialized": bool(test.get("serialized-indices", True)),
                "values": [{"field": ["data", "value"]}]})
            wait_for_index(self.conn, q.index(SET_IDX))
        with_retry(go)

    def invoke(self, test, op):
        def body():
            if op["f"] == "add":
                v = op["value"]
                self.conn.query(q.create(q.ref(ELEMENTS_CLASS, v),
                                         {"data": {"value": v}}))
                return {**op, "type": "ok"}
            if test.get("strong-read"):
                # read + side-effecting create in one txn (`set.clj:44-53`)
                rows = query_all(
                    self.conn,
                    q.let({"r": q.match(q.index(SET_IDX))},
                          q.do(q.at(q.NOW, q.create(
                              q.class_(SIDE_EFFECTS_CLASS), {})),
                               q.var("r"))))
            else:
                rows = query_all(self.conn, q.match(q.index(SET_IDX)))
            return {**op, "type": "ok", "value": sorted(set(rows))}
        return with_errors(op, frozenset({"read"}), body,
                           self._pause_s(test))


def set_workload(opts: dict) -> dict:
    adds = gen.IterGen({"type": "invoke", "f": "add", "value": v}
                       for v in itertools.count())
    def reads(test, ctx):
        # fn gen: a bare dict is one-shot, capping the run at 1 read
        return {"type": "invoke", "f": "read", "value": None}

    return {
        "client": SetClient(),
        # reads deliberately starve writes (`set.clj:76-79`)
        "generator": gen.stagger(1 / 5, gen.mix([adds, reads])),
        "final-generator": gen.once(
            {"type": "invoke", "f": "read", "value": None}),
        "checker": checker.set_full(
            linearizable=bool(opts.get("strong-read")
                              and opts.get("serialized-indices"))),
    }


# ---------------------------------------------------------------------------
# pages (`pages.clj`)
# ---------------------------------------------------------------------------

class PagesClient(_FaunaClient):
    """Insert groups atomically; read the whole keyed index
    (`pages.clj:27-64`)."""

    def setup(self, test):
        def go():
            upsert_class(self.conn, {"name": ELEMENTS_CLASS})
            upsert_index(self.conn, {
                "name": SET_IDX,
                "source": q.class_(ELEMENTS_CLASS),
                "active": True,
                "serialized": bool(test.get("serialized-indices", True)),
                "terms": [{"field": ["data", "key"]}],
                "values": [{"field": ["data", "value"]}]})
            wait_for_index(self.conn, q.index(SET_IDX))
        with_retry(go)

    def invoke(self, test, op):
        def body():
            k, v = op["value"]
            if op["f"] == "add":
                self.conn.query(q.do(*[
                    q.create(q.class_(ELEMENTS_CLASS),
                             {"data": {"key": k, "value": x}})
                    for x in v]))
                return {**op, "type": "ok"}
            rows = query_all(self.conn, q.match(q.index(SET_IDX), k))
            return {**op, "type": "ok",
                    "value": independent.ktuple(k, list(rows))}
        return with_errors(op, frozenset({"read"}), body,
                           self._pause_s(test))


def pages_read_errs(idx: dict, read: set, errs=None) -> list:
    """Can `read` be expressed as a union of add-groups? Pick any
    element, cross off its whole group, recurse (`pages.clj:66-89`)."""
    errs = [] if errs is None else errs
    read = set(read)
    while read:
        e = next(iter(read))
        group = idx.get(e, frozenset({e}))
        missing = [x for x in group if x not in read]
        if missing:
            errs.append({"expected": sorted(group),
                         "found": sorted(read & set(group))})
        read -= set(group)
    return errs


class PagesChecker(checker.Checker):
    """Each read must be a union of potentially-committed add groups
    with no duplicates (`pages.clj:91-141`)."""

    def check(self, test, hist, opts):
        invokes, fails = set(), set()
        groups = []
        for op in hist:
            if op.get("f") != "add":
                continue
            v = tuple(op.get("value") or ())
            if op.get("type") == "invoke":
                invokes.add(v)
                groups.append(v)
            elif op.get("type") == "fail":
                fails.add(v)
        possible = invokes - fails
        idx: dict = {}
        # dedupe while preserving invocation order (the reference folds
        # over a *set* of adds, `pages.clj:110-120`)
        for g in dict.fromkeys(groups):
            if g in possible:
                for x in g:
                    assert x not in idx, "Elements must be unique"
                    idx[x] = frozenset(g)
        errs = []
        ok_reads = 0
        for op in hist:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            ok_reads += 1
            v = list(op.get("value") or [])
            vs = set(v)
            if len(v) != len(vs):
                errs.append({"op": op, "errors": ["duplicate-items"]})
                continue
            es = pages_read_errs(idx, vs)
            if es:
                errs.append({"op": op, "errors": es})
        return {"valid?": not errs,
                "ok-read-count": ok_reads,
                "error-count": len(errs),
                "first-error": errs[0] if errs else None}


def pages_workload(opts: dict) -> dict:
    n = max(1, len(opts.get("nodes", [])) or 5)
    half_range = opts.get("pages-elements", 10_000)
    group_size = 4

    def fgen(k):
        vals = list(range(-half_range, half_range))
        gen.rng.shuffle(vals)
        groups = [tuple(vals[i:i + group_size])
                  for i in range(0, len(vals), group_size)]
        # 4:1 add:read weighting (`pages.clj:153-161`); four separate
        # IterGen wrappers over ONE shared iterator so no group is
        # emitted twice (a single instance in four mix slots would
        # re-emit its memoized head from each slot)
        it = iter({"type": "invoke", "f": "add", "value": g}
                  for g in groups)
        reads = {"type": "invoke", "f": "read", "value": None}
        return gen.stagger(
            1 / 5, gen.limit(opts.get("ops-per-key", 256),
                             gen.mix([gen.IterGen(it), gen.IterGen(it),
                                      gen.IterGen(it), gen.IterGen(it),
                                      reads])))

    return {"client": PagesClient(),
            "generator": independent.concurrent_generator(
                2 * n, itertools.count(), fgen),
            "checker": independent.checker(PagesChecker())}


# ---------------------------------------------------------------------------
# monotonic (`monotonic.clj`)
# ---------------------------------------------------------------------------

REGISTERS_CLASS = "registers"
MONO_KEY = 0


def strip_time(ts) -> str:
    """Drop the trailing Z so timestamps compare as strings
    (`monotonic.clj:52-60`)."""
    s = str(ts)
    assert s.endswith("Z"), s
    return s[:-1]


class MonotonicClient(_FaunaClient):
    """Increment-only register read at current and past timestamps
    (`monotonic.clj:84-147`)."""

    def setup(self, test):
        with_retry(lambda: upsert_class(self.conn,
                                        {"name": REGISTERS_CLASS}))

    def _jittered_now(self, test, jitter_ms: int) -> str:
        """A timestamp up to jitter_ms in the past
        (`client.clj:312-318` jitter-time)."""
        now = self.conn.query(q.NOW)
        fn = test.get("fauna-jitter-time-fn")
        if fn is not None:
            return fn(str(now), jitter_ms)
        from datetime import datetime, timedelta
        base = datetime.fromisoformat(str(now).rstrip("Z"))
        back = timedelta(
            milliseconds=gen.rng.randrange(jitter_ms + 1))
        return (base - back).isoformat() + "Z"

    def invoke(self, test, op):
        def body():
            r = q.ref(REGISTERS_CLASS, MONO_KEY)
            f = op["f"]
            if f == "inc":
                res = self.conn.query(
                    [q.NOW,
                     q.if_(q.exists(r),
                           q.let({"v": q.select(["data", "value"],
                                                q.get(r)),
                                  "_": q.update(
                                      r, {"data": {"value": q.add(
                                          q.var("v"), 1)}})},
                                 q.var("v")),
                           q.do(q.create(r, {"data": {"value": 1}}), 0))])
                return {**op, "type": "ok",
                        "value": [strip_time(res[0]), res[1]]}
            if f == "read":
                res = self.conn.query(
                    [q.NOW, q.if_(q.exists(r),
                                  q.select(["data", "value"], q.get(r)),
                                  0)])
                return {**op, "type": "ok",
                        "value": [strip_time(res[0]), res[1]]}
            if f == "read-at":
                ts = (op.get("value") or [None])[0]
                jitter = test.get("at-query-jitter", 0)
                if ts is None and jitter:
                    ts = self._jittered_now(test, jitter)
                ts_expr = ts if ts is not None else q.NOW
                res = self.conn.query(
                    [ts_expr,
                     q.at(ts_expr,
                          q.if_(q.exists(r),
                                q.select(["data", "value"], q.get(r)),
                                0))])
                return {**op, "type": "ok",
                        "value": [strip_time(res[0]), res[1]]}
            # events: the instance's version history (`monotonic.clj:136`)
            evs = self.conn.query(q.paginate(q.events(r), size=1000))
            return {**op, "type": "ok", "value": evs.get("data", [])}

        def guarded():
            try:
                return body()
            except FaunaError as e:
                if e.not_found:
                    return {**op, "type": "fail", "error": "not-found"}
                raise
        return with_errors(op, frozenset({"read", "read-at"}), guarded,
                           self._pause_s(test))


def non_monotonic_pairs_by_process(extract, hist) -> list:
    """Pairs of same-process ok ops whose extracted value went
    backwards (`monotonic.clj:151-171`)."""
    last: dict = {}
    errs = []
    for op in hist:
        if op.get("type") != "ok":
            continue
        p = op.get("process")
        v = extract(op)
        prev = last.get(p)
        if prev is not None and extract(prev) is not None \
                and v is not None and v < extract(prev):
            errs.append([prev, op])
        last[p] = op
    return errs


class MonotonicChecker(checker.Checker):
    """Per-process monotonicity of values and timestamps
    (`monotonic.clj:173-190`)."""

    def check(self, test, hist, opts):
        ops = [o for o in hist if o.get("f") in ("read", "inc")]
        value_errs = non_monotonic_pairs_by_process(
            lambda o: (o.get("value") or [None, None])[1], ops)
        ts_errs = non_monotonic_pairs_by_process(
            lambda o: (o.get("value") or [None])[0], ops)
        return {"valid?": not value_errs and not ts_errs,
                "value-errors": value_errs, "ts-errors": ts_errs}


class TimestampValueChecker(checker.Checker):
    """Globally: sorting reads/incs by Fauna timestamp, values must
    never decrease (`monotonic.clj:203-216`)."""

    def check(self, test, hist, opts):
        ops = sorted((o for o in hist
                      if o.get("type") == "ok"
                      and o.get("f") in ("read-at", "inc")
                      and (o.get("value") or [None])[0] is not None),
                     key=lambda o: o["value"][0])
        errs = [[a, b] for a, b in zip(ops, ops[1:])
                if a["value"][1] is not None and b["value"][1] is not None
                and b["value"][1] < a["value"][1]]
        return {"valid?": not errs, "errors": errs}


class TimestampValuePlotter(checker.Checker):
    """SVG scatter of register value against Fauna timestamp, windowed
    around non-monotonic spots (`monotonic.clj:218-300`: spots ->
    merged +/-32 windows -> one plot each; gnuplot in the reference,
    our plot library renders SVG)."""

    def check(self, test, hist, opts):
        ops = sorted((o for o in hist
                      if o.get("type") == "ok" and o.get("f") == "read-at"
                      and (o.get("value") or [None, None])[1] is not None),
                     key=lambda o: o["value"][0])
        if not ops or not test.get("store-dir"):
            return {"valid?": True}
        from ..checker.perf import out_path
        from ..plot import merged_windows, process_series, \
            regression_spots
        # spots in timestamp order: per-process regressions (the
        # reference plotter's shape) PLUS global consecutive decreases
        # (what TimestampValueChecker flags), so every checker-cited
        # anomaly lands inside a plotted window
        spots = regression_spots(
            [(o.get("process"), o["value"][1]) for o in ops],
            global_too=True)
        # nothing anomalous: plot everything once (the reference emits
        # no plot at all; one overview costs little and helps triage)
        windows = merged_windows(32, spots) or [[0, len(ops)]]
        for wi, (lo, hi) in enumerate(windows):
            window = ops[max(lo, 0):min(hi + 1, len(ops))]
            by_process: dict = {}
            t0 = None
            for o in window:
                try:
                    ts = float(o["value"][0].replace("T", " ")
                               .replace("-", "").replace(":", "")
                               .replace(" ", "") or 0)
                except ValueError:
                    ts = 0.0
                t0 = ts if t0 is None else t0
                by_process.setdefault(o.get("process"), []).append(
                    (ts - t0, o["value"][1]))
            p = Plot(title=f"{test.get('name', '')} timestamp-value "
                           f"by process",
                     xlabel="faunadb timestamp", ylabel="register value",
                     series=process_series(by_process))
            try:
                plot_write(p, out_path(
                    test, opts, f"timestamp-value-{wi}.svg"))
            except Exception:  # noqa: BLE001 — plotting is best-effort
                pass
        return {"valid?": True}


class NotFoundChecker(checker.Checker):
    """Existence-checked reads must never observe not-found
    (`monotonic.clj:302-315`)."""

    def check(self, test, hist, opts):
        errs = [o for o in hist
                if o.get("type") == "fail" and o.get("error") == "not-found"]
        return {"valid?": not errs, "error-count": len(errs),
                "first": errs[0] if errs else None}


def monotonic_workload(opts: dict) -> dict:
    def inc_gen(test, ctx):
        return {"type": "invoke", "f": "inc", "value": None}

    def read_gen(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def read_at_gen(test, ctx):
        return {"type": "invoke", "f": "read-at", "value": [None, None]}

    return {
        "client": MonotonicClient(),
        "generator": gen.mix([inc_gen, read_gen, read_at_gen]),
        "final-generator": gen.once(
            {"type": "invoke", "f": "events", "value": None}),
        "checker": checker.compose({
            "monotonic": MonotonicChecker(),
            "not-found": NotFoundChecker(),
            "timestamp-value": TimestampValueChecker(),
            "timestamp-value-plot": TimestampValuePlotter(),
        }),
    }


# ---------------------------------------------------------------------------
# multimonotonic (`multimonotonic.clj`)
# ---------------------------------------------------------------------------

def map_compare(m1: dict, m2: dict) -> int:
    """Partial-order comparator over state maps; raises Incomparable
    when per-key orders conflict (`multimonotonic.clj:110-150`)."""
    c = 0
    for k, v1 in m1.items():
        if k not in m2:
            continue
        v2 = m2[k]
        c2 = (v1 > v2) - (v1 < v2)
        if c * c2 < 0:
            raise Incomparable(m1, m2)
        if c == 0:
            c = c2
    return c


class Incomparable(Exception):
    def __init__(self, m1, m2):
        super().__init__(f"incomparable states {m1} vs {m2}")
        self.m1, self.m2 = m1, m2


def nonmonotonic_states(state_fn, ops) -> list:
    """Walk ops inferring a per-key lower bound; flag states below it
    (`multimonotonic.clj:152-216`)."""
    inferred: dict = {}
    errs = []
    for op in ops:
        state = state_fn(op)
        nm = [k for k, v in state.items()
              if k in inferred and v < inferred[k]["value"]]
        if nm:
            errs.append({
                "inferred": {k: inferred[k]["value"] for k in state
                             if k in inferred},
                "observed": state, "op": op,
                "errors": {k: [inferred[k],
                               {"value": state[k],
                                "op-index": op.get("index")}]
                           for k in nm}})
        for k, v in state.items():
            if k not in inferred or inferred[k]["value"] < v:
                inferred[k] = {"value": v, "op-index": op.get("index")}
    return errs


def _read_state(op) -> dict:
    regs = (op.get("value") or {}).get("registers") or {}
    return {k: r["value"] for k, r in regs.items()}


class TsOrderChecker(checker.Checker):
    """Reads ordered by Fauna timestamp must observe monotonic register
    states (`multimonotonic.clj:230-246`)."""

    def check(self, test, hist, opts):
        ops = sorted((o for o in hist
                      if o.get("type") == "ok" and o.get("f") == "read"
                      and (o.get("value") or {}).get("ts") is not None),
                     key=lambda o: o["value"]["ts"])
        errs = nonmonotonic_states(_read_state, ops)
        return {"valid?": not errs, "errors": errs}


class ReadSkewChecker(checker.Checker):
    """Read-skew detection via cycle search over per-key version
    orders. The reference documents this algorithm but ships a stub
    that always passes (`multimonotonic.clj:248-290`); here it is
    implemented: each read's state map is a node; for every key we add
    edges from each state to the states holding the next-larger value;
    any SCC larger than one node is a skew cycle."""

    def check(self, test, hist, opts):
        states: list[dict] = []
        seen = set()
        for o in hist:
            if o.get("type") == "ok" and o.get("f") == "read":
                s = _read_state(o)
                key = tuple(sorted(s.items()))
                if s and key not in seen:
                    seen.add(key)
                    states.append(s)
        # per-key next-value edges (`multimonotonic.clj:266-273`)
        edges: dict[int, set[int]] = {i: set() for i in range(len(states))}
        keys = {k for s in states for k in s}
        for k in keys:
            vals = sorted({s[k] for s in states if k in s})
            nxt = {v: vals[i + 1] for i, v in enumerate(vals[:-1])}
            by_val: dict = {}
            for i, s in enumerate(states):
                if k in s:
                    by_val.setdefault(s[k], []).append(i)
            for i, s in enumerate(states):
                if k in s and s[k] in nxt:
                    for j in by_val[nxt[s[k]]]:
                        edges[i].add(j)
        sccs = _tarjan(edges)
        cycles = [[states[i] for i in c] for c in sccs if len(c) > 1]
        return {"valid?": not cycles, "cycles": cycles}


def _tarjan(adj: dict[int, set]) -> list[list[int]]:
    """Iterative Tarjan SCC (host-side; the big transactional SCC work
    lives in the elle kernels — reads here number at most a few
    thousand)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = itertools.count()
    for root in adj:
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = next(counter)
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = next(counter)
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs


class MultiMonotonicClient(_FaunaClient):
    """Blind per-thread register writes + multi-register snapshot reads
    (`multimonotonic.clj:76-110`)."""

    def setup(self, test):
        with_retry(lambda: upsert_class(self.conn,
                                        {"name": REGISTERS_CLASS}))

    def invoke(self, test, op):
        def body():
            if op["f"] == "write":
                self.conn.query([
                    upsert_by_ref(q.ref(REGISTERS_CLASS, k),
                                  {"data": {"value": v}})
                    for k, v in op["value"].items()])
                return {**op, "type": "ok"}
            ks = list(op["value"])
            res = self.conn.query(
                [q.NOW,
                 [q.when(q.exists(q.ref(REGISTERS_CLASS, k)),
                         q.get(q.ref(REGISTERS_CLASS, k))) for k in ks]])
            regs = {}
            for k, inst in zip(ks, res[1]):
                if isinstance(inst, dict):
                    regs[k] = {"value": inst["data"]["value"],
                               "ts": inst.get("ts")}
            return {**op, "type": "ok",
                    "value": {"ts": strip_time(res[0]),
                              "registers": regs}}
        return with_errors(op, frozenset({"read"}), body,
                           self._pause_s(test))


class _MMWrites(gen.Gen):
    """Each thread owns one register (key = its thread id) and blindly
    writes 0, 1, 2, ... — sequenced through update() so probing op()
    twice can't skip values (`multimonotonic.clj:generator`)."""

    def __init__(self, seen: dict, counts: dict | None = None):
        self.seen = seen
        self.counts = counts if counts is not None else {}

    def op(self, test, ctx):
        ts = gen.all_threads(ctx)
        if not ts:
            return None
        t = int(ts[0])
        return (gen.fill_in_op(
            {"type": "invoke", "f": "write",
             "value": {t: self.counts.get(t, 0)}}, ctx), self)

    def update(self, test, ctx, event):
        if event.get("type") == "invoke" and event.get("f") == "write":
            (k, v), = event["value"].items()
            self.seen[k] = max(self.seen.get(k, -1), v)
            counts = dict(self.counts)
            counts[k] = v + 1
            return _MMWrites(self.seen, counts)
        return self


class _MMReads(gen.Gen):
    """Reads of a random nonempty subset of the keys written so far."""

    def __init__(self, seen: dict):
        self.seen = seen

    def op(self, test, ctx):
        ks = sorted(self.seen)
        if not ks:
            ks = [0]
        subset = [k for k in ks if gen.rng.random() < 0.5] or \
            [ks[gen.rng.randrange(len(ks))]]
        return (gen.fill_in_op(
            {"type": "invoke", "f": "read", "value": subset}, ctx), self)

    def update(self, test, ctx, event):
        if event.get("type") == "invoke" and event.get("f") == "write":
            (k, v), = event["value"].items()
            self.seen[k] = max(self.seen.get(k, -1), v)
        return self


def multimonotonic_workload(opts: dict) -> dict:
    seen: dict = {}
    writers = max(1, int(opts.get("concurrency", 10)) // 2)
    return {
        "client": MultiMonotonicClient(),
        "generator": gen.reserve(
            writers, gen.each_thread(_MMWrites(seen)), _MMReads(seen)),
        "checker": checker.compose({
            "ts-order": TsOrderChecker(),
            "read-skew": ReadSkewChecker(),
        }),
    }


# ---------------------------------------------------------------------------
# internal (`internal.clj`)
# ---------------------------------------------------------------------------

CATS_CLASS = "cats"
CATS_IDX = "cats_by_type"


def _match_cats(type_: str):
    """Names of cats of a type, via the index (`internal.clj:33-40`)."""
    return q.select(["data"], q.paginate(
        q.match(q.index(CATS_IDX), type_), size=1024))


class InternalClient(_FaunaClient):
    """Intra-transaction consistency probes: a create must be invisible
    to reads sequenced before it in the same txn, visible after
    (`internal.clj:55-137`)."""

    def setup(self, test):
        def go():
            upsert_class(self.conn, {"name": CATS_CLASS})
            upsert_index(self.conn, {
                "name": CATS_IDX,
                "source": q.class_(CATS_CLASS),
                "active": True,
                "serialized": bool(test.get("serialized-indices", True)),
                "terms": [{"field": ["data", "type"]}],
                "values": [{"field": ["data", "name"]}]})
            wait_for_index(self.conn, q.index(CATS_IDX))
        with_retry(go)

    def invoke(self, test, op):
        def body():
            f, v = op["f"], op.get("value")
            if f == "reset":
                # delete all tabbies and calicos (`internal.clj:42-53`)
                for t in ("tabby", "calico"):
                    for name in query_all(self.conn,
                                          q.match(q.index(CATS_IDX), t)):
                        self.conn.query(q.when(
                            q.exists(q.ref(CATS_CLASS, name)),
                            q.delete(q.ref(CATS_CLASS, name))))
                return {**op, "type": "ok", "value": None}
            if f in ("create-tabby-let", "create-tabby-obj",
                     "create-tabby-arr"):
                create = q.create(q.ref(CATS_CLASS, v),
                                  {"data": {"type": "tabby", "name": v}})
                if f == "create-tabby-let":
                    expr = q.let({"tabbies0": _match_cats("tabby"),
                                  "tabby": create,
                                  "tabbies1": _match_cats("tabby")},
                                 [q.var("tabbies0"), q.var("tabby"),
                                  q.var("tabbies1")])
                else:
                    # obj/arr permutations exercise literal-evaluation
                    # order; our array form covers both
                    expr = [_match_cats("tabby"), create,
                            _match_cats("tabby")]
                t0, tabby, t1 = self.conn.query(expr)
                return {**op, "type": "ok",
                        "value": {"tabbies-0": t0, "tabby": tabby,
                                  "tabbies-1": t1}}
            # change-type (`internal.clj:124-133`)
            res = self.conn.query([
                q.let({"rs": _match_cats("tabby")},
                      q.when(q.non_empty(q.var("rs")),
                             q.update(q.ref(CATS_CLASS,
                                            q.select([0], q.var("rs"))),
                                      {"data": {"type": "calico"}}))),
                _match_cats("tabby"),
                _match_cats("calico")])
            return {**op, "type": "ok", "value": res}
        return with_errors(op, frozenset(), body, self._pause_s(test))


def internal_op_errors(op: dict) -> list:
    """Consistency errors within one op (`internal.clj:139-195`)."""
    v = op.get("value")
    f = op.get("f")
    errs = []
    if f in ("create-tabby-let", "create-tabby-obj", "create-tabby-arr"):
        name = ((v or {}).get("tabby") or {}).get("data", {}).get("name")
        if name is not None:
            if name in (v.get("tabbies-0") or []):
                errs.append({"type": "present-before-create",
                             "name": name, "op": op})
            if name not in (v.get("tabbies-1") or []):
                errs.append({"type": "missing-after-create",
                             "name": name, "op": op})
    elif f == "change-type":
        cat, tabbies, calicos = (v or [None, [], []])[:3]
        name = (cat or {}).get("data", {}).get("name") \
            if isinstance(cat, dict) else None
        if name is not None:
            if name in (tabbies or []):
                errs.append({"type": "present-after-change",
                             "name": name, "op": op})
            if name not in (calicos or []):
                errs.append({"type": "missing-after-change",
                             "name": name, "op": op})
    return errs


class InternalChecker(checker.Checker):
    def check(self, test, hist, opts):
        errors = [e for op in hist if op.get("type") == "ok"
                  for e in internal_op_errors(op)]
        return {"valid?": not errors,
                "error-count": len(errors),
                "error-types": sorted({e["type"] for e in errors}),
                "errors": errors}


def internal_workload(opts: dict) -> dict:
    ids = itertools.count()
    lock = threading.Lock()

    def next_id() -> int:
        with lock:
            return next(ids)

    def creator(f):
        def g(test, ctx):
            return {"type": "invoke", "f": f, "value": next_id()}
        return g

    return {
        "client": InternalClient(),
        "generator": gen.stagger(1 / 10, gen.mix([
            lambda test, ctx: {"type": "invoke", "f": "reset",
                               "value": None},
            lambda test, ctx: {"type": "invoke", "f": "change-type",
                               "value": None},
            creator("create-tabby-let"),
            creator("create-tabby-obj"),
            creator("create-tabby-arr")])),
        "checker": InternalChecker(),
    }


# ---------------------------------------------------------------------------
# Topology (`topology.clj`)
# ---------------------------------------------------------------------------

def replica_name(n: int) -> str:
    return f"replica-{n}"


def initial_topology(test: dict) -> dict:
    """{replica-count, nodes: [{node, state, replica}]}
    (`topology.clj:12-27`)."""
    replicas = test.get("replicas", 1)
    return {"replica-count": replicas,
            "nodes": [{"node": n, "state": "active",
                       "replica": replica_name(i % replicas)}
                      for i, n in enumerate(test["nodes"])]}


def get_node(topo: dict, name: str) -> dict | None:
    for n in topo["nodes"]:
        if n["node"] == name:
            return n
    return None


def only_active(topo: dict) -> dict:
    return {**topo, "nodes": [n for n in topo["nodes"]
                              if n["state"] == "active"]}


def replicas(topo: dict) -> list[str]:
    return [replica_name(i) for i in range(topo["replica-count"])]


def nodes_by_replica(topo: dict) -> dict[str, list[str]]:
    out: dict = {}
    for n in topo["nodes"]:
        out.setdefault(n["replica"], []).append(n["node"])
    return out


def add_ops(test: dict, topo: dict) -> list[dict]:
    """Every node we could add (`topology.clj:104-115`)."""
    active = [n["node"] for n in topo["nodes"]]
    if not active:
        return []
    return [{"type": "info", "f": "add-node",
             "value": {"node": n,
                       "join": active[gen.rng.randrange(len(active))]}}
            for n in set(test["nodes"]) - set(active)]


def remove_ops(test: dict, topo: dict) -> list[dict]:
    """Nodes removable without emptying a replica
    (`topology.clj:117-143`)."""
    topo = only_active(topo)
    candidates = [n for ns in nodes_by_replica(topo).values()
                  if len(ns) > 1 for n in ns]
    return [{"type": "info", "f": "remove-node", "value": n}
            for n in candidates]


def topo_ops(test: dict, topo: dict) -> list[dict]:
    return add_ops(test, topo) + remove_ops(test, topo)


def rand_topo_op(test: dict, topo: dict) -> dict | None:
    """A random transition, balanced across op *types*
    (`topology.clj:163-180`)."""
    groups = [g for g in (add_ops(test, topo), remove_ops(test, topo)) if g]
    if not groups:
        return None
    g = groups[gen.rng.randrange(len(groups))]
    return g[gen.rng.randrange(len(g))]


def apply_topo_op(topo: dict, op: dict) -> dict:
    """The topology resulting from a transition (`topology.clj:182-207`)."""
    f = op["f"]
    if f == "add-node":
        return {**topo,
                "nodes": topo["nodes"] + [{
                    "node": op["value"]["node"], "state": "active",
                    "replica": replica_name(
                        gen.rng.randrange(topo["replica-count"]))}]}
    if f == "remove-node":
        return {**topo,
                "nodes": [{**n, "state": "removing"}
                          if n["node"] == op["value"] else n
                          for n in topo["nodes"]]}
    raise ValueError(f"unknown topology op {f!r}")


# ---------------------------------------------------------------------------
# Nemesis (`nemesis.clj`)
# ---------------------------------------------------------------------------

def _topology(test: dict) -> dict:
    topo = test.get("topology")
    if topo is None:
        topo = {"value": initial_topology(test)}
        test["topology"] = topo
    return topo


def single_node_partition_start(test, ctx):
    """Isolate one node (`nemesis.clj:20-27`)."""
    grudge = npart.complete_grudge(npart.split_one(list(test["nodes"])))
    return {"type": "info", "f": "start-partition", "value": grudge,
            "partition-type": "single-node"}


def intra_replica_partition_start(test, ctx):
    """Split one replica internally (`nemesis.clj:29-40`)."""
    groups = list(nodes_by_replica(_topology(test)["value"]).items())
    replica, nodes = groups[gen.rng.randrange(len(groups))]
    nodes = list(nodes)
    gen.rng.shuffle(nodes)
    grudge = npart.complete_grudge(npart.bisect(nodes))
    return {"type": "info", "f": "start-partition", "value": grudge,
            "partition-type": ["intra-replica", replica]}


def inter_replica_partition_start(test, ctx):
    """Divide replicas from each other (`nemesis.clj:42-55`)."""
    groups = list(nodes_by_replica(_topology(test)["value"]).values())
    gen.rng.shuffle(groups)
    a, b = npart.bisect(groups)
    flat = ([n for g in a for n in g], [n for g in b for n in g])
    grudge = npart.complete_grudge(flat)
    return {"type": "info", "f": "start-partition", "value": grudge,
            "partition-type": "inter-replica"}


def topo_op_gen(test, ctx):
    """A random topology transition, or nothing when none is possible
    (`nemesis.clj:65-72`)."""
    return rand_topo_op(test, _topology(test)["value"])


class TopoNemesis(Nemesis):
    """Applies add-node / remove-node transitions through the cluster
    automation, then commits the new topology (`nemesis.clj:74-139`)."""

    def fs(self):
        return {"add-node", "remove-node"}

    def invoke(self, test, op):
        auto = test.get("fauna-auto") or FaunaAuto()
        topo = _topology(test)
        new = apply_topo_op(topo["value"], op)
        f, v = op["f"], op["value"]
        if f == "add-node":
            def act(t, node):
                auto.configure(t, new, node)
                if node == v["node"]:
                    auto.start(t, node)
                    auto.join(t, node, v["join"])
                return "configured"
            control.on_nodes(test, act,
                             [n["node"] for n in new["nodes"]])
            res = ["added", v]
        else:
            def kill(t, node):
                auto.kill(t, node)
                auto.delete_data_files(t, node)
                return "killed"
            control.on_nodes(test, kill, [v])
            others = [n["node"] for n in topo["value"]["nodes"]
                      if n["node"] != v]
            if others:
                def remove(t, node):
                    auto.remove_node(t, node, v)
                    return "removed"
                control.on_nodes(
                    test, remove,
                    [others[gen.rng.randrange(len(others))]])
            new = {**new, "nodes": [n for n in new["nodes"]
                                    if n["node"] != v]}
            res = ["removed", v]
        topo["value"] = new
        return {**op, "value": res}


class RestartStopKill(Nemesis):
    """start all / stop / kill a random subset (`nemesis.clj:141-161`)."""

    def fs(self):
        return {"restart", "stop", "kill"}

    def invoke(self, test, op):
        auto = test.get("fauna-auto") or FaunaAuto()
        nodes = [n["node"] for n in _topology(test)["value"]["nodes"]]
        if op["f"] in ("stop", "kill"):
            from ..nemesis import combined as ncomb
            nodes = ncomb.random_nonempty_subset(nodes)
        act = {"restart": auto.start, "stop": auto.stop,
               "kill": auto.kill}[op["f"]]

        def f(t, node):
            act(t, node)
            return op["f"]
        return {**op, "value": control.on_nodes(test, f, nodes)}


NEMESIS_SPECS = frozenset({
    "inter-replica-partition", "intra-replica-partition",
    "single-node-partition", "kill", "stop", "topology", "clock-skew"})


def full_nemesis() -> Nemesis:
    """Every fault mode in one composed nemesis (`nemesis.clj:172-186`)."""
    return n_compose([
        n_timeout(60_000, RestartStopKill()),
        n_fmap(lambda f: {"start": "start-partition",
                          "stop": "stop-partition"}.get(f, f),
               npart.partitioner()),
        TopoNemesis(),
        n_fmap(lambda f: {"reset": "reset-clock",
                          "strobe": "strobe-clock",
                          "check-offsets": "check-clock-offsets",
                          "bump": "bump-clock"}.get(f, f),
               ntime.clock_nemesis()),
    ])


def _op(f: str) -> dict:
    return {"type": "info", "f": f, "value": None}


def full_generator(n: dict, interval: float):
    """Mixed fault stream per the enabled specs
    (`nemesis.clj:205-233`)."""
    gens: list = []
    # a bare op dict is a ONE-SHOT generator: recurring fault streams
    # must cycle their op pairs, else each fault fires exactly once
    if n.get("kill"):
        gens.append(itertools.cycle([_op("kill"), _op("restart")]))
    if n.get("stop"):
        gens.append(itertools.cycle([_op("stop"), _op("restart")]))
    if n.get("inter-replica-partition"):
        gens += [inter_replica_partition_start,
                 itertools.cycle([_op("stop-partition")])]
    if n.get("intra-replica-partition"):
        gens += [intra_replica_partition_start,
                 itertools.cycle([_op("stop-partition")])]
    if n.get("single-node-partition"):
        gens += [single_node_partition_start,
                 itertools.cycle([_op("stop-partition")])]
    if n.get("clock-skew"):
        gens.append(gen.f_map(
            lambda f: {"reset": "reset-clock", "strobe": "strobe-clock",
                       "check-offsets": "check-clock-offsets",
                       "bump": "bump-clock"}.get(f, f),
            ntime.clock_gen()))
    if n.get("topology"):
        gens.append(topo_op_gen)
    if not gens:
        return None
    return gen.stagger(interval, gen.mix(gens))


def fauna_nemesis_package(opts: dict) -> dict:
    """{nemesis, generator, final-generator} (`nemesis.clj:235-249`)."""
    n = opts
    finals = []
    if n.get("clock-skew"):
        finals.append(_op("reset-clock"))
    if any(n.get(k) for k in ("inter-replica-partition",
                              "intra-replica-partition",
                              "single-node-partition")):
        finals.append(_op("stop-partition"))
    if n.get("stop") or n.get("kill"):
        finals.append(_op("restart"))
    return {"nemesis": full_nemesis(),
            "generator": full_generator(n, n.get("interval", 10)),
            "final-generator": gen.IterGen(iter(finals))
            if finals else None,
            "perf": [{"name": "partition", "fs": ["start-partition"],
                      "start": ["start-partition"],
                      "stop": ["stop-partition"]}]}


# ---------------------------------------------------------------------------
# Cluster automation (`auto.clj`)
# ---------------------------------------------------------------------------

LOG_DIR = "/var/log/faunadb"
DATA_DIR = "/var/lib/faunadb"
CONFIG = "/etc/faunadb.yml"


class FaunaAuto:
    """Install/configure/init/join over the control layer
    (`auto.clj:107-455`)."""

    def __init__(self, version: str = "2.5.5"):
        self.version = version

    def install(self, test, node):
        """apt repo + package (`auto.clj:379-414`)."""
        debian.install(["curl", "gnupg"])
        control.exec_("bash", "-c",
                      "curl -fsS https://repo.fauna.com/faunadb-gpg-public"
                      ".key | apt-key add -")
        cutil.write_file(
            "deb [arch=all] https://repo.fauna.com/debian stable non-free",
            "/etc/apt/sources.list.d/faunadb.list")
        debian.maybe_update()
        debian.install({"faunadb": self.version})

    def configure(self, test, topo, node):
        """Render /etc/faunadb.yml for this node's replica
        (`auto.clj:416-443`)."""
        me = get_node(topo, node) or {"replica": replica_name(0)}
        cfg = "\n".join([  # (`auto.clj:416-443` renders the same keys)
            "auth_root_key: " + ROOT_KEY,
            f"network_coordinator_http_address: {node}",
            f"network_broadcast_address: {node}",
            f"network_datacenter_name: {me['replica']}",
            f"network_host_id: {node}",
            f"network_listen_address: {node}",
            f"storage_data_path: {DATA_DIR}",
            "storage_transaction_log_nodes:",
            *[f"  - {ns}" for ns in
              [n["node"] for n in topo["nodes"]
               if n.get("state") == "active"]],
        ])
        control.util.write_file(cfg, CONFIG)

    def start(self, test, node):
        control.exec_("service", "faunadb", "start")

    def stop(self, test, node):
        control.exec_("service", "faunadb", "stop")

    def kill(self, test, node):
        control.exec_("bash", "-c",
                      "pkill -9 -f faunadb || true")

    def init(self, test, node):
        """First node initializes the cluster (`auto.clj:114-139`)."""
        control.exec_("faunadb-admin", "init")

    def join(self, test, node, target: str):
        control.exec_("faunadb-admin", "join", target)

    def remove_node(self, test, node, target: str):
        control.exec_("faunadb-admin", "remove", target)

    def status(self, test, node) -> str:
        return control.exec_("faunadb-admin", "status")

    def delete_data_files(self, test, node):
        control.exec_("bash", "-c", f"rm -rf {DATA_DIR}/*")


class FaunaDB(jdb.DB, jdb.Process, jdb.Primary, jdb.LogFiles):
    """DB lifecycle glue (`auto.clj:456-472`). nodes[0] always runs
    `faunadb-admin init`; everyone else synchronizes on the barrier and
    then joins it — init must not race the joins (`auto.clj:107-139`
    has init! and join! as distinct single-node steps)."""

    def __init__(self, auto: FaunaAuto | None = None):
        self.auto = auto or FaunaAuto()

    def setup(self, test, node):
        from .. import core
        test.setdefault("fauna-auto", self.auto)
        topo = _topology(test)["value"]
        self.auto.install(test, node)
        self.auto.configure(test, topo, node)
        self.auto.start(test, node)
        coordinator = test["nodes"][0]
        if node == coordinator:
            self.auto.init(test, node)
        core.synchronize(test)   # joiners wait for init to finish
        if node != coordinator:
            self.auto.join(test, node, coordinator)

    def teardown(self, test, node):
        self.auto.kill(test, node)
        self.auto.delete_data_files(test, node)

    def start(self, test, node):
        self.auto.start(test, node)

    def kill(self, test, node):
        self.auto.kill(test, node)

    def primaries(self, test):
        return [n["node"]
                for n in _topology(test)["value"]["nodes"][:1]]

    def log_files(self, test, node):
        return [f"{LOG_DIR}/core.log", f"{LOG_DIR}/query.log"]


# ---------------------------------------------------------------------------
# Runner (`runner.clj`)
# ---------------------------------------------------------------------------

WORKLOADS = {
    "register": register_workload,
    "bank": bank_workload,
    "bank-index": bank_index_workload,
    "g2": g2_workload,
    "set": set_workload,
    "pages": pages_workload,
    "monotonic": monotonic_workload,
    "multimonotonic": multimonotonic_workload,
    "internal": internal_workload,
}

WORKLOAD_OPTIONS = {
    "set": {"serialized-indices": [True, False],
            "strong-read": [True, False]},
    "bank": {"fixed-instances": [True, False],
             "at-query": [True, False]},
    "bank-index": {"fixed-instances": [True, False],
                   "serialized-indices": [True, False]},
    "g2": {"serialized-indices": [True, False]},
    "internal": {"serialized-indices": [True, False]},
    "monotonic": {"at-query-jitter": [0, 10000, 100000]},
    "multimonotonic": {},
    "pages": {"serialized-indices": [True, False]},
    "register": {},
}

WORKLOAD_OPTIONS_EXPECTED_TO_PASS = {
    **WORKLOAD_OPTIONS,
    "set": {"serialized-indices": [True], "strong-read": [True]},
    "g2": {"serialized-indices": [True]},
}


def all_combos(opts: dict) -> list[dict]:
    """Combinatorial expansion of option values (`runner.clj:67-79`)."""
    out = [{}]
    for k, vs in opts.items():
        out = [{**m, k: v} for m in out for v in vs]
    return out


def all_workload_options(workload_options: dict) -> list[dict]:
    return [{"workload": w, **combo}
            for w, opts in workload_options.items()
            for combo in all_combos(opts)]


ALL_NEMESES = [
    {},
    {"kill": True},
    {"stop": True},
    {"clock-skew": True},
    {"inter-replica-partition": True, "intra-replica-partition": True,
     "single-node-partition": True},
    {"inter-replica-partition": True, "intra-replica-partition": True,
     "single-node-partition": True, "clock-skew": True, "kill": True,
     "stop": True},
    {"topology": True},
]


def faunadb_test(opts: dict) -> dict:
    """Build the full test map (`runner.clj:126-220`)."""
    from .. import testkit

    workload_name = opts.get("workload", "register")
    time_limit = opts.get("time-limit", opts.get("time_limit", 60))
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    opts = {**opts, "nodes": nodes}
    w = WORKLOADS[workload_name](opts)

    nem_opts = {f: True for f in (opts.get("nemesis") or [])}
    nem_opts["interval"] = opts.get("nemesis-interval", 10)
    pkg = fauna_nemesis_package(nem_opts)

    rate = float(opts.get("rate", 10))
    client_gen = gen.clients(gen.stagger(1 / rate, w["generator"]))
    main_gen = gen.time_limit(
        time_limit,
        gen.any(client_gen, gen.nemesis(pkg["generator"]))
        if pkg["generator"] is not None else client_gen)
    phases = [main_gen]
    if pkg["final-generator"] is not None:
        phases.append(gen.nemesis(pkg["final-generator"]))
    if w.get("final-generator") is not None:
        phases.append(gen.clients(w["final-generator"]))

    name = " ".join(
        ["fauna", workload_name]
        + [k for k in ("strong-read", "at-query", "fixed-instances")
           if opts.get(k)]
        + (["serialized"] if opts.get("serialized-indices") else []))
    test = {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": name,
        "os": debian.os,
        "db": FaunaDB(FaunaAuto(opts.get("version", "2.5.5"))),
        "replicas": opts.get("replicas", 1),
        "client": w["client"],
        "nemesis": pkg["nemesis"],
        "plot": {"nemeses": pkg.get("perf")},
        "generator": gen.phases(*phases) if len(phases) > 1 else main_gen,
        "checker": checker.compose({
            "perf": checker.perf_checker(),
            "workload": w["checker"],
            "stats": checker.stats(),
            "exceptions": checker.unhandled_exceptions(),
        }),
    }
    test["topology"] = {"value": initial_topology(test)}
    return test


OPT_SPEC = [
    cli.opt("--workload", "-w", default="register",
            choices=sorted(WORKLOADS), help="Which workload to run"),
    cli.opt("--rate", type=float, default=10,
            help="approximate op rate per second"),
    cli.opt("--nemesis", action="append",
            choices=sorted(NEMESIS_SPECS), help="fault types (repeatable)"),
    cli.opt("--nemesis-interval", type=float, default=10,
            help="seconds between nemesis operations"),
    cli.opt("--replicas", type=int, default=1,
            help="number of FaunaDB replicas (datacenters)"),
    cli.opt("--version", default="2.5.5", help="FaunaDB version"),
    cli.opt("--serialized-indices", action="store_true",
            help="make indexes serialized"),
    cli.opt("--strong-read", action="store_true",
            help="set workload: force strict-serializable reads"),
    cli.opt("--fixed-instances", action="store_true",
            help="bank: write zero balances instead of deleting"),
    cli.opt("--at-query", action="store_true",
            help="bank: read through temporal at-queries"),
]


def _all_tests(opts):
    """The full sweep: every workload-option combination expected to
    pass, crossed with every nemesis set (`runner.clj:215-231`
    all-tests over workload-options-expected-to-pass x all-nemeses)."""
    for nem in ALL_NEMESES:
        for combo in all_workload_options(
                WORKLOAD_OPTIONS_EXPECTED_TO_PASS):
            yield faunadb_test({**opts, **combo,
                                "nemesis": sorted(nem)})


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": faunadb_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.test_all_cmd({"tests_fn": _all_tests,
                                 "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
