"""Minimal pure-Python MySQL client protocol (for TiDB).

The reference's tidb suite talks to TiDB over JDBC/MySQL
(`tidb/src/tidb/sql.clj:1-60`). No MySQL driver ships in this
environment, so — like the zookeeper suite's jute client
(`zk_proto.py`) — this implements just the slice of the wire protocol
the suite needs: protocol-41 handshake with mysql_native_password,
COM_QUERY with text result sets, OK/ERR/EOF packets.

Values travel as text (the text protocol); rows come back as lists of
str-or-None. Errors raise MySQLError(code, message).
"""

from __future__ import annotations

import hashlib
import socket

from .netutil import nodelay
import struct

CLIENT_PROTOCOL_41 = 0x0200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x0008_0000
CLIENT_CONNECT_WITH_DB = 0x0008

COM_QUIT = 0x01
COM_QUERY = 0x03
COM_PING = 0x0E


class MySQLError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"({code}) {message}")
        self.code = code
        self.message = message


def _scramble(password: str, salt: bytes) -> bytes:
    """mysql_native_password: SHA1(p) XOR SHA1(salt + SHA1(SHA1(p)))."""
    if not password:
        return b""
    p1 = hashlib.sha1(password.encode()).digest()
    p2 = hashlib.sha1(p1).digest()
    mix = hashlib.sha1(salt + p2).digest()
    return bytes(a ^ b for a, b in zip(p1, mix))


def _lenenc_int(b: bytes, off: int) -> tuple[int, int]:
    first = b[off]
    if first < 0xFB:
        return first, off + 1
    if first == 0xFC:
        return struct.unpack_from("<H", b, off + 1)[0], off + 3
    if first == 0xFD:
        return int.from_bytes(b[off + 1:off + 4], "little"), off + 4
    if first == 0xFE:
        return struct.unpack_from("<Q", b, off + 1)[0], off + 9
    raise MySQLError(-1, f"bad length-encoded integer 0x{first:x}")


def _lenenc_str(b: bytes, off: int) -> tuple[bytes | None, int]:
    if b[off] == 0xFB:  # NULL
        return None, off + 1
    n, off = _lenenc_int(b, off)
    return b[off:off + n], off + n


class Conn:
    """One MySQL connection. query() returns (rows, column_names) for
    result sets or (affected_rows, None) for OK responses."""

    def __init__(self, host: str, port: int = 4000, user: str = "root",
                 password: str = "", database: str = "",
                 timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout_s)
        nodelay(self.sock)
        self.seq = 0
        self._handshake(user, password, database)

    # -- packet framing ----------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise MySQLError(-1, "connection closed by server")
            buf += chunk
        return buf

    def _read_packet(self) -> bytes:
        head = self._read_exact(4)
        n = int.from_bytes(head[:3], "little")
        self.seq = (head[3] + 1) % 256
        return self._read_exact(n)

    def _send_packet(self, payload: bytes) -> None:
        head = len(payload).to_bytes(3, "little") + bytes([self.seq])
        self.sock.sendall(head + payload)
        self.seq = (self.seq + 1) % 256

    # -- handshake ---------------------------------------------------------

    def _handshake(self, user: str, password: str, database: str) -> None:
        greet = self._read_packet()
        if greet and greet[0] == 0xFF:
            raise self._err(greet)
        if greet[0] != 10:
            raise MySQLError(-1, f"unsupported protocol {greet[0]}")
        off = 1
        end = greet.index(0, off)
        off = end + 1          # server version
        off += 4               # thread id
        salt = greet[off:off + 8]
        off += 8 + 1           # auth data part 1 + filler
        off += 2 + 1 + 2 + 2   # caps low, charset, status, caps high
        if len(greet) > off:
            off += 1 + 10      # auth data len + reserved
            rest = greet[off:]
            salt2 = rest.split(b"\0", 1)[0] if rest else b""
            salt = (salt + salt2)[:20]
        caps = (CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS |
                CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH)
        if database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = _scramble(password, salt)
        payload = struct.pack("<IIB23x", caps, 1 << 24, 33)
        payload += user.encode() + b"\0"
        payload += bytes([len(auth)]) + auth
        if database:
            payload += database.encode() + b"\0"
        payload += b"mysql_native_password\0"
        self._send_packet(payload)
        resp = self._read_packet()
        if resp and resp[0] == 0xFF:
            raise self._err(resp)
        # 0x00 OK; 0xFE auth-switch unsupported (TiDB doesn't send it
        # for mysql_native_password)
        if resp and resp[0] == 0xFE:
            raise MySQLError(-1, "auth method switch not supported")

    @staticmethod
    def _err(pkt: bytes) -> MySQLError:
        code = struct.unpack_from("<H", pkt, 1)[0]
        msg = pkt[3:].decode("utf-8", "replace")
        if msg.startswith("#"):
            msg = msg[6:]  # strip sql-state marker
        return MySQLError(code, msg)

    # -- queries -----------------------------------------------------------

    def query(self, sql: str) -> tuple:
        """Run one statement. Returns (rows, columns) for result sets —
        rows are lists of str|None — or (affected_rows, None) for DML."""
        self.seq = 0
        self._send_packet(bytes([COM_QUERY]) + sql.encode())
        pkt = self._read_packet()
        if pkt[0] == 0xFF:
            raise self._err(pkt)
        if pkt[0] == 0x00:  # OK
            affected, _ = _lenenc_int(pkt, 1)
            return affected, None
        ncols, _ = _lenenc_int(pkt, 0)
        cols = []
        for _ in range(ncols):
            cdef = self._read_packet()
            # column def41: catalog, schema, table, org_table, name, ...
            off = 0
            parts = []
            for _f in range(5):
                s, off = _lenenc_str(cdef, off)
                parts.append(s)
            cols.append((parts[4] or b"").decode())
        pkt = self._read_packet()
        if pkt[0] != 0xFE:  # EOF after column definitions
            raise MySQLError(-1, "expected EOF after column definitions")
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:  # EOF
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            off = 0
            row = []
            for _ in range(ncols):
                s, off = _lenenc_str(pkt, off)
                row.append(None if s is None else s.decode())
            rows.append(row)
        return rows, cols

    def ping(self) -> bool:
        self.seq = 0
        self._send_packet(bytes([COM_PING]))
        return self._read_packet()[0] == 0x00

    def close(self) -> None:
        try:
            self.seq = 0
            self._send_packet(bytes([COM_QUIT]))
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
