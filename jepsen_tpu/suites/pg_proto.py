"""Minimal pure-Python PostgreSQL v3 wire client (for CockroachDB).

The reference's cockroachdb suite talks Postgres-protocol JDBC
(`cockroachdb/src/jepsen/cockroach/client.clj:1-60`). This implements
the slice the suite needs against an insecure (trust-auth) CockroachDB:
startup, simple Query, text result sets, transaction status tracking.

Rows come back as lists of str-or-None. Errors raise
PGError(code, message) carrying the SQLSTATE (e.g. '40001' for
serialization conflicts, which CockroachDB asks clients to retry).
"""

from __future__ import annotations

import socket

from .netutil import nodelay
import struct


class PGError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message


class Conn:
    """One Postgres connection in simple-query mode.

    txn_status after each query is 'I' (idle), 'T' (in transaction), or
    'E' (in failed transaction) — from ReadyForQuery."""

    def __init__(self, host: str, port: int = 26257, user: str = "root",
                 database: str = "", timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout_s)
        nodelay(self.sock)
        self.txn_status = "I"
        params = ["user", user]
        if database:
            params += ["database", database]
        body = struct.pack("!I", 196608)  # protocol 3.0
        for p in params:
            body += p.encode() + b"\0"
        body += b"\0"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self._until_ready(startup=True)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise PGError("08006", "connection closed by server")
            buf += chunk
        return buf

    def _read_msg(self) -> tuple[bytes, bytes]:
        head = self._read_exact(5)
        typ = head[:1]
        n = struct.unpack("!I", head[1:])[0] - 4
        return typ, self._read_exact(n)

    @staticmethod
    def _error(body: bytes) -> PGError:
        code, msg = "XX000", ""
        for field in body.split(b"\0"):
            if not field:
                continue
            if field[0:1] == b"C":
                code = field[1:].decode()
            elif field[0:1] == b"M":
                msg = field[1:].decode("utf-8", "replace")
        return PGError(code, msg)

    def _until_ready(self, startup: bool = False):
        """Consume messages until ReadyForQuery; returns (rows, cols,
        complete_tags, error)."""
        rows: list = []
        cols: list = []
        tags: list = []
        err: PGError | None = None
        while True:
            typ, body = self._read_msg()
            if typ == b"R":
                auth = struct.unpack("!I", body[:4])[0]
                if auth != 0:
                    raise PGError("28000",
                                  f"unsupported auth method {auth}")
            elif typ in (b"S", b"K", b"N"):  # params, key data, notices
                pass
            elif typ == b"T":
                n = struct.unpack("!H", body[:2])[0]
                off = 2
                cols = []
                for _ in range(n):
                    end = body.index(0, off)
                    cols.append(body[off:end].decode())
                    off = end + 1 + 18  # per-column fixed fields
            elif typ == b"D":
                n = struct.unpack("!H", body[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack_from("!i", body, off)[0]
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif typ == b"C":
                tags.append(body.rstrip(b"\0").decode())
            elif typ == b"E":
                err = err or self._error(body)
            elif typ == b"Z":
                self.txn_status = body[:1].decode()
                return rows, cols, tags, err
            elif typ == b"I":  # EmptyQueryResponse
                pass
            else:
                pass  # ignore unknown message types
            if startup and typ == b"E":
                raise self._error(body)

    def query(self, sql: str) -> tuple:
        """Run one simple query. Returns (rows, columns) for result
        sets, (affected, None) otherwise. Raises PGError on error."""
        body = sql.encode() + b"\0"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        rows, cols, tags, err = self._until_ready()
        if err is not None:
            raise err
        if cols:
            return rows, cols
        affected = 0
        for t in tags:
            parts = t.split()
            if parts and parts[-1].isdigit():
                affected += int(parts[-1])
        return affected, None

    def close(self) -> None:
        try:
            self.sock.sendall(b"X" + struct.pack("!I", 4))
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
