"""Shared socket plumbing for the hand-rolled wire clients."""

from __future__ import annotations

import socket


def nodelay(sock: socket.socket) -> socket.socket:
    """Disable Nagle: every protocol here is strict request/response,
    where Nagle + delayed ACK otherwise cost ~40ms per round trip (the
    reference's JDBC/DataStax drivers set this themselves)."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
