"""Minimal pure-Python BSON + MongoDB OP_MSG wire client.

The reference's mongodb suites talk to mongod through the monger/Java
driver (`mongodb-rocks/src/jepsen/mongodb_rocks.clj:15-27`). This
implements the slice needed to drive a replica set: the BSON scalar/
document/array types the commands use, OP_MSG framing (opcode 2013,
kind-0 body section), and a `Conn.command(db, doc)` request/reply
call. Commands raise MongoError on {'ok': 0} replies.
"""

from __future__ import annotations

import socket

from .netutil import nodelay
import struct
import threading

OP_MSG = 2013


class MongoError(Exception):
    def __init__(self, code, message):
        super().__init__(f"({code}) {message}")
        self.code = code
        self.message = message


class WriteConcernError(MongoError):
    """The write applied locally but the requested write concern was
    not satisfied — durability is unknown, so callers must record the
    op as :info (indeterminate), never :fail."""


# -- BSON --------------------------------------------------------------------

def _encode_value(name: bytes, v) -> bytes:
    if v is None:
        return b"\x0a" + name + b"\0"
    if isinstance(v, bool):
        return b"\x08" + name + b"\0" + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + name + b"\0" + struct.pack("<i", v)
        return b"\x12" + name + b"\0" + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + name + b"\0" + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + name + b"\0" + \
            struct.pack("<i", len(b) + 1) + b + b"\0"
    if isinstance(v, dict):
        return b"\x03" + name + b"\0" + encode_doc(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + name + b"\0" + encode_doc(
            {str(i): x for i, x in enumerate(v)})
    raise TypeError(f"cannot BSON-encode {type(v)}")


def encode_doc(doc: dict) -> bytes:
    body = b"".join(_encode_value(str(k).encode(), v)
                    for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\0"


def _decode_value(t: int, b: bytes, off: int):
    if t == 0x0A:
        return None, off
    if t == 0x08:
        return b[off] == 1, off + 1
    if t == 0x10:
        return struct.unpack_from("<i", b, off)[0], off + 4
    if t == 0x12:
        return struct.unpack_from("<q", b, off)[0], off + 8
    if t == 0x01:
        return struct.unpack_from("<d", b, off)[0], off + 8
    if t == 0x02:
        n = struct.unpack_from("<i", b, off)[0]
        return b[off + 4:off + 4 + n - 1].decode(), off + 4 + n
    if t == 0x03:
        n = struct.unpack_from("<i", b, off)[0]
        return decode_doc(b[off:off + n]), off + n
    if t == 0x04:
        n = struct.unpack_from("<i", b, off)[0]
        d = decode_doc(b[off:off + n])
        return [d[k] for k in sorted(d, key=int)], off + n
    if t == 0x11:  # timestamp
        return struct.unpack_from("<q", b, off)[0], off + 8
    if t == 0x07:  # objectid: pass through as hex
        return b[off:off + 12].hex(), off + 12
    if t == 0x09:  # UTC datetime
        return struct.unpack_from("<q", b, off)[0], off + 8
    raise MongoError(-1, f"cannot BSON-decode type 0x{t:02x}")


def decode_doc(b: bytes) -> dict:
    out: dict = {}
    off = 4
    while b[off] != 0:
        t = b[off]
        off += 1
        end = b.index(0, off)
        name = b[off:end].decode()
        off = end + 1
        out[name], off = _decode_value(t, b, off)
    return out


# -- OP_MSG ------------------------------------------------------------------

class Conn:
    """One mongod connection in OP_MSG mode."""

    def __init__(self, host: str, port: int = 27017,
                 timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout_s)
        nodelay(self.sock)
        self.req_id = 0
        self.lock = threading.Lock()

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise MongoError(-1, "connection closed by server")
            buf += chunk
        return buf

    def command(self, db: str, cmd: dict) -> dict:
        """Run one command; returns the reply doc, raising MongoError
        on ok == 0."""
        doc = dict(cmd)
        doc["$db"] = db
        body = struct.pack("<I", 0) + b"\x00" + encode_doc(doc)
        with self.lock:
            self.req_id += 1
            header = struct.pack("<iiii", 16 + len(body), self.req_id,
                                 0, OP_MSG)
            self.sock.sendall(header + body)
            raw = self._read_exact(16)
            length, _rid, _rto, opcode = struct.unpack("<iiii", raw)
            payload = self._read_exact(length - 16)
        if opcode != OP_MSG:
            raise MongoError(-1, f"unexpected opcode {opcode}")
        # flagBits(4) + kind byte + doc
        reply = decode_doc(payload[5:])
        if not reply.get("ok"):
            raise MongoError(reply.get("code", -1),
                             reply.get("errmsg", "command failed"))
        # MongoDB reports per-document write failures and unsatisfied
        # write concern on ok:1 replies — surface them, or callers
        # would record failed / non-majority-durable writes as :ok.
        if reply.get("writeErrors"):
            we = reply["writeErrors"][0]
            raise MongoError(we.get("code", -1),
                             we.get("errmsg", "write error"))
        if reply.get("writeConcernError"):
            wce = reply["writeConcernError"]
            raise WriteConcernError(wce.get("code", -1),
                                    wce.get("errmsg",
                                            "write concern error"))
        return reply

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
