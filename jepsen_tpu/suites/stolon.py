"""Stolon test suite — PostgreSQL HA under a cloud-native failover
manager.

Mirrors the reference's stolon suite
(`/root/reference/stolon/src/jepsen/stolon{,/db,/client,/append,
/ledger}.clj`): postgres + stolon sentinel/keeper/proxy daemons backed
by an etcd store (`db.clj:22-120`), with the elle list-append workload
(`append.clj` — CONCAT-based list rows over the proxy) and a
ledger/bank workload (`ledger.clj`).

Clients reuse the Postgres wire client (`pg_proto.py`); hermetic tests
run against the in-process Postgres-protocol fake."""

from __future__ import annotations

import logging

from .. import cli, client as jclient, control
from .. import db as jdb
from ..control import util as cu
from ..os_ import debian
from ..workloads import append as append_w, bank as bank_w
from . import std_opts, std_test
from .pg_proto import Conn, PGError

log = logging.getLogger(__name__)

DIR = "/opt/stolon"
DATA_DIR = f"{DIR}/data"
CLUSTER = "jepsen-cluster"
PROXY_PORT = 25432
PG_PORT = 5432
ETCD_ENDPOINT_PORT = 2379

SENTINEL = ("stolon-sentinel", f"{DIR}/sentinel.log",
            f"{DIR}/sentinel.pid")
KEEPER = ("stolon-keeper", f"{DIR}/keeper.log", f"{DIR}/keeper.pid")
PROXY = ("stolon-proxy", f"{DIR}/proxy.log", f"{DIR}/proxy.pid")

DEFAULT_VERSION = "0.16.0"

# 40003 (completion unknown) deliberately absent: ambiguous commits
# must stay :info, not :fail (the txn may have applied).
DEFINITE_ABORT = {"40001", "40P01"}


def tarball_url(version: str) -> str:
    return (f"https://github.com/sorintlab/stolon/releases/download/"
            f"v{version}/stolon-v{version}-linux-amd64.tar.gz")


def store_endpoints(test: dict) -> str:
    return ",".join(f"http://{n}:{ETCD_ENDPOINT_PORT}"
                    for n in test["nodes"])


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """postgres packages + the stolon daemon trio on every node
    (`db.clj:40-180`). Assumes an etcd store is reachable on the test
    nodes (the reference composes `jepsen.etcd.db` the same way)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing stolon %s", node, self.version)
            debian.install(["postgresql", "postgresql-client"])
            control.exec_("service", "postgresql", "stop")
            url = test.get("tarball") or tarball_url(self.version)
            cu.install_archive(url, DIR)
            control.exec_("mkdir", "-p", DATA_DIR)
            control.exec_("chown", "-R", "postgres:postgres", DIR)
            if node == test["nodes"][0]:
                control.exec_(
                    f"{DIR}/bin/stolonctl", "init", "-y",
                    "--cluster-name", CLUSTER,
                    "--store-backend", "etcdv3",
                    "--store-endpoints", store_endpoints(test))
            self.start(test, node)

    def start(self, test, node):
        store = ["--cluster-name", CLUSTER, "--store-backend", "etcdv3",
                 "--store-endpoints", store_endpoints(test)]
        with control.su():
            for (bin_, logf, pidf), args in (
                (SENTINEL, []),
                (KEEPER, ["--uid", f"keeper_{node.replace('-', '_')}",
                          "--data-dir", DATA_DIR,
                          "--pg-listen-address", node,
                          "--pg-port", str(PG_PORT),
                          "--pg-su-password", "jepsen",
                          "--pg-repl-username", "repl",
                          "--pg-repl-password", "jepsen"]),
                (PROXY, ["--listen-address", "0.0.0.0",
                         "--port", str(PROXY_PORT)]),
            ):
                cu.start_daemon(
                    {"logfile": logf, "pidfile": pidf, "chdir": DIR},
                    f"{DIR}/bin/{bin_}", *store, *args)

    def kill(self, test, node):
        with control.su():
            for bin_, _logf, pidf in (PROXY, KEEPER, SENTINEL):
                cu.stop_daemon(pidf, cmd=bin_)
                cu.grepkill(bin_)
            cu.grepkill("postgres")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", DATA_DIR,
                          *(x[1] for x in (SENTINEL, KEEPER, PROXY)))

    def log_files(self, test, node):
        return [x[1] for x in (SENTINEL, KEEPER, PROXY)]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


def _connect(test, node) -> Conn:
    fn = test.get("sql-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, PROXY_PORT, user="postgres", database="jepsen")


class _SQLClient(jclient.Client):
    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _capture(self, op, e: Exception, read_only: bool) -> dict:
        if isinstance(e, PGError):
            if e.code in DEFINITE_ABORT or read_only:
                return {**op, "type": "fail",
                        "error": ["sql", e.code, e.message]}
            return {**op, "type": "info",
                    "error": ["sql", e.code, e.message]}
        return {**op, "type": "fail" if read_only else "info",
                "error": ["conn", str(e)]}

    def _txn(self, stmts_fn, op, read_only=False):
        conn = self.conn
        try:
            conn.query("begin")
            out = stmts_fn(conn)
            conn.query("commit")
            return {**op, "type": "ok", **out}
        except Exception as e:  # noqa: BLE001 — classified below
            try:
                conn.query("rollback")
            except Exception:  # noqa: BLE001 — conn may be dead
                pass
            if isinstance(e, (PGError, OSError, ConnectionError)):
                return self._capture(op, e, read_only)
            raise


class AppendClient(_SQLClient):
    """Elle list-append micro-ops over one table, appends via
    ON CONFLICT + concat (`append.clj:40-90`)."""

    def setup(self, test):
        self.conn.query("create table if not exists lists "
                        "(id int primary key, val text)")

    def _mop(self, conn, m):
        f, k, v = m[0], m[1], m[2]
        if f == "r":
            rows, _ = conn.query(f"select val from lists where id = {k}")
            if not rows or rows[0][0] is None:
                return ["r", k, []]
            return ["r", k,
                    [int(x) for x in rows[0][0].split(",") if x != ""]]
        conn.query(f"insert into lists (id, val) values ({k}, '{v}') "
                   f"on conflict (id) do update set val = "
                   f"concat(val, ',', '{v}')")
        return ["append", k, v]

    def invoke(self, test, op):
        txn = op["value"]

        def body(conn):
            return {"value": [self._mop(conn, m) for m in txn]}
        return self._txn(body, op,
                         read_only=all(m[0] == "r" for m in txn))


class BankClient(_SQLClient):
    """Ledger-style transfers (`ledger.clj`)."""

    def setup(self, test):
        self.conn.query("create table if not exists accounts "
                        "(id int primary key, balance bigint)")
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            self.conn.query(
                f"insert into accounts (id, balance) values "
                f"({a}, {total if a == accounts[0] else 0}) "
                f"on conflict (id) do update set balance = balance")

    def invoke(self, test, op):
        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query("select id, balance from accounts")
                return {"value": {int(r[0]): int(r[1]) for r in rows}}
            return self._txn(read_body, op, read_only=True)

        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]

        def transfer_body(conn):
            rows, _ = conn.query(
                f"select balance from accounts where id = {frm}")
            b1 = int(rows[0][0]) - amount
            rows, _ = conn.query(
                f"select balance from accounts where id = {to}")
            b2 = int(rows[0][0]) + amount
            if b1 < 0:
                raise _InsufficientFunds()
            conn.query(f"update accounts set balance = {b1} "
                       f"where id = {frm}")
            conn.query(f"update accounts set balance = {b2} "
                       f"where id = {to}")
            return {}

        try:
            return self._txn(transfer_body, op)
        except _InsufficientFunds:
            return {**op, "type": "fail", "error": "negative"}


class _InsufficientFunds(Exception):
    pass


def append_workload(opts: dict) -> dict:
    w = append_w.workload(opts)
    w["client"] = AppendClient()
    return w


def bank_workload(opts: dict) -> dict:
    w = bank_w.test(opts)
    w["client"] = BankClient()
    return w


WORKLOADS = {
    "append": append_workload,
    "bank": bank_workload,
}


def stolon_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "append")
    return std_test(
        opts, name=f"stolon-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "append", DEFAULT_VERSION,
                    "stolon release version")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": stolon_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
