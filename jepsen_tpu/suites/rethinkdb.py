"""RethinkDB test suite — document-level CAS with per-op write/read
concerns.

Mirrors `/root/reference/rethinkdb/src/jepsen/rethinkdb{,/
document_cas}.clj`: apt-repo install with optional faketime wrapper
around the binary, cluster join config, table creation with 5
replicas + write_acks/read_mode reconfiguration, and the document-cas
workload — reads via `get(field).default(nil)`, writes via insert
with conflict=update, cas via an update whose row-function branches on
equality and errors to abort (`document_cas.clj:80-106`). Error
classification mirrors `rethinkdb.clj:144-163` (op-indeterminacy by
idempotence; ReQL runtime 'abort' means the cas definitely failed)."""

from __future__ import annotations

import logging

from .. import cli, client as jclient, control, independent
from .. import db as jdb
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test
from . import reql_proto as r
from .reql_proto import Conn, ReQLError

log = logging.getLogger(__name__)

LOG_FILE = "/var/log/rethinkdb"
CONF = "/etc/rethinkdb/instances.d/jepsen.conf"
CLIENT_PORT = 28015
CLUSTER_PORT = 29015

DEFAULT_VERSION = "2.3.5~0jessie"

DB_NAME = "jepsen"
TABLE = "cas"


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """apt install + join config (`rethinkdb.clj:52-96`)."""

    def __init__(self, version: str = DEFAULT_VERSION,
                 faketime: bool = False):
        self.version = version
        self.faketime = faketime

    def setup(self, test, node):
        with control.su():
            debian.add_repo(
                "rethinkdb",
                "deb http://download.rethinkdb.com/apt jessie main")
            control.exec_raw(
                "wget -qO - https://download.rethinkdb.com/apt/"
                "pubkey.gpg | apt-key add -")
        debian.install({"rethinkdb": self.version})
        with control.su():
            if self.faketime:
                # replace the binary with a random-rate faketime
                # wrapper (`rethinkdb.clj:33-50`)
                try:
                    control.exec_("test", "-e",
                                  "/usr/bin/rethinkdb.no-faketime")
                except RemoteError:
                    control.exec_("mv", "/usr/bin/rethinkdb",
                                  "/usr/bin/rethinkdb.no-faketime")
                    cu.write_file(
                        "#!/bin/bash\n"
                        'faketime -m -f "+$((RANDOM%100))s '
                        'x1.${RANDOM}" /usr/bin/rethinkdb.no-faketime'
                        ' "$@"\n', "/usr/bin/rethinkdb")
                    control.exec_("chmod", "a+x", "/usr/bin/rethinkdb")
            joins = "\n".join(f"join={n}:{CLUSTER_PORT}"
                              for n in test["nodes"])
            cu.write_file(
                f"{joins}\n\nserver-name={node}\nserver-tag={node}\n"
                f"bind=all\n", CONF)
            control.exec_("touch", LOG_FILE)
            control.exec_("chown", "rethinkdb:rethinkdb", LOG_FILE)
            self.start(test, node)
            cu.await_tcp_port(CLIENT_PORT)

    def start(self, test, node):
        with control.su():
            control.exec_("service", "rethinkdb", "start")

    def kill(self, test, node):
        with control.su():
            cu.grepkill("rethinkdb")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            try:
                control.exec_("rm", "-rf",
                              "/var/lib/rethinkdb/instances.d")
            except RemoteError:
                pass

    def log_files(self, test, node):
        return [LOG_FILE]


def db(version: str = DEFAULT_VERSION, faketime: bool = False) -> DB:
    return DB(version, faketime)


def _connect(test, node) -> Conn:
    fn = test.get("reql-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, CLIENT_PORT)


class DocumentCASClient(jclient.Client):
    """Register per document id; per-op write_acks/read_mode
    (`document_cas.clj:53-106`)."""

    def __init__(self, write_acks: str = "majority",
                 read_mode: str = "majority"):
        self.write_acks = write_acks
        self.read_mode = read_mode
        self.conn: Conn | None = None

    def open(self, test, node):
        c = DocumentCASClient(self.write_acks, self.read_mode)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def setup(self, test):
        try:
            self.conn.run(r.db_create(DB_NAME))
        except ReQLError:
            pass  # exists
        try:
            self.conn.run(r.table_create(
                DB_NAME, TABLE, replicas=len(test["nodes"])))
        except ReQLError:
            pass  # exists / another worker created it
        try:
            # write-acks + shard layout via the system table, as the
            # reference does (`document_cas.clj:30-40` set-write-acks!)
            self.conn.run(r.update(
                r.table("rethinkdb", "table_config"),
                {"write_acks": self.write_acks,
                 "shards": [{"primary_replica": test["nodes"][0],
                             "replicas": list(test["nodes"])}]}))
        except ReQLError:
            pass  # hermetic fakes have no system tables
        # every client waits for replica readiness, even the ones that
        # lost the creation race (`document_cas.clj:57-67`)
        self.conn.run(r.wait(r.table(DB_NAME, TABLE)))

    def _row(self, k):
        return r.get(r.table(DB_NAME, TABLE,
                             read_mode=self.read_mode), k)

    def invoke(self, test, op):
        k, v = op["value"]
        idempotent = op["f"] == "read"
        try:
            if op["f"] == "read":
                out = self.conn.run(
                    r.default(r.get_field(self._row(k), "val"), None))
                return {**op, "type": "ok",
                        "value": independent.ktuple(k, out)}
            if op["f"] == "write":
                res = self.conn.run(
                    r.insert(r.table(DB_NAME, TABLE),
                             {"id": k, "val": v}, conflict="update"))
                if res.get("errors"):
                    raise ReQLError(-1, res.get("first_error", ""))
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                res = self.conn.run(
                    r.update(self._row(k), r.func(
                        r.branch(
                            r.eq(r.get_field(r.var(1), "val"), old),
                            {"val": new},
                            r.error("abort")))))
                ok = (res.get("errors", 1) == 0
                      and res.get("replaced", 0) == 1)
                return {**op, "type": "ok" if ok else "fail"}
            raise ValueError(f"unknown f {op['f']!r}")
        except ReQLError as e:
            if "abort" in str(e):
                return {**op, "type": "fail", "error": "cas-abort"}
            t = "fail" if idempotent else "info"
            return {**op, "type": t, "error": str(e)}
        except OSError as e:
            t = "fail" if idempotent else "info"
            return {**op, "type": t, "error": str(e)}


def document_cas_workload(opts: dict) -> dict:
    w = linearizable_register_test(opts)
    w["client"] = DocumentCASClient(
        opts.get("write-acks", "majority"),
        opts.get("read-mode", "majority"))
    return w


def linearizable_register_test(opts):
    from ..workloads import linearizable_register
    return dict(linearizable_register.test(opts))


WORKLOADS = {"document-cas": document_cas_workload}


def rethinkdb_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "document-cas")
    return std_test(
        opts, name=f"rethinkdb-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION),
              opts.get("faketime", False)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "document-cas", DEFAULT_VERSION,
                    "rethinkdb apt version") + [
    cli.opt("--write-acks", default="majority",
            choices=["single", "majority"], help="write concern"),
    cli.opt("--read-mode", default="majority",
            choices=["single", "majority", "outdated"],
            help="read concern"),
    cli.opt("--faketime", action="store_true",
            help="wrap the binary in a random-rate faketime"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": rethinkdb_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
