"""CrateDB test suite — dirty reads, lost updates, and version
divergence over the HTTP `_sql` endpoint.

Mirrors `/root/reference/crate/src/jepsen/crate/`:

  * dirty-read (`dirty_read.clj`): writers keep one in-flight insert
    per node while readers chase it; a final strong read per thread
    feeds the set-algebra checker (reads of rows no strong read ever
    saw are dirty; acknowledged writes no strong read saw are lost).
  * lost-updates (`lost_updates.clj`): per-key JSON-array sets updated
    with `_version` preconditions; zero-row updates are definite
    fails.
  * version-divergence (`version_divergence.clj`): every read returns
    (value, _version); the multiversion checker requires each _version
    of a row to name exactly one value.

Where the reference drives Crate's shaded JDBC/PSQL driver, this port
speaks the HTTP `_sql` endpoint ({"stmt": ..., "args": [...]}) —
Crate's own first-class API. Hermetic tests run against
`tests/fake_crate.py`."""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request

from .. import checker, cli, client as jclient, control, independent
from .. import db as jdb
from .. import generator as gen
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test

log = logging.getLogger(__name__)

HTTP_PORT = 4200
DEFAULT_VERSION = "0.54.9"

CRATE_YML = """\
cluster.name: jepsen
node.name: {node}
network.host: 0.0.0.0
discovery.zen.ping.multicast.enabled: false
discovery.zen.ping.unicast.hosts: [{hosts}]
discovery.zen.minimum_master_nodes: {quorum}
"""


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        debian.install_jdk11()
        with control.su():
            url = test.get("tarball") or (
                "https://cdn.crate.io/downloads/releases/"
                f"crate-{self.version}.tar.gz")
            cu.install_archive(url, "/opt/crate")
            hosts = ", ".join(f'"{n}"' for n in test["nodes"])
            cu.write_file(CRATE_YML.format(
                node=node, hosts=hosts,
                quorum=len(test["nodes"]) // 2 + 1),
                "/opt/crate/config/crate.yml")
            cu.start_daemon(
                {"logfile": "/opt/crate/crate.log",
                 "pidfile": "/opt/crate/crate.pid",
                 "chdir": "/opt/crate"},
                "/opt/crate/bin/crate")
            cu.await_tcp_port(HTTP_PORT)

    def start(self, test, node):
        with control.su():
            cu.start_daemon(
                {"logfile": "/opt/crate/crate.log",
                 "pidfile": "/opt/crate/crate.pid",
                 "chdir": "/opt/crate"},
                "/opt/crate/bin/crate")

    def kill(self, test, node):
        with control.su():
            cu.stop_daemon("/opt/crate/crate.pid", cmd="crate")
            cu.grepkill("crate")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            try:
                control.exec_("rm", "-rf", "/opt/crate/data")
            except RemoteError:
                pass

    def log_files(self, test, node):
        return ["/opt/crate/crate.log"]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


class CrateError(Exception):
    def __init__(self, code, message):
        super().__init__(f"crate error {code}: {message}")
        self.code = code


class SQLClient(jclient.Client):
    """_sql endpoint client; rows come back as arrays."""

    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = timeout_s
        self.base: str | None = None

    def open(self, test, node):
        c = type(self)(self.timeout_s)
        fn = test.get("crate-url-fn")
        c.base = fn(node) if fn else f"http://{node}:{HTTP_PORT}"
        c.on_open(test, node)
        return c

    def on_open(self, test, node):
        pass

    def sql(self, stmt: str, *args):
        req = urllib.request.Request(
            self.base + "/_sql",
            data=json.dumps({"stmt": stmt, "args": list(args)}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            body = json.loads(e.read() or b"{}")
            err = body.get("error", {})
            raise CrateError(err.get("code", e.code),
                             err.get("message", "sql error")) from e


# -- dirty read (`dirty_read.clj`) -------------------------------------------

class DirtyReadClient(SQLClient):
    def on_open(self, test, node):
        try:
            self.sql("create table if not exists dirty_read "
                     "(id integer primary key)")
        except CrateError:
            pass

    def invoke(self, test, op):
        try:
            if op["f"] == "write":
                self.sql("insert into dirty_read (id) values (?)",
                         op["value"])
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = self.sql(
                    "select id from dirty_read where id = ?",
                    op["value"])
                found = bool(res.get("rows"))
                return {**op, "type": "ok" if found else "fail"}
            if op["f"] == "strong-read":
                self.sql("refresh table dirty_read")
                res = self.sql("select id from dirty_read")
                return {**op, "type": "ok",
                        "value": sorted(r[0] for r in res["rows"])}
            raise ValueError(f"unknown f {op['f']!r}")
        except (CrateError, OSError) as e:
            t = "fail" if op["f"] != "write" else "info"
            return {**op, "type": t, "error": str(e)}


class DirtyReadChecker(checker.Checker):
    """Set algebra over reads vs per-thread strong reads
    (`dirty_read.clj:143-193`)."""

    def check(self, test, hist, opts):
        writes, reads, strong = set(), set(), []
        for o in hist:
            if o.get("type") != "ok":
                continue
            if o["f"] == "write":
                writes.add(o["value"])
            elif o["f"] == "read":
                reads.add(o["value"])
            elif o["f"] == "strong-read":
                strong.append(set(o["value"]))
        if not strong:
            return {"valid?": "unknown",
                    "error": "no strong reads completed"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        dirty = reads - on_some
        lost = writes - on_some
        return {
            "valid?": (on_all == on_some and not dirty and not lost),
            "nodes-agree?": on_all == on_some,
            "read-count": len(reads),
            "on-all-count": len(on_all),
            "on-some-count": len(on_some),
            "not-on-all": sorted(on_some - on_all)[:32],
            "dirty": sorted(dirty)[:32],
            "lost": sorted(lost)[:32],
            "some-lost": sorted(writes - on_all)[:32],
        }


class RWGen(gen.Gen):
    """The first `w` threads write fresh values, recording the last
    in-flight write per node; the rest read their node's in-flight
    value (`dirty_read.clj:195-226`)."""

    def __init__(self, w: int, state=None):
        self.w = w
        self.state = state or {"write": -1, "in_flight": {}}

    def op(self, test, ctx):
        p = gen.some_free_process(ctx)
        if p is None:
            return gen.PENDING, self
        n_nodes = len(test["nodes"])
        # crashed processes are replaced with higher ids: route by the
        # stable THREAD, as the reference does (`dirty_read.clj:216`)
        thread = gen.process_to_thread(ctx, p)
        thread = thread if isinstance(thread, int) else 0
        node_ix = thread % n_nodes
        if thread < self.w:
            self.state["write"] += 1
            v = self.state["write"]
            self.state["in_flight"][node_ix] = v
            o = {"type": "invoke", "f": "write", "value": v,
                 "process": p, "time": ctx.time}
        else:
            v = self.state["in_flight"].get(node_ix, 0)
            o = {"type": "invoke", "f": "read", "value": v,
                 "process": p, "time": ctx.time}
        return o, RWGen(self.w, self.state)


def dirty_read_workload(opts) -> dict:
    return {
        "client": DirtyReadClient(),
        "generator": RWGen(opts.get("writers", 2)),
        "checker": DirtyReadChecker(),
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "strong-read", "value": None})),
    }


# -- lost updates (`lost_updates.clj`) ---------------------------------------

class LostUpdatesClient(SQLClient):
    def on_open(self, test, node):
        try:
            self.sql("create table if not exists sets "
                     "(id integer primary key, elements string)")
        except CrateError:
            pass

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                res = self.sql(
                    "select elements from sets where id = ?", k)
                rows = res.get("rows")
                els = sorted(json.loads(rows[0][0])) if rows else []
                return {**op, "type": "ok",
                        "value": independent.ktuple(k, els)}
            if op["f"] == "add":
                res = self.sql(
                    "select elements, _version from sets where id = ?",
                    k)
                rows = res.get("rows")
                if rows:
                    els = json.loads(rows[0][0])
                    version = rows[0][1]
                    res = self.sql(
                        "update sets set elements = ? "
                        "where id = ? and _version = ?",
                        json.dumps(els + [v]), k, version)
                    if res.get("rowcount", 0) == 1:
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail",
                            "error": "version-conflict"}
                self.sql("insert into sets (id, elements) "
                         "values (?, ?)", k, json.dumps([v]))
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (CrateError, OSError) as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": str(e)}


def lost_updates_workload(opts) -> dict:
    import itertools

    counters: dict = {}

    def add(test, ctx):
        k = gen.rng.randrange(8)
        c = counters.setdefault(k, itertools.count())
        return {"type": "invoke", "f": "add",
                "value": independent.ktuple(k, next(c))}

    def final(test, ctx):
        return independent.sequential_generator(
            range(8), lambda k: gen.once(
                {"type": "invoke", "f": "read", "value": None}))

    return {
        "client": LostUpdatesClient(),
        "generator": add,
        "checker": independent.checker(checker.set_checker()),
        "final-generator": gen.derefer(final),
    }


# -- version divergence (`version_divergence.clj`) ---------------------------

class VersionDivergenceClient(SQLClient):
    def on_open(self, test, node):
        try:
            self.sql("create table if not exists registers "
                     "(id integer primary key, value integer)")
        except CrateError:
            pass

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                res = self.sql(
                    "select value, _version from registers "
                    "where id = 0")
                rows = res.get("rows")
                if not rows:
                    return {**op, "type": "ok", "value": None}
                return {**op, "type": "ok",
                        "value": [rows[0][0], rows[0][1]]}
            if op["f"] == "write":
                res = self.sql(
                    "update registers set value = ? where id = ?",
                    op["value"], 0)
                if res.get("rowcount", 0) == 0:
                    self.sql("insert into registers (id, value) "
                             "values (?, ?)", 0, op["value"])
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (CrateError, OSError) as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": str(e)}


class MultiVersionChecker(checker.Checker):
    """Each _version of the row must name exactly one value
    (`version_divergence.clj:94-108`)."""

    def check(self, test, hist, opts):
        by_version: dict = {}
        for o in hist:
            if o.get("type") == "ok" and o.get("f") == "read" \
                    and o.get("value"):
                value, version = o["value"]
                by_version.setdefault(version, set()).add(value)
        divergent = {v: sorted(vals) for v, vals in by_version.items()
                     if len(vals) > 1}
        return {"valid?": not divergent,
                "versions-read": len(by_version),
                "divergent": divergent}


def version_divergence_workload(opts) -> dict:
    import itertools

    values = itertools.count()

    def w(test, ctx):
        return {"type": "invoke", "f": "write", "value": next(values)}

    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    return {
        "client": VersionDivergenceClient(),
        "generator": gen.mix([w, r, r]),
        "checker": MultiVersionChecker(),
    }


WORKLOADS = {
    "dirty-read": dirty_read_workload,
    "lost-updates": lost_updates_workload,
    "version-divergence": version_divergence_workload,
}


def crate_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "lost-updates")
    return std_test(
        opts, name=f"crate-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "lost-updates", DEFAULT_VERSION,
                    "CrateDB tarball version") + [
    cli.opt("--writers", type=int, default=2,
            help="writer threads for the dirty-read workload"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": crate_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
