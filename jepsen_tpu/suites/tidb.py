"""TiDB test suite.

Mirrors the reference's tidb suite (`/root/reference/tidb/src/tidb/`):
pd/tikv/tidb cluster automation (`db.clj`), a MySQL-protocol SQL layer
with the reference's error classification and retry semantics
(`sql.clj`), and the workload menu that matters for the north-star
configs — elle list-append (`txn.clj`, BASELINE config 5 at 100k txns),
rw-register, bank (`bank.clj`), independent linearizable register
(`register.clj`), grow-only set (`sets.clj`), long-fork
(`long_fork.clj`), and the additional-graphs consumers: monotonic
(`monotonic.clj`), sequential (`sequential.clj`), and table
(`table.clj`).

Clients speak the wire protocol directly (`mysql_proto.py`) — no driver
dependency; hermetic tests run against an in-process MySQL-protocol
fake (tests/fake_mysql.py) exactly like the reference's dummy tier.
"""

from __future__ import annotations

import itertools
import logging

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from ..control import util as cu
from ..workloads import append as append_w, bank as bank_w, \
    linearizable_register, long_fork as long_fork_w, \
    monotonic as monotonic_w, sequential as sequential_w, \
    table as table_w, wr as wr_w
from . import std_opts, std_test
from .mysql_proto import Conn, MySQLError

log = logging.getLogger(__name__)

DIR = "/opt/tidb"
BIN = f"{DIR}/bin"
PD_LOG, KV_LOG, DB_LOG = (f"{DIR}/pd.log", f"{DIR}/kv.log", f"{DIR}/db.log")
PD_PID, KV_PID, DB_PID = (f"{DIR}/pd.pid", f"{DIR}/kv.pid", f"{DIR}/db.pid")
PD_DATA, KV_DATA = f"{DIR}/data/pd", f"{DIR}/data/kv"

CLIENT_PORT = 2379   # pd client (db.clj:45)
PEER_PORT = 2380     # pd peer (db.clj:46)
SQL_PORT = 4000      # tidb-server MySQL port
KV_PORT = 20160

DEFAULT_VERSION = "v3.0.0"

# TiDB/TiKV error codes that mean the transaction definitely rolled
# back — safe to call :fail (`sql.clj` rollback classification):
# deadlock, lock-wait timeout, TiKV busy/conflict/region errors.
DEFINITE_ABORT = {1205, 1213, 8002, 8022, 8028, 9004, 9005, 9007}


def tarball_url(version: str) -> str:
    return (f"https://download.pingcap.org/tidb-{version}"
            f"-linux-amd64.tar.gz")


def peer_url(node: str) -> str:
    return f"http://{node}:{PEER_PORT}"


def client_url(node: str) -> str:
    return f"http://{node}:{CLIENT_PORT}"


def initial_cluster(test: dict) -> str:
    """pd1=http://n1:2380,... (`db.clj:72-79`)."""
    return ",".join(f"pd{i + 1}={peer_url(n)}"
                    for i, n in enumerate(test["nodes"]))


def pd_endpoints(test: dict) -> str:
    return ",".join(f"{n}:{CLIENT_PORT}" for n in test["nodes"])


class DB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    """pd + tikv + tidb on every node (`db.clj:102-240`): install the
    release tarball, then start pd (all nodes), tikv against the pd
    quorum, and tidb-server last."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing TiDB %s", node, self.version)
            url = test.get("tarball") or tarball_url(self.version)
            cu.install_archive(url, DIR)
            control.exec_("mkdir", "-p", PD_DATA, KV_DATA)
            self.start(test, node)

    def start(self, test, node):
        i = test["nodes"].index(node) + 1
        with control.su():
            cu.start_daemon(
                {"logfile": PD_LOG, "pidfile": PD_PID, "chdir": DIR},
                f"{BIN}/pd-server",
                "--name", f"pd{i}",
                "--data-dir", PD_DATA,
                "--client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
                "--advertise-client-urls", client_url(node),
                "--peer-urls", f"http://0.0.0.0:{PEER_PORT}",
                "--advertise-peer-urls", peer_url(node),
                "--initial-cluster", initial_cluster(test))
            cu.await_tcp_port(CLIENT_PORT)
            cu.start_daemon(
                {"logfile": KV_LOG, "pidfile": KV_PID, "chdir": DIR},
                f"{BIN}/tikv-server",
                "--pd", pd_endpoints(test),
                "--addr", f"0.0.0.0:{KV_PORT}",
                "--advertise-addr", f"{node}:{KV_PORT}",
                "--data-dir", KV_DATA)
            cu.await_tcp_port(KV_PORT)
            cu.start_daemon(
                {"logfile": DB_LOG, "pidfile": DB_PID, "chdir": DIR},
                f"{BIN}/tidb-server",
                "--store", "tikv",
                "--path", pd_endpoints(test),
                "-P", str(SQL_PORT))
            cu.await_tcp_port(SQL_PORT)

    def teardown(self, test, node):
        log.info("%s tearing down TiDB", node)
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", f"{DIR}/data", PD_LOG, KV_LOG,
                          DB_LOG)

    def kill(self, test, node):
        with control.su():
            for pid, name in ((DB_PID, "tidb-server"),
                              (KV_PID, "tikv-server"),
                              (PD_PID, "pd-server")):
                cu.stop_daemon(pid, cmd=name)
                cu.grepkill(name)

    def pause(self, test, node):
        with control.su():
            for name in ("tidb-server", "tikv-server", "pd-server"):
                cu.signal(name, "STOP")

    def resume(self, test, node):
        with control.su():
            for name in ("tidb-server", "tikv-server", "pd-server"):
                cu.signal(name, "CONT")

    def log_files(self, test, node):
        return [PD_LOG, KV_LOG, DB_LOG]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


# -- SQL layer (`sql.clj`) ---------------------------------------------------

def _connect(test, node) -> Conn:
    fn = test.get("sql-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, SQL_PORT, user="root", password="",
                database="", timeout_s=10.0)


def _q(s) -> str:
    """Quote a value into SQL text: ints pass through, strings quote.
    Keys/values in these workloads are ints or int-derived strings."""
    if isinstance(s, bool):
        raise ValueError("no boolean literals in this dialect")
    if isinstance(s, int):
        return str(s)
    s = str(s)
    if "'" in s or "\\" in s:
        raise ValueError(f"unquotable literal {s!r}")
    return f"'{s}'"


class _SQLClient(jclient.Client):
    """Shared open/close and error classification. A statement error
    inside a transaction rolls back and classifies: DEFINITE_ABORT
    codes -> fail; anything else (connection death included) -> info
    unless the op was read-only."""

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _capture(self, op, e: Exception, read_only: bool) -> dict:
        if isinstance(e, MySQLError):
            if e.code in DEFINITE_ABORT or read_only:
                return {**op, "type": "fail", "error": ["sql", e.code,
                                                        e.message]}
            return {**op, "type": "info", "error": ["sql", e.code,
                                                    e.message]}
        return {**op, "type": "fail" if read_only else "info",
                "error": ["conn", str(e)]}

    def _txn(self, stmts_fn, op, read_only=False):
        """Run stmts_fn(conn) inside begin/commit with rollback and
        classification (`sql.clj` with-txn). SQL/connection errors are
        classified into fail/info; other exceptions (client control
        flow like a failed CAS) roll back and propagate."""
        conn = self.conn
        try:
            conn.query("begin")
            out = stmts_fn(conn)
            conn.query("commit")
            return {**op, "type": "ok", **out}
        except Exception as e:  # noqa: BLE001 — classified below
            try:
                conn.query("rollback")
            except Exception:  # noqa: BLE001 — conn may be dead
                pass
            if isinstance(e, (MySQLError, OSError, ConnectionError)):
                return self._capture(op, e, read_only)
            raise


# -- transactional micro-op client (`txn.clj`) -------------------------------

class TxnClient(_SQLClient):
    """Executes [f k v] micro-op transactions over `table_count` striped
    tables (`txn.clj:8-51`). Appends use ON DUPLICATE KEY UPDATE +
    CONCAT so the row is created or extended atomically."""

    def __init__(self, table_count: int = 7):
        super().__init__()
        self.table_count = table_count

    def _table(self, k) -> str:
        return f"txn{hash(k) % self.table_count}"

    def setup(self, test):
        for i in range(self.table_count):
            self.conn.query(
                f"create table if not exists txn{i} "
                f"(id int not null primary key, sk int not null, "
                f"val text)")

    def _mop(self, conn, m):
        f, k, v = m[0], m[1], m[2]
        t = self._table(k)
        if f == "r":
            rows, _ = conn.query(
                f"select val from {t} where id = {_q(k)}")
            if not rows or rows[0][0] is None:
                return ["r", k, []]
            raw = rows[0][0]
            return ["r", k, [int(x) for x in raw.split(",") if x != ""]]
        if f == "w":
            conn.query(
                f"insert into {t} (id, sk, val) values "
                f"({_q(k)}, {_q(k)}, {_q(str(v))}) "
                f"on duplicate key update val = {_q(str(v))}")
            return ["w", k, v]
        if f == "append":
            conn.query(
                f"insert into {t} (id, sk, val) values "
                f"({_q(k)}, {_q(k)}, {_q(str(v))}) "
                f"on duplicate key update val = "
                f"concat(val, ',', {_q(str(v))})")
            return ["append", k, v]
        raise ValueError(f"unknown micro-op {f!r}")

    def invoke(self, test, op):
        txn = op["value"]

        def body(conn):
            return {"value": [self._mop(conn, m) for m in txn]}

        if len(txn) > 1:
            return self._txn(body, op,
                             read_only=all(m[0] == "r" for m in txn))
        try:
            return {**op, "type": "ok", **body(self.conn)}
        except Exception as e:  # noqa: BLE001 — classified
            return self._capture(op, e,
                                 read_only=all(m[0] == "r" for m in txn))


class WrTxnClient(TxnClient):
    """rw-register flavor: reads return a single int value."""

    def _mop(self, conn, m):
        f, k, v = m[0], m[1], m[2]
        t = self._table(k)
        if f == "r":
            rows, _ = conn.query(
                f"select val from {t} where id = {_q(k)}")
            val = None if not rows or rows[0][0] is None \
                else int(rows[0][0])
            return ["r", k, val]
        return super()._mop(conn, m)


# -- monotonic (`monotonic.clj`) ---------------------------------------------

class MonotonicClient(_SQLClient):
    """Read-increment-write registers (`monotonic.clj:24-60`): a 'w'
    micro-op with a nil value writes its key's just-read value + 1, so
    every committed write is predecessor + 1. Reads in read-write txns
    take locks (select for update) like the reference's increments."""

    def setup(self, test):
        self.conn.query("create table if not exists mono "
                        "(id int not null primary key, val int)")

    def invoke(self, test, op):
        txn = op["value"]
        read_only = all(m[0] == "r" for m in txn)

        def body(conn):
            out = []
            cur: dict = {}
            for m in txn:
                f, k, v = m[0], m[1], m[2]
                if f == "r":
                    lock = "" if read_only else " for update"
                    rows, _ = conn.query(
                        f"select val from mono where id = {_q(k)}"
                        f"{lock}")
                    val = None if not rows or rows[0][0] is None \
                        else int(rows[0][0])
                    cur[k] = val
                    out.append(["r", k, val])
                else:
                    val = v if v is not None else (cur.get(k) or 0) + 1
                    conn.query(
                        f"insert into mono (id, val) values "
                        f"({_q(k)}, {_q(val)}) "
                        f"on duplicate key update val = {_q(val)}")
                    cur[k] = val
                    out.append(["w", k, val])
            return {"value": out}

        return self._txn(body, op, read_only=read_only)


# -- table (`table.clj`) -----------------------------------------------------

class TableClient(_SQLClient):
    """Creates numbered tables and races inserts into them; an insert
    that finds no table fails ['table-missing', t] (MySQL 1146), which
    the checker cross-references against create completions."""

    def invoke(self, test, op):
        if op["f"] == "create-table":
            t = op["value"]
            try:
                self.conn.query(
                    f"create table if not exists tbl{_q(t)} "
                    f"(id int not null primary key, val int)")
                return {**op, "type": "ok"}
            except Exception as e:  # noqa: BLE001 — classified
                return self._capture(op, e, read_only=False)
        t, k = op["value"]
        try:
            self.conn.query(f"insert into tbl{_q(t)} (id, val) values "
                            f"({_q(k)}, 1)")
            return {**op, "type": "ok"}
        except MySQLError as e:
            if e.code == 1146:
                return {**op, "type": "fail",
                        "error": ["table-missing", t]}
            return self._capture(op, e, read_only=False)
        except Exception as e:  # noqa: BLE001 — classified
            return self._capture(op, e, read_only=False)


# -- bank (`bank.clj`) -------------------------------------------------------

class BankClient(_SQLClient):
    def setup(self, test):
        self.conn.query("create table if not exists accounts "
                        "(id int not null primary key, "
                        "balance bigint not null)")
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            try:
                self.conn.query(
                    f"insert into accounts (id, balance) values "
                    f"({_q(a)}, {_q(total if a == accounts[0] else 0)})")
            except MySQLError as e:
                if e.code != 1062:  # another client seeded it
                    raise

    def invoke(self, test, op):
        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query("select id, balance from accounts")
                return {"value": {int(r[0]): int(r[1]) for r in rows}}
            return self._txn(read_body, op, read_only=True)

        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]

        def transfer_body(conn):
            rows, _ = conn.query(
                f"select balance from accounts where id = {_q(frm)} "
                f"for update")
            b1 = int(rows[0][0]) - amount
            rows, _ = conn.query(
                f"select balance from accounts where id = {_q(to)} "
                f"for update")
            b2 = int(rows[0][0]) + amount
            if b1 < 0:
                raise _InsufficientFunds(frm, b1)
            conn.query(f"update accounts set balance = {_q(b1)} "
                       f"where id = {_q(frm)}")
            conn.query(f"update accounts set balance = {_q(b2)} "
                       f"where id = {_q(to)}")
            return {}

        try:
            return self._txn(transfer_body, op)
        except _InsufficientFunds as e:
            return {**op, "type": "fail",
                    "value": ["negative", e.account, e.balance]}


class _InsufficientFunds(Exception):
    def __init__(self, account, balance):
        super().__init__(f"{account} would go to {balance}")
        self.account = account
        self.balance = balance


# -- linearizable register (`register.clj`) ----------------------------------

class RegisterClient(_SQLClient):
    """Independent-keyed CAS register: read/write/cas over one row per
    key, cas via select-for-update + conditional update in a txn."""

    def setup(self, test):
        self.conn.query("create table if not exists test "
                        "(id int not null primary key, val int)")

    def invoke(self, test, op):
        v = op["value"]
        if independent.is_tuple(v):
            k, inner = v

            def wrap(x):
                return independent.ktuple(k, x)
        else:
            k, inner = 0, v

            def wrap(x):
                return x

        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query(
                    f"select val from test where id = {_q(k)}")
                val = None if not rows or rows[0][0] is None \
                    else int(rows[0][0])
                return {"value": wrap(val)}
            try:
                return {**op, "type": "ok", **read_body(self.conn)}
            except Exception as e:  # noqa: BLE001 — classified
                return self._capture(op, e, read_only=True)

        if op["f"] == "write":
            def write_body(conn):
                conn.query(
                    f"insert into test (id, val) values "
                    f"({_q(k)}, {_q(inner)}) "
                    f"on duplicate key update val = {_q(inner)}")
                return {}
            return self._txn(write_body, op)

        old, new = inner

        def cas_body(conn):
            rows, _ = conn.query(
                f"select val from test where id = {_q(k)} for update")
            cur = None if not rows or rows[0][0] is None \
                else int(rows[0][0])
            if cur != old:
                raise _CasFail()
            conn.query(f"update test set val = {_q(new)} "
                       f"where id = {_q(k)}")
            return {}

        try:
            return self._txn(cas_body, op)
        except _CasFail:
            return {**op, "type": "fail"}


class _CasFail(Exception):
    pass


# -- grow-only set (`sets.clj`) ----------------------------------------------

class SetClient(_SQLClient):
    def setup(self, test):
        self.conn.query("create table if not exists sets "
                        "(id int not null auto_increment primary key, "
                        "value bigint)")

    def invoke(self, test, op):
        if op["f"] == "add":
            def add_body(conn):
                conn.query(f"insert into sets (value) values "
                           f"({_q(op['value'])})")
                return {}
            return self._txn(add_body, op)

        def read_body(conn):
            rows, _ = conn.query("select value from sets")
            return {"value": sorted(int(r[0]) for r in rows)}
        return self._txn(read_body, op, read_only=True)


# -- workloads ---------------------------------------------------------------

def append_workload(opts: dict) -> dict:
    w = append_w.workload(opts)
    w["client"] = TxnClient()
    return w


def wr_workload(opts: dict) -> dict:
    w = wr_w.workload(opts)
    w["client"] = WrTxnClient()
    return w


def bank_workload(opts: dict) -> dict:
    w = bank_w.test(opts)
    w["client"] = BankClient()
    return w


def register_workload(opts: dict) -> dict:
    w = linearizable_register.test({
        "nodes": opts["nodes"],
        "per-key-limit": opts.get("ops-per-key", 100),
    })
    w["client"] = RegisterClient()
    return w


def set_workload(opts: dict) -> dict:
    adds = ({"type": "invoke", "f": "add", "value": i}
            for i in itertools.count())
    return {
        "client": SetClient(),
        "checker": checker.set_checker(),
        "generator": adds,
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


def long_fork_workload(opts: dict) -> dict:
    w = long_fork_w.workload()
    w["client"] = WrTxnClient()
    return w


def monotonic_workload(opts: dict) -> dict:
    w = monotonic_w.workload(opts)
    w["client"] = MonotonicClient()
    return w


def sequential_workload(opts: dict) -> dict:
    w = sequential_w.workload(opts)
    w["client"] = WrTxnClient()
    return w


def table_workload(opts: dict) -> dict:
    w = table_w.workload(opts)
    w["client"] = TableClient()
    return w


WORKLOADS = {
    "append": append_workload,
    "wr": wr_workload,
    "bank": bank_workload,
    "register": register_workload,
    "set": set_workload,
    "long-fork": long_fork_workload,
    "monotonic": monotonic_workload,
    "sequential": sequential_workload,
    "table": table_workload,
}


def tidb_test(opts: dict) -> dict:
    """Build the test map from CLI options (`core.clj` + `run.sh`
    shape): workload menu x nemesis package."""
    workload_name = opts.get("workload", "append")
    return std_test(
        opts, name=f"tidb-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "append", DEFAULT_VERSION,
                    "TiDB version to install") + [
    cli.opt("--ops-per-key", type=int, default=100,
            help="ops per independent key (register workload)"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": tidb_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
