"""Percona XtraDB Cluster test suite.

Mirrors the reference's percona suite
(`/root/reference/percona/src/jepsen/percona{,/dirty_reads}.clj`):
the same dirty-reads and bank workloads as galera — Percona XtraDB is
a Galera-based MySQL — over Percona's package install. The clients and
checkers are shared with the galera suite module; only the DB
automation differs (percona repositories + percona-xtradb-cluster
packages, `percona.clj:34-80`)."""

from __future__ import annotations

import logging

from .. import cli, control
from ..control import util as cu
from ..os_ import debian
from . import std_opts, std_test
from .galera import (  # noqa: F401 — shared clients/checkers/workloads
    SQL_PORT, BankClient, DirtyReadsChecker, DirtyReadsClient,
    WORKLOADS, cluster_address)
from .galera import config_body as _galera_config

log = logging.getLogger(__name__)

CONFIG = "/etc/mysql/conf.d/cluster.cnf"
LOGFILE = "/var/log/mysql/error.log"
DEFAULT_VERSION = "5.6"

import jepsen_tpu.db as jdb  # noqa: E402


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing percona-xtradb %s", node,
                     self.version)
            debian.install(["rsync",
                            f"percona-xtradb-cluster-{self.version}"])
            control.exec_("sh", "-c",
                          f"cat > {CONFIG} <<'EOF'\n"
                          f"{_galera_config(test)}EOF")
            control.exec_("service", "mysql", "stop")
            if node == test["nodes"][0]:
                control.exec_("service", "mysql", "bootstrap-pxc")
            else:
                control.exec_("service", "mysql", "start")
            cu.await_tcp_port(SQL_PORT)
            control.exec_(
                "mysql", "-u", "root", "-e",
                "create database if not exists jepsen; "
                "grant all on jepsen.* to 'jepsen'@'%' "
                "identified by 'jepsen'; flush privileges")

    def start(self, test, node):
        with control.su():
            control.exec_("service", "mysql", "start")

    def kill(self, test, node):
        with control.su():
            cu.grepkill("mysqld")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", "/var/lib/mysql/grastate.dat")

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


def percona_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "dirty-reads")
    return std_test(
        opts, name=f"percona-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "dirty-reads", DEFAULT_VERSION,
                    "percona-xtradb-cluster version")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": percona_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
