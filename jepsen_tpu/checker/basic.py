"""The O(n) fold checkers.

Behavioral parity with `jepsen/src/jepsen/checker.clj`:
stats (:166-183), unhandled-exceptions (:124-151), queue (:218-238),
set (:240-291), set-full (:294-592), total-queue (:628-687, with drain
expansion :594-626), unique-ids (:689-734), counter (:737-795),
log-file-pattern (:839-881).
"""

from __future__ import annotations

import os
import re
from collections import Counter
from typing import Any

from .. import models as m
from ..history import (History, NEMESIS, is_client_op, is_fail, is_info,
                       is_invoke, is_ok)
from ..util import bounded_pmap, integer_interval_set_str, nanos_to_ms
from . import Checker, UNKNOWN, merge_valid


def _stats(ops) -> dict:
    ok = sum(1 for o in ops if is_ok(o))
    fail = sum(1 for o in ops if is_fail(o))
    info = sum(1 for o in ops if is_info(o))
    return {"valid?": ok > 0, "count": ok + fail + info,
            "ok-count": ok, "fail-count": fail, "info-count": info}


class Stats(Checker):
    """Success/failure rates, overall and by :f. Valid iff every :f saw at
    least one :ok op."""

    def check(self, test, hist, opts):
        comps = [o for o in hist
                 if not is_invoke(o) and o.get("process") != NEMESIS]
        by_f: dict = {}
        for o in comps:
            by_f.setdefault(o["f"], []).append(o)
        groups = {f: _stats(ops) for f, ops in sorted(by_f.items(),
                                                      key=lambda kv: str(kv[0]))}
        out = _stats(comps)
        out["by-f"] = groups
        out["valid?"] = merge_valid(g["valid?"] for g in groups.values())
        return out


def stats() -> Checker:
    return Stats()


class UnhandledExceptions(Checker):
    """Aggregates :info ops carrying an :exception, grouped by class,
    descending frequency."""

    def check(self, test, hist, opts):
        excs = [o for o in hist
                if o.get("exception") is not None and is_info(o)]
        groups: dict = {}
        for o in excs:
            cls = o["exception"].get("class") \
                if isinstance(o["exception"], dict) \
                else type(o["exception"]).__name__
            groups.setdefault(cls, []).append(o)
        out = [{"count": len(ops), "class": cls, "example": ops[0]}
               for cls, ops in sorted(groups.items(),
                                      key=lambda kv: -len(kv[1]))]
        result = {"valid?": True}
        if out:
            result["exceptions"] = out
        return result


def unhandled_exceptions() -> Checker:
    return UnhandledExceptions()


class Queue(Checker):
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded, only :ok dequeues succeeded; fold through the model."""

    def __init__(self, model: m.Model):
        self.model = model

    def check(self, test, hist, opts):
        state = self.model
        for o in hist:
            take = (is_invoke(o) if o["f"] == "enqueue"
                    else is_ok(o) if o["f"] == "dequeue" else False)
            if not take:
                continue
            state = state.step(o)
            if m.is_inconsistent(state):
                return {"valid?": False, "error": state.msg}
        return {"valid?": True, "final-queue": state}


def queue(model: m.Model) -> Checker:
    return Queue(model)


class SetChecker(Checker):
    """:add ops followed by a final :read; every acknowledged add must be
    present, and nothing never-attempted may appear."""

    def check(self, test, hist, opts):
        attempts = {o["value"] for o in hist
                    if is_invoke(o) and o["f"] == "add"}
        adds = {o["value"] for o in hist if is_ok(o) and o["f"] == "add"}
        final_read = None
        for o in hist:
            if is_ok(o) and o["f"] == "read":
                final_read = o["value"]
        if final_read is None:
            return {"valid?": UNKNOWN, "error": "Set was never read"}
        final_read = set(final_read)
        ok = final_read & attempts
        unexpected = final_read - attempts
        lost = adds - final_read
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }


def set_checker() -> Checker:
    return SetChecker()


# -- set-full ---------------------------------------------------------------

class _SetElement:
    """Timeline state for one element (reference SetFullElement,
    checker.clj:313-344)."""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known = None          # completion op confirming existence
        self.last_present = None   # most recent observing read *invocation*
        self.last_absent = None    # most recent missing read *invocation*

    def add_ok(self, op):
        if self.known is None:
            self.known = op

    def read_present(self, inv, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or \
                self.last_present["index"] < inv["index"]:
            self.last_present = inv

    def read_absent(self, inv, op):
        if self.last_absent is None or \
                self.last_absent["index"] < inv["index"]:
            self.last_absent = inv


def _set_element_results(e: _SetElement) -> dict:
    def idx(op, default=-1):
        return op["index"] if op is not None else default

    stable = e.last_present is not None and \
        idx(e.last_absent) < idx(e.last_present)
    lost = (e.known is not None and e.last_absent is not None
            and idx(e.last_present) < idx(e.last_absent)
            and idx(e.known) < idx(e.last_absent))
    never_read = not (stable or lost)
    known_time = e.known["time"] if e.known else None
    stable_time = ((e.last_absent["time"] + 1 if e.last_absent else 0)
                   if stable else None)
    lost_time = ((e.last_present["time"] + 1 if e.last_present else 0)
                 if lost else None)
    stable_latency = (int(nanos_to_ms(max(0, stable_time - known_time)))
                      if stable else None)
    lost_latency = (int(nanos_to_ms(max(0, lost_time - known_time)))
                    if lost else None)
    return {"element": e.element,
            "outcome": ("stable" if stable else
                        "lost" if lost else "never-read"),
            "stable-latency": stable_latency,
            "lost-latency": lost_latency,
            "known": e.known,
            "last-absent": e.last_absent}


def _frequency_distribution(points, xs):
    xs = sorted(xs)
    if not xs:
        return None
    n = len(xs)
    return {p: xs[min(n - 1, int(n * p))] for p in points}


class SetFull(Checker):
    """Per-element stable/lost timeline analysis (reference set-full,
    checker.clj:461-592). With linearizable=True, stale reads fail."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, hist, opts):
        hist = History(hist).index()
        elements: dict[Any, _SetElement] = {}
        reads: dict[int, dict] = {}   # process -> read invocation
        dups: dict[Any, int] = {}     # element -> max multiplicity > 1
        for o in hist:
            if not is_client_op(o):
                continue
            f, p, v = o["f"], o["process"], o["value"]
            if f == "add":
                if is_invoke(o):
                    elements.setdefault(v, _SetElement(v))
                elif is_ok(o):
                    if v in elements:
                        elements[v].add_ok(o)
            elif f == "read":
                if is_invoke(o):
                    reads[p] = o
                elif is_fail(o):
                    reads.pop(p, None)
                elif is_ok(o):
                    inv = reads.pop(p, o)
                    for x, n in Counter(v).items():
                        if n > 1:
                            dups[x] = max(dups.get(x, 0), n)
                    vs = set(v)
                    for element, state in elements.items():
                        if element in vs:
                            state.read_present(inv, o)
                        else:
                            state.read_absent(inv, o)
        rs = [_set_element_results(e)
              for _, e in sorted(elements.items(), key=lambda kv: kv[0])]
        outcomes: dict[str, list] = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable-latency"]]
        worst_stale = sorted(stale, key=lambda r: -r["stable-latency"])[:8]
        valid = (False if lost else
                 UNKNOWN if not stable else
                 False if self.linearizable and stale else
                 True)
        out = {
            "valid?": valid if not dups else False,
            "attempt-count": len(rs),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted(r["element"] for r in lost),
            "never-read-count": len(never_read),
            "never-read": sorted(r["element"] for r in never_read),
            "stale-count": len(stale),
            "stale": sorted(r["element"] for r in stale),
            "worst-stale": worst_stale,
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items())),
        }
        points = [0, 0.5, 0.95, 0.99, 1]
        sl = _frequency_distribution(
            points, [r["stable-latency"] for r in rs
                     if r["stable-latency"] is not None])
        if sl:
            out["stable-latencies"] = sl
        ll = _frequency_distribution(
            points, [r["lost-latency"] for r in rs
                     if r["lost-latency"] is not None])
        if ll:
            out["lost-latencies"] = ll
        return out


def set_full(linearizable: bool = False) -> Checker:
    return SetFull(linearizable)


# -- queues -----------------------------------------------------------------

def expand_queue_drain_ops(hist) -> list[dict]:
    """Expand :ok :drain ops (value = collection of elements) into
    :dequeue invoke/ok pairs (reference checker.clj:594-626)."""
    out = []
    for o in hist:
        if o["f"] != "drain":
            out.append(o)
        elif is_invoke(o) or is_fail(o):
            continue
        elif is_ok(o):
            for element in o["value"]:
                out.append({**o, "type": "invoke", "f": "dequeue",
                            "value": None})
                out.append({**o, "type": "ok", "f": "dequeue",
                            "value": element})
        else:
            raise ValueError(f"can't handle a crashed drain operation: {o}")
    return out


class TotalQueue(Checker):
    """What goes in must come out; requires the history to fully drain the
    queue (reference total-queue, checker.clj:628-687)."""

    def check(self, test, hist, opts):
        # Indeterminate dequeues/drains may have consumed messages whose
        # values we never learned (e.g. a destructive get whose response
        # was lost in transit). Each :info dequeue can absorb one lost
        # message — a :info drain, any number — degrading a "lost"
        # verdict to unknown rather than reporting a false loss.
        indet = sum(1 for o in hist
                    if is_info(o) and o["f"] == "dequeue")
        indet_drain = any(is_info(o) and o["f"] == "drain" for o in hist)
        hist = expand_queue_drain_ops(
            [o for o in hist if not (is_info(o) and o["f"] == "drain")])
        attempts = Counter(o["value"] for o in hist
                           if is_invoke(o) and o["f"] == "enqueue")
        enqueues = Counter(o["value"] for o in hist
                           if is_ok(o) and o["f"] == "enqueue")
        dequeues = Counter(o["value"] for o in hist
                           if is_ok(o) and o["f"] == "dequeue")
        ok = dequeues & attempts
        unexpected = Counter({k: n for k, n in dequeues.items()
                              if k not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        valid: Any = not lost and not unexpected
        if (lost and not unexpected
                and (indet_drain or sum(lost.values()) <= indet)):
            valid = UNKNOWN
        return {
            "valid?": valid,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }


def total_queue() -> Checker:
    return TotalQueue()


class UniqueIds(Checker):
    """A unique-id generator must emit distinct ids (:f :generate)."""

    def check(self, test, hist, opts):
        attempted = sum(1 for o in hist
                        if is_invoke(o) and o["f"] == "generate")
        acks = [o["value"] for o in hist
                if is_ok(o) and o["f"] == "generate"]
        counts = Counter(acks)
        dups = {k: n for k, n in counts.items() if n > 1}
        rng = [min(acks), max(acks)] if acks else [None, None]
        return {
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": len(acks),
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items(),
                                      key=lambda kv: -kv[1])[:48]),
            "range": rng,
        }


def unique_ids() -> Checker:
    return UniqueIds()


class CounterChecker(Checker):
    """Monotonically-increasing counter bounds: at each read, the observed
    value must lie within [sum of :ok adds at invoke, sum of attempted adds
    at completion] (reference counter, checker.clj:737-795)."""

    def check(self, test, hist, opts):
        hist = History(hist).client_ops()
        pairs = hist.pair_index()
        # knossos history/complete semantics: drop pairs whose completion
        # failed; reads take their completion's observed value.
        drop = set()
        values: dict[int, Any] = {}
        for i, o in enumerate(hist.ops):
            j = pairs.get(i)
            if is_fail(o):
                drop.add(i)
                if j is not None:
                    drop.add(j)
            if is_invoke(o) and j is not None:
                values[i] = hist.ops[j]["value"]
        lower, upper = 0, 0
        pending_reads: dict[int, list] = {}
        reads = []
        for i, o in enumerate(hist.ops):
            if i in drop:
                continue
            t, f, p = o["type"], o["f"], o["process"]
            if t == "invoke" and f == "read":
                pending_reads[p] = [lower, values.get(i, o["value"])]
            elif t == "ok" and f == "read":
                r = pending_reads.pop(p, [lower, o["value"]])
                reads.append([r[0], o["value"], upper])
            elif t == "invoke" and f == "add":
                assert o["value"] >= 0, "counter assumes increments only"
                upper += o["value"]
            elif t == "ok" and f == "add":
                lower += o["value"]
        errors = [r for r in reads if not r[0] <= r[1] <= r[2]]
        return {"valid?": not errors, "reads": reads, "errors": errors}


def counter() -> Checker:
    return CounterChecker()


class CounterPlotChecker(Checker):
    """Renders counter.svg: the admissible [lower, upper] band over
    time (lower = sum of acked adds, upper = sum of attempted adds)
    with each observed read on top — green inside the band, red
    outside.  The reference wants exactly this plot (its `doc/plan.md`
    "add a plot for counters, showing the upper and lower bounds, and
    the observed value"); compose it next to `counter()`, which does
    the judging."""

    def check(self, test, hist, opts):
        from .. import plot as gp
        from .perf import out_path

        hist = History(hist).client_ops()
        # same pair semantics as CounterChecker: a failed completion
        # definitely did not happen, so its invoke must not widen the
        # upper bound — otherwise the plot green-lights reads the
        # counter checker rejects
        pairs = hist.pair_index()
        drop = set()
        for i, o in enumerate(hist.ops):
            if is_fail(o):
                drop.add(i)
                j = pairs.get(i)
                if j is not None:
                    drop.add(j)
        lower = upper = 0
        t0 = hist.ops[0]["time"] if hist.ops else 0
        lows, highs, ok_reads, bad_reads = [], [], [], []
        pending: dict[int, int] = {}  # process -> lower at invoke
        for i, o in enumerate(hist.ops):
            if i in drop:
                continue
            t = (o["time"] - t0) / 1e9
            ty, f, p = o["type"], o["f"], o["process"]
            if f == "add":
                if ty == "invoke":
                    upper += o["value"]
                    highs.append((t, upper))
                elif ty == "ok":
                    lower += o["value"]
                    lows.append((t, lower))
            elif f == "read":
                if ty == "invoke":
                    pending[p] = lower
                elif ty == "ok":
                    lo = pending.pop(p, lower)
                    tgt = ok_reads if lo <= o["value"] <= upper \
                        else bad_reads
                    tgt.append((t, o["value"]))
        p = gp.Plot(title=f"{test.get('name', '')} counter",
                    ylabel="Value")
        if lows:
            p.series.append(gp.Series(
                title="lower bound (acked adds)", data=lows,
                color="#4477aa", mode="steps"))
        if highs:
            p.series.append(gp.Series(
                title="upper bound (attempted adds)", data=highs,
                color="#FFA400", mode="steps"))
        if ok_reads:
            p.series.append(gp.Series(
                title="read", data=ok_reads, color="#6DB6FE",
                mode="points", point_type=1))
        if bad_reads:
            p.series.append(gp.Series(
                title="read out of bounds", data=bad_reads,
                color="#FF1E90", mode="points", point_type=2))
        gp.write(p, out_path(test, opts, "counter.svg"))
        return {"valid?": True}


def counter_plot() -> Checker:
    return CounterPlotChecker()


class LogFilePattern(Checker):
    """Greps each node's downloaded log file for a pattern; matches make the
    history invalid (reference checker.clj:839-881)."""

    def __init__(self, pattern: str, filename: str):
        self.pattern = re.compile(pattern)
        self.filename = filename

    def check(self, test, hist, opts):
        from .. import store
        matches = []

        def search(node):
            path = store.path(test, node, self.filename)
            if not os.path.exists(path):
                return []
            out = []
            with open(path, errors="replace") as fh:
                for line in fh:
                    if self.pattern.search(line):
                        out.append({"node": node, "line": line.rstrip("\n")})
            return out

        for found in bounded_pmap(search, test.get("nodes", [])):
            matches.extend(found)
        return {"valid?": not matches, "count": len(matches),
                "matches": matches}


def log_file_pattern(pattern: str, filename: str) -> Checker:
    return LogFilePattern(pattern, filename)
