"""Performance analysis: latency and rate graphs from histories.

Reference: `jepsen/src/jepsen/checker/perf.clj` — time-bucketing and
quantile extraction (:21-86), splitting invocations by f and completion
type (:95-125), nemesis activity regions/lines (:184-324), and the
latency point/quantile/rate graphs (:484-599). Rendering goes through
`jepsen_tpu.plot` (SVG) instead of the reference's external gnuplot
binary.
"""

from __future__ import annotations

import itertools
import logging
from typing import Iterable, Optional

from .. import plot as gp
from .. import store, util
from ..history import NEMESIS, history, is_invoke
from . import Checker

log = logging.getLogger(__name__)

DEFAULT_NEMESIS_COLOR = "#cccccc"
NEMESIS_ALPHA = 0.6

TYPES = ("ok", "info", "fail")

TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}

QUANTILE_COLORS = ["red", "orange", "purple", "blue", "green", "grey"]


# -- time bucketing (`perf.clj:21-49`) --------------------------------------

def bucket_scale(dt: float, b: float) -> float:
    """Time at the midpoint of bucket number b."""
    return int(b) * dt + dt / 2


def bucket_time(dt: float, t: float) -> float:
    """Midpoint of the bucket t falls into."""
    return bucket_scale(dt, t / dt)


def buckets(dt: float, tmax: float) -> list[float]:
    """Midpoints of each bucket up to tmax."""
    out, b = [], 0
    while True:
        t = bucket_scale(dt, b)
        if t > tmax:
            return out
        out.append(t)
        b += 1


def bucket_points(dt: float, points: Iterable) -> dict:
    """{bucket-midpoint: [point, ...]}, ordered by time."""
    out: dict = {}
    for p in points:
        out.setdefault(bucket_time(dt, p[0]), []).append(p)
    return dict(sorted(out.items()))


def quantiles(qs: Iterable[float], points: Iterable[float]) -> dict:
    """{q: value-at-q} over points (`perf.clj:51-61`)."""
    s = sorted(points)
    if not s:
        return {}
    n = len(s)
    return {q: s[min(n - 1, int(n * q))] for q in qs}


def latencies_to_quantiles(dt: float, qs, points) -> dict:
    """{q: [[bucket-time, latency-at-q], ...]} (`perf.clj:63-85`)."""
    assert all(0 <= q <= 1 for q in qs)
    bucketed = [(t, quantiles(qs, [p[1] for p in ps]))
                for t, ps in bucket_points(dt, points).items()]
    return {q: [[t, qv.get(q)] for t, qv in bucketed] for q in qs}


# -- history splitting (`perf.clj:87-148`) ----------------------------------

def invokes_by_type(ops) -> dict:
    """Split invocations by their completion's type."""
    return {t: [o for o in ops
                if (o.get("completion") or {}).get("type") == t]
            for t in TYPES}


def invokes_by_f(hist) -> dict:
    out: dict = {}
    for o in hist:
        if is_invoke(o):
            out.setdefault(o.get("f"), []).append(o)
    return out


def invokes_by_f_type(hist) -> dict:
    return {f: invokes_by_type(ops) for f, ops in invokes_by_f(hist).items()}


def completions_by_f_type(hist) -> dict:
    out: dict = {}
    for o in hist:
        if not is_invoke(o):
            out.setdefault(o.get("f"), {}) \
               .setdefault(o.get("type"), []).append(o)
    return out


def rate(hist) -> dict:
    """Completion *counts* by f and type, with 'all' totals at each
    level (`perf.clj:127-141`)."""
    out: dict = {}
    for o in hist:
        if is_invoke(o):
            continue
        f, t = o.get("f"), o.get("type")
        for kf, kt in ((f, t), (f, "all"), ("all", t), ("all", "all")):
            out.setdefault(kf, {})
            out[kf][kt] = out[kf].get(kt, 0) + 1
    return out


def latency_point(op: dict) -> tuple:
    """[time-in-seconds, latency-in-ms] (`perf.clj:143-148`)."""
    return (util.nanos_to_secs(op["time"]),
            op["latency"] / 1e6)


def fs_to_points(fs) -> dict:
    """f -> point-shape index, one distinct marker per f
    (`perf.clj:150-156`)."""
    return {f: i for i, f in enumerate(fs)}


def qs_to_colors(qs) -> dict:
    """quantile -> color, highest quantile hottest
    (`perf.clj:158-172`)."""
    return dict(zip(sorted(qs, reverse=True),
                    itertools.cycle(QUANTILE_COLORS)))


def polysort(xs) -> list:
    return sorted(xs, key=lambda x: (str(type(x)), str(x)))


# -- nemesis activity (`perf.clj:184-324`) ----------------------------------

def nemesis_ops(nemeses, hist) -> list[dict]:
    """Partition the history's nemesis ops among the nemesis specs;
    unmatched ops fall into a default 'nemesis' spec
    (`perf.clj:184-216`)."""
    nemeses = list(nemeses or [])
    assert all(n.get("name") for n in nemeses)
    index = {}
    for n in nemeses:
        for f in (list(n.get("start") or ["start"]) +
                  list(n.get("stop") or ["stop"]) +
                  list(n.get("fs") or [])):
            index[f] = n["name"]
    by_name: dict = {}
    for o in hist:
        if o.get("process") == NEMESIS:
            by_name.setdefault(index.get(o.get("f")), []).append(o)
    out = [dict(n, ops=by_name[n["name"]])
           for n in nemeses if n["name"] in by_name]
    if None in by_name:
        out.append({"name": "nemesis", "ops": by_name[None]})
    return out


def nemesis_activity(nemeses, hist) -> list[dict]:
    """nemesis_ops plus [start, stop] interval pairing
    (`perf.clj:218-231`)."""
    out = []
    for n in nemesis_ops(nemeses, hist):
        start = set(n.get("start") or ["start"])
        stop = set(n.get("stop") or ["stop"])
        out.append(dict(n, intervals=util.nemesis_intervals(
            n["ops"], start_fs=start, stop_fs=stop)))
    return out


def interval_times(interval) -> tuple:
    a, b = interval
    return (util.nanos_to_secs(a["time"]),
            util.nanos_to_secs(b["time"]) if b else None)


def with_nemeses(p: gp.Plot, hist, nemeses) -> gp.Plot:
    """Add shaded activity regions, event lines, and legend entries for
    each nemesis (`perf.clj:240-324`). Each nemesis gets a twelfth of
    the graph height, stacked from the top."""
    height, padding = 0.0834, 0.00615
    for i, n in enumerate(nemesis_activity(nemeses, hist)):
        fill = n.get("fill-color") or n.get("color") or DEFAULT_NEMESIS_COLOR
        line = n.get("line-color") or n.get("color") or DEFAULT_NEMESIS_COLOR
        alpha = n.get("transparency", NEMESIS_ALPHA)
        bot = 1 - height * (i + 1)
        top = bot + height
        for iv in n["intervals"]:
            t0, t1 = interval_times(iv)
            p.regions.append(gp.Region(
                x0=t0, x1=t1, y0_frac=bot + padding, y1_frac=top - padding,
                color=fill, alpha=alpha))
        for o in n["ops"]:
            p.vlines.append(gp.VLine(
                x=util.nanos_to_secs(o["time"]), color=line,
                width=float(n.get("line-width", 1))))
        # legend entry via a dummy line series (`perf.clj:295-308`)
        p.series.append(gp.Series(title=str(n["name"]), data=[],
                                  color=fill, mode="lines", line_width=6))
    return p


# -- graphs (`perf.clj:484-599`) --------------------------------------------

def out_path(test, opts, filename: str) -> str:
    """Path for a rendered artifact, honoring opts['subdirectory'] (the
    reference's `store/path! test subdirectory file` idiom)."""
    sub = (opts or {}).get("subdirectory")
    parts = ([str(sub)] if sub else []) + [filename]
    return store.make_path(test, *parts)


def _nemeses(test, opts):
    return (opts or {}).get("nemeses") or \
        ((test.get("plot") or {}).get("nemeses"))


def point_graph(test, hist, opts=None) -> Optional[str]:
    """Raw latency scatter: one point per invocation, colored by
    completion type, marker shape by f (`perf.clj:484-511`)."""
    hist = util.history_latencies(hist)
    datasets = invokes_by_f_type(hist)
    fs = polysort(datasets.keys())
    shapes = fs_to_points(fs)
    p = gp.Plot(title=f"{test.get('name', '')} latency",
                ylabel="Latency (ms)", logscale_y=True,
                draw_fewer_on_top=True)
    for f in fs:
        for t in TYPES:
            data = datasets[f].get(t) or []
            if data:
                p.series.append(gp.Series(
                    title=f"{f} {t}", data=[latency_point(o) for o in data],
                    color=TYPE_COLORS[t], mode="points",
                    point_type=shapes[f]))
    with_nemeses(p, hist, _nemeses(test, opts))
    return gp.write(p, out_path(test, opts, "latency-raw.svg"))


NICE_DTS = (1, 2, 5, 10, 30, 60, 120, 300, 600, 1800, 3600)


def adaptive_dt(hist, target_buckets: int = 60,
                t_max: float | None = None) -> float:
    """Bucket width giving ~target_buckets windows over the history's
    duration, snapped to a human-friendly step.  Fixed 30 s windows (the
    reference's default) flatten a one-minute test into two points and
    oversample a day-long soak; adapting to point density is its
    plan.md "adaptive temporal resolution" item.  Pass t_max (seconds)
    when the caller already scanned the history for it."""
    if t_max is None:
        t_max = util.nanos_to_secs(max((o.get("time", 0) for o in hist),
                                       default=0))
    want = t_max / max(target_buckets, 1)
    for dt in NICE_DTS:
        if dt >= want:
            return dt
    return NICE_DTS[-1]


def quantiles_graph(test, hist, opts=None, dt: float | None = None,
                    qs=(0.5, 0.95, 0.99, 1)) -> Optional[str]:
    """Latency quantiles per f over dt-second windows
    (`perf.clj:513-550`); dt=None picks an adaptive width."""
    hist = util.history_latencies(hist)
    if dt is None:
        dt = adaptive_dt(hist)
    colors = qs_to_colors(qs)
    datasets = {
        f: latencies_to_quantiles(dt, qs, [latency_point(o) for o in ops
                                           if "latency" in o])
        for f, ops in invokes_by_f(hist).items()}
    fs = polysort(datasets.keys())
    shapes = fs_to_points(fs)
    p = gp.Plot(title=f"{test.get('name', '')} latency",
                ylabel="Latency (ms)", logscale_y=True)
    for f in fs:
        for q in qs:
            data = [d for d in datasets[f].get(q, []) if d[1] is not None]
            if data:
                p.series.append(gp.Series(
                    title=f"{f} {q}", data=data, color=colors[q],
                    mode="linespoints", point_type=shapes[f]))
    with_nemeses(p, hist, _nemeses(test, opts))
    return gp.write(p, out_path(test, opts, "latency-quantiles.svg"))


def rate_graph(test, hist, opts=None, dt: float | None = None
               ) -> Optional[str]:
    """Completion rate (hz) by f and type over dt-second buckets;
    nemesis completions are excluded (`perf.clj:559-599`).  dt=None
    picks an adaptive width."""
    hist = history(hist)
    t_max = util.nanos_to_secs(max((o.get("time", 0) for o in hist),
                                   default=0))
    if dt is None:
        dt = adaptive_dt(hist, t_max=t_max)
    datasets: dict = {}
    for o in hist:
        if is_invoke(o) or not isinstance(o.get("process"), int):
            continue
        b = bucket_time(dt, util.nanos_to_secs(o["time"]))
        d = datasets.setdefault(o.get("f"), {}).setdefault(o.get("type"), {})
        d[b] = d.get(b, 0) + 1.0 / dt
    fs = polysort(datasets.keys())
    shapes = fs_to_points(fs)
    bs = buckets(dt, t_max)
    p = gp.Plot(title=f"{test.get('name', '')} rate",
                ylabel="Throughput (hz)")
    for f in fs:
        for t in TYPES:
            m = datasets[f].get(t)
            if m:
                p.series.append(gp.Series(
                    title=f"{f} {t}",
                    data=[(b, m.get(b, 0)) for b in bs],
                    color=TYPE_COLORS[t], mode="linespoints",
                    point_type=shapes[f]))
    with_nemeses(p, hist, _nemeses(test, opts))
    return gp.write(p, out_path(test, opts, "rate.svg"))


# -- checkers (`checker.clj:797-829`) ---------------------------------------

class LatencyGraph(Checker):
    """Renders raw + quantile latency graphs (`checker.clj:797-808`)."""

    def check(self, test, hist, opts):
        point_graph(test, hist, opts)
        quantiles_graph(test, hist, opts)
        return {"valid?": True}


def latency_graph() -> Checker:
    return LatencyGraph()


class RateGraph(Checker):
    """Renders the rate graph (`checker.clj:810-820`)."""

    def check(self, test, hist, opts):
        rate_graph(test, hist, opts)
        return {"valid?": True}


def rate_graph_checker() -> Checker:
    return RateGraph()


def perf_checker() -> Checker:
    """Composes latency and rate graphs (`checker.clj:822-829`)."""
    from . import compose
    return compose({"latency-graph": latency_graph(),
                    "rate-graph": rate_graph_checker()})
