"""Tier-1 verification: O(n) streaming invariant screens.

Full WGL / Elle checking of every history is too expensive to run on
all traffic; this module is the cheap first tier that makes always-on
verification affordable (ROADMAP: "tiered always-on verification",
A-QED-style cheap-screen + selective-full-check, arXiv 2108.06081).
A screen consumes a history one op at a time — live off
`store.Journal.subscribe` via the OnlineChecker, or post-hoc in one
pass — maintains O(1)-per-op invariants, and emits a *screen verdict*
with a **suspicion score**:

  * suspicion >= 1 (a definite invariant violation, or a provable
    cycle) escalates to the full device search, which produces the
    authoritative verdict and blame certificate;
  * suspicion in (0, 1) is soft signal (crashed mutating ops make
    anomalies easier to hide and searches harder) — it raises the
    sampling odds but never forces escalation alone;
  * a sampled fraction of clean histories escalates anyway
    (deterministically, keyed on the history length), so the screen's
    blind spots are audited continuously. The sampling probability is
    priced through ``wgl.select_engine``'s cost model: histories whose
    modeled full-check cost is high are sampled proportionally less,
    so the tier-1 audit budget buys the most checks per element-op.

Model families without invariant checks (mutex, unordered-queue,
host-only models) report ``screenable: False`` and ALWAYS escalate —
a no-op screen never feeds the sampled-audit path.

The screens are SOUND for validity ("violation found" implies the
history is really not linearizable / not serializable) but incomplete
— a pure ordering anomaly among concurrent register ops can pass the
linearizable screen. The wr screen is stronger: cycle *existence* is
decided exactly (linear-time SCC over the accumulated dependency
edges — every Adya cycle anomaly implies a nontrivial SCC), so only
the classification/certificate work is deferred to escalation.

Checks per model family (each O(1) amortized per op; g-set is O(E)
per read with E <= GSET_MAX_ELEMENTS):

  register / cas-register
    phantom-read    an ok read observes a value no op ever wrote
    stale-read      a read r of v where some write w' completed before
                    r invoked AND every write of v completed before w'
                    invoked — v was definitely overwritten (the
                    classic single-register real-time violation)
  counter
    counter-bounds  an observed read outside [lo, hi], where definite
                    adds (completed before the read's invoke) count
                    exactly and in-flight adds contribute their signed
                    range — sound under any linearization
  g-set
    set-lost        a read missing an element whose add completed
                    before the read invoked
    set-phantom     a read containing a never-added element
  wr transactions (WrScreen)
    the single-pass Elle cases (G1a / G1b / internal / duplicate
    writes) plus exact dependency-cycle existence via SCC

Escalation plumbing lives in `linear.Linearizable(tier=...)` /
`elle.RWRegisterChecker` / CLI ``--tier`` (knob ``--screen-sample``).
"""

from __future__ import annotations

import logging
import time as _time
from typing import Any

from .. import telemetry as _telemetry
from ..history import history as as_history
from . import UNKNOWN  # noqa: F401  (re-exported result vocabulary)

log = logging.getLogger(__name__)

# -- telemetry (doc/observability.md catalogs these) -------------------------
# Per-op increments are deliberately avoided on the screen hot path:
# ops are counted in one batch at finish(), so the O(n) screens stay
# O(n) work + O(1) bookkeeping.
_M_SCREENED = _telemetry.counter(
    "jepsen_tpu_screen_screened_ops_total",
    "History ops consumed by tier-1 screens", ("screen",))
_M_SECONDS = _telemetry.histogram(
    "jepsen_tpu_screen_pass_seconds",
    "Tier-1 screen wall time, feed to finish", ("screen",))
_M_VIOL = _telemetry.counter(
    "jepsen_tpu_screen_violations_total",
    "Definite tier-1 invariant violations by check", ("check",))
_M_ESC = _telemetry.counter(
    "jepsen_tpu_screen_escalations_total",
    "Tier-1 escalations to the full device search, by reason",
    ("why",))

# escalate when suspicion reaches this (any definite violation does)
ESCALATE_THRESHOLD = 1.0
# default sampled-escalation fraction for clean histories
DEFAULT_SAMPLE = 0.05
# soft-signal weight per crashed mutating op, and its total cap —
# always strictly below the threshold: soft signals alone never force
# a full check, they only raise the sampling odds
SOFT_CRASH_WEIGHT = 0.02
SOFT_CAP = 0.5
# modeled element-ops at which sampling is at full strength; costlier
# histories sample proportionally less (see should_escalate)
COST_REF = 5e7


def tier_is_screen(tier) -> bool:
    """Normalize the tier knob: 1 / '1' / 'screen' select the tiered
    pipeline; None / 0 / 'full' keep today's always-full behavior."""
    return tier in (1, "1", "screen")


def sample_decision(key: int, fraction: float) -> bool:
    """Deterministic Bernoulli(fraction) on an integer key (Knuth
    multiplicative hash) — reproducible across runs and processes, so
    a replayed history makes the same escalation choice."""
    if fraction <= 0:
        return False
    if fraction >= 1:
        return True
    u = ((int(key) * 2654435761) & 0xFFFFFFFF) / 2.0 ** 32
    return u < fraction


def should_escalate(screen: dict, sample: float = DEFAULT_SAMPLE,
                    cost: float | None = None,
                    key: int | None = None) -> tuple[bool, str]:
    """The tier-1 escalation decision. Returns (escalate?, why) with
    why in {'suspicion', 'unscreened-model', 'sampled', ''}. A screen
    that ran NO invariants (screenable=False — a model family the
    screen has no checks for) always escalates: a no-op screen must
    never pass a history into the sampled-audit path. `cost` is the
    modeled element-op cost of the full check (price_escalation): the
    sampled fraction scales down as min(1, COST_REF / cost) so the
    audit budget is spent where full checks are cheap."""
    if not screen.get("screenable", True):
        _M_ESC.labels(why="unscreened-model").inc()
        return True, "unscreened-model"
    s = float(screen.get("suspicion", 0.0))
    if s >= ESCALATE_THRESHOLD:
        _M_ESC.labels(why="suspicion").inc()
        return True, "suspicion"
    p = float(sample)
    if cost:
        p *= min(1.0, COST_REF / max(float(cost), 1.0))
    k = key if key is not None else screen.get("op-count", 0)
    if sample_decision(int(k), p):
        _M_ESC.labels(why="sampled").inc()
        return True, "sampled"
    return False, ""


def price_escalation(model, hist) -> dict | None:
    """Price a would-be escalation through the WGL cost model: which
    engine `select_engine` would pick and its modeled element-ops.
    None when the history has no device form (host-only models price
    nothing — escalation still works, just unscaled)."""
    from . import wgl
    try:
        ops = wgl.encode_ops_for_model(model, hist)
        p = wgl.required_slots(ops)
        srange = wgl._state_range(model.device_model, model, [ops])
        dec = wgl.select_engine(srange, p, wgl.event_count(ops))
        return {"family": dec.family, "dedup": dec.dedup,
                "reason": dec.reason, "cost": wgl.engine_cost(dec)}
    except Exception:  # noqa: BLE001 — pricing is advisory
        return None


# ---------------------------------------------------------------------------
# The linearizable-model screen
# ---------------------------------------------------------------------------

class ScreenStream:
    """O(n) invariant screen over one linearizability target's ops.

    feed(op) with every history op in journal order (invokes and
    completions interleaved — that IS the real-time order the
    invariants quantify over); finish() returns the screen verdict.
    Host-only and model-shaped: works for models with no device form
    too. Usable as an OnlineChecker target (`violation` flips on the
    first definite violation, so --abort-on-violation works at tier
    1 without any device search)."""

    def __init__(self, model):
        self.model = model
        name = getattr(model, "device_model", None)
        self._kind = name if name in ("register", "cas-register",
                                      "counter", "g-set") else None
        self.violations: list[dict] = []
        self.violation = False
        self.soft = 0.0
        self.client_ops = 0
        self._crashed_mutators = 0
        self._t = 0                      # arrival clock
        self._t0: float | None = None
        # register/cas state. The model's initial value acts as a
        # write that completed at time 0 (before every client op):
        # reading it is legal until some real write completes, exactly
        # like any other value — so registers initialized to 0 by
        # their DB (models.cas_register(0)) screen correctly and a
        # read of the WRONG initial value is a phantom.
        init = getattr(model, "value", None) \
            if self._kind in ("register", "cas-register") else None
        self._seen: set = {init}         # values possibly written
        self._wpend: dict = {}           # value -> pending write count
        self._R: dict = {init: 0}        # value -> max completed-write t
        self._S = 0                      # max inv t among completed writes
        self._open: dict = {}            # process -> (inv_t, snapshot)
        # counter state
        self._init = 0
        if self._kind == "counter":
            try:
                self._init = int(model.device_state())
            except Exception:  # noqa: BLE001 — host-only counter models
                self._init = 0
        self._tpos = self._tneg = 0      # invoked add ranges
        self._d = self._dpos = self._dneg = 0   # completed adds
        # g-set state
        self._added: set = set()
        self._completed_adds: dict = {}  # element -> completion t

    def export_checkpoint(self) -> dict:
        """Screens are host-side and O(n): a recovering service
        re-feeds them from the journal, so the durable manifest only
        records progress (kind='host' = nothing to import)."""
        return {"kind": "host", "ops-fed": int(self.client_ops)}

    # -- feeding -----------------------------------------------------------

    def feed(self, op: dict) -> None:
        if not isinstance(op.get("process"), int):
            return
        self.client_ops += 1
        self._t += 1
        if self._t0 is None:
            self._t0 = _time.monotonic()
        t = op.get("type")
        if t == "invoke":
            self._invoke(op)
        elif t == "ok":
            self._complete(op)
        elif t == "info":
            self._info(op)
        elif t == "fail":
            self._open.pop(op.get("process"), None)

    def _flag(self, check: str, op: dict, **detail) -> None:
        self.violations.append({"check": check, "op": op, **detail})
        self.violation = True
        _M_VIOL.labels(check=check).inc()

    def _is_write(self, op) -> bool:
        return op.get("f") in ("write", "w", "cas", "add", "append",
                               "acquire", "release", "enqueue",
                               "dequeue", "txn")

    def _invoke(self, op: dict) -> None:
        k, f, v = self._kind, op.get("f"), op.get("value")
        snap: Any = None
        if k in ("register", "cas-register"):
            if f in ("write", "w"):
                self._seen.add(v)
                self._wpend[v] = self._wpend.get(v, 0) + 1
            elif f == "cas" and isinstance(v, (list, tuple)) \
                    and len(v) == 2:
                self._seen.add(v[1])
                self._wpend[v[1]] = self._wpend.get(v[1], 0) + 1
            snap = self._S            # reads AND cas observe at >= inv
        elif k == "counter":
            if f == "add" and v is not None:
                d = int(v)
                self._tpos += max(d, 0)
                self._tneg += min(d, 0)
            snap = (self._d, self._dpos, self._dneg)
        elif k == "g-set":
            if f == "add" and v is not None:
                self._added.add(v)
            snap = self._t            # compare completion times to this
        self._open[op["process"]] = (self._t, snap)

    def _complete(self, op: dict) -> None:
        k, f, v = self._kind, op.get("f"), op.get("value")
        inv = self._open.pop(op.get("process"), None)
        inv_t, snap = inv if inv is not None else (self._t, None)
        if k in ("register", "cas-register"):
            if f in ("write", "w"):
                self._write_done(v, inv_t)
            elif f == "cas" and isinstance(v, (list, tuple)) \
                    and len(v) == 2:
                # a successful cas observed v[0] and wrote v[1]
                self._read_check(op, v[0], snap)
                self._write_done(v[1], inv_t)
            elif f in ("read", "r"):
                self._read_check(op, v, snap)
        elif k == "counter":
            if f == "add" and v is not None:
                d = int(v)
                self._d += d
                self._dpos += max(d, 0)
                self._dneg += min(d, 0)
            elif f == "read" and v is not None and snap is not None:
                d0, dp0, dn0 = snap
                lo = self._init + d0 + (self._tneg - dn0)
                hi = self._init + d0 + (self._tpos - dp0)
                if not lo <= int(v) <= hi:
                    self._flag("counter-bounds", op, lo=lo, hi=hi)
        elif k == "g-set":
            if f == "add" and v is not None:
                self._completed_adds.setdefault(v, self._t)
            elif f == "read" and v is not None:
                got = set(v)
                phantom = got - self._added
                if phantom:
                    self._flag("set-phantom", op,
                               elements=sorted(phantom))
                if snap is not None:
                    lost = sorted(
                        el for el, ct in self._completed_adds.items()
                        if ct < inv_t and el not in got)
                    if lost:
                        self._flag("set-lost", op, elements=lost)

    def _write_done(self, v, inv_t: int) -> None:
        if self._wpend.get(v, 0) > 0:
            self._wpend[v] -= 1
        self._R[v] = self._t          # latest completion of a v-write
        self._S = max(self._S, inv_t)

    def _read_check(self, op: dict, v, s_at_inv) -> None:
        """The register read invariants, evaluated at completion time
        (so only writes invoked early enough to serve this read are in
        scope — see the module docstring for the soundness argument)."""
        if s_at_inv is None:
            return
        if v not in self._seen:
            # never written by any op and not the initial value
            self._flag("phantom-read", op, value=v)
            return
        if self._wpend.get(v, 0) > 0:
            return    # an in-flight write of v can still serve freshly
        r = self._R.get(v)
        if r is not None and s_at_inv > r:
            # some write w' was invoked after EVERY write of v had
            # completed (the initial value "completed" at time 0), and
            # w' itself completed before this read invoked: v cannot
            # be current
            self._flag("stale-read", op, value=v)

    def _info(self, op: dict) -> None:
        self._open.pop(op.get("process"), None)
        if self._is_write(op):
            self._crashed_mutators += 1
            self.soft = min(SOFT_CAP,
                            self.soft + SOFT_CRASH_WEIGHT)
        # register family: a crashed write may or may not have landed;
        # its value stays in _seen (added at invoke) and its pending
        # count stays up forever — both directions stay sound

    # -- finish ------------------------------------------------------------

    @property
    def suspicion(self) -> float:
        return len(self.violations) + self.soft

    def finish(self) -> dict:
        now = _time.monotonic()
        _M_SCREENED.labels(screen="linear").inc(self.client_ops)
        if self._t0 is not None:
            _M_SECONDS.labels(screen="linear").observe(now - self._t0)
        return {
            "screened": True,
            "analyzer": "tier1-screen",
            "valid?": not self.violations,
            "model": repr(self.model),
            # a model family with no invariant checks is NOT screened
            # clean — should_escalate always escalates it
            "screenable": self._kind is not None,
            "suspicion": self.suspicion,
            "violations": self.violations[:10],
            "violation-count": len(self.violations),
            "signals": {"crashed-mutators": self._crashed_mutators,
                        "model-kind": self._kind or "generic"},
            "op-count": self.client_ops,
            "history-len": self.client_ops,
            "duration-ms": ((now - self._t0) * 1e3
                            if self._t0 is not None else 0.0),
        }


def screen_history(model, hist) -> dict:
    """One-pass convenience: push a complete history through a
    ScreenStream (as the live journal feed would) and finish."""
    s = ScreenStream(model)
    for op in as_history(hist).ops:
        s.feed(op)
    return s.finish()


# ---------------------------------------------------------------------------
# The wr-transaction screen
# ---------------------------------------------------------------------------

class WrScreen:
    """Tier-1 screen for rw-register transaction histories.

    Rides WrStream's incremental edge/case accumulation (the same
    machinery the online Elle checker uses) but finishes with only the
    LINEAR-TIME work: the single-pass anomalies plus an SCC pass over
    the accumulated sparse edges for exact cycle existence — no dense
    blocks, no device classification, no certificates. Every Adya
    cycle anomaly (G0/G1c/G-single/G2-item and variants) implies a
    nontrivial SCC of these edges, so "screen passed" has no false
    negatives for the cycle classes; escalation buys the anomaly
    *classification* and human-readable certificates."""

    def __init__(self, anomalies=None):
        from .streaming import WrStream
        self._ws = WrStream(anomalies=anomalies)
        self.violation = False
        self._t0: float | None = None   # first feed, for pass_seconds

    def feed(self, op: dict) -> None:
        if self._t0 is None:
            self._t0 = _time.monotonic()
        self._ws.feed(op)
        if not self.violation and (
                self._ws._g1a or self._ws._g1b or self._ws._internal
                or self._ws._duplicates):
            self.violation = True

    def export_checkpoint(self) -> dict:
        """See ScreenStream.export_checkpoint: progress only."""
        return {"kind": "host",
                "ops-fed": int(self._ws.client_ops_fed)}

    @property
    def suspicion(self) -> float:
        """Live suspicion from the single-pass cases (the SCC cycle
        check only runs at finish — a mid-stream score can grow at
        finish, never shrink). The service's suspicion-priority
        scheduling reads this while the stream is still feeding."""
        ws = self._ws
        return float(len(ws._g1a) + len(ws._g1b)
                     + len(ws._internal) + len(ws._duplicates))

    def finish(self) -> dict:
        import numpy as np

        from .elle import kernels
        t0 = _time.monotonic()
        ws = self._ws
        violations: list[dict] = []
        for check, cases in (("G1a", ws._g1a), ("G1b", ws._g1b),
                             ("internal", ws._internal),
                             ("duplicate-writes", ws._duplicates)):
            if cases:
                violations.append({"check": check, "count": len(cases),
                                   "first": cases[0]})
        n = len(ws.txns)
        sccs = 0
        if ws._acc and n:
            src = np.fromiter((i for i, _ in ws._acc), np.int64,
                              count=len(ws._acc))
            dst = np.fromiter((j for _, j in ws._acc), np.int64,
                              count=len(ws._acc))
            labels = kernels.scc_labels(n, src, dst)
            sccs = int((np.bincount(labels, minlength=n) >= 2).sum())
            if sccs:
                violations.append({"check": "dependency-cycle",
                                   "sccs": sccs})
        if violations:
            self.violation = True
            for v in violations:
                _M_VIOL.labels(check=v["check"]).inc()
        _M_SCREENED.labels(screen="wr").inc(ws.client_ops_fed)
        # feed-to-finish, like the linear screen's series — the two
        # label values of one histogram must stay comparable
        _M_SECONDS.labels(screen="wr").observe(
            _time.monotonic() - (self._t0 if self._t0 is not None
                                 else t0))
        return {
            "screened": True,
            "analyzer": "tier1-screen-wr",
            "screenable": True,
            "valid?": not violations,
            "suspicion": float(len(violations)),
            "violations": violations,
            "violation-count": len(violations),
            "signals": {"txns": n, "edges": len(ws._acc),
                        "cyclic-sccs": sccs},
            "txn-count": n,
            "op-count": ws.client_ops_fed,
            "history-len": ws.client_ops_fed,
            "duration-ms": (_time.monotonic() - t0) * 1e3,
        }


def screen_wr(hist, anomalies=None) -> dict:
    """One-pass convenience for WrScreen."""
    s = WrScreen(anomalies=anomalies)
    for op in as_history(hist).ops:
        s.feed(op)
    return s.finish()


def escalation_record(screen: dict, why: str,
                      price: dict | None = None) -> dict:
    """The 'escalated' payload stamped onto a full-check result that
    tier 1 triggered — what the screen saw and what the cost model
    said, for Compose/report/web surfacing."""
    rec = {
        "why": why,
        "suspicion": screen.get("suspicion", 0.0),
        "violations": screen.get("violation-count",
                                 len(screen.get("violations", []))),
    }
    if price:
        rec["engine"] = price
    return rec
