"""TPU linearizability kernel: a JIT-linearization frontier search in XLA.

This replaces the reference's CPU-bound Knossos search (consumed via
`jepsen/src/jepsen/checker.clj:185-216`; `knossos.linear` / `knossos.wgl`),
which needs a 32 GB heap and "can take hours" on 10k-op histories. The
algorithm here is the same just-in-time linearization search, re-shaped for
a systolic/vector machine:

**Configurations are fixed-width.** A configuration is (model state: int32,
linearized-pending-ops bitmask: uint32[W]). Each in-flight operation holds a
*slot* in [0, P); slots are assigned host-side by scanning the history
(freed at completion, held forever by crashed :info ops), so the bitmask
width is bounded by real concurrency, not history length.

**The search is a frontier, not a stack.** The frontier is a dense array of
F configurations. We process history entries in order inside one
`lax.while_loop`:

  * *invoke*: the op occupies its slot. The frontier is closed under
    linearization (invariant), so only sequences beginning with the new op
    can add configurations: stage A linearizes just the new op against all
    F configs (one small sort to dedup); stage B repeatedly expands from
    freshly-added configs against all P pending slots (F*P candidates)
    until closure — in typical histories stage B's legality mask is empty
    and its sort never runs.
  * *complete*: every configuration must have linearized the op (its
    linearization point precedes its completion); survivors clear the bit
    and the slot is recycled.

Dedup is a multi-word lexicographic `lax.sort` + neighbor-equality mask;
stable sort with old-configs-first makes "new config" detection exact.
The history is linearizable iff any configuration survives every entry.
The event stream ships to the device as packed *steps* (see Steps):
runs of consecutive completions merge into the next invoke's step,
nearly halving the sequential depth of the device loop, and the whole
stream is one int32 matrix — one host->device transfer per check.

Soundness under resource caps: frontier overflow (> F live configs) only
*drops* candidate linearizations, so a 'valid' verdict is always sound; an
'invalid' verdict under overflow is reported as 'unknown' and escalated.
Slot overflow (> P concurrent+crashed pending ops) is detected host-side
before launch.

Batching: `vmap` over independent per-key histories;
`check_batch_sharded` shards the key axis over a `jax.sharding.Mesh` and
reduces verdicts with a psum-OR over ICI.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import heapq
import logging
import os
import time as _time
from typing import Callable

import numpy as np

from .. import calibrate as _calibrate, telemetry as _telemetry
from .._platform import (FAULT_COMPILE, FAULT_DEVICE_LOST,
                         FAULT_OOM, attest_enabled, backend_reinit,
                         classify_backend_error, guarded_device_get,
                         maybe_corrupt, maybe_inject_fault)
from ..history import (DeviceEncodingError, F_CAS, F_READ, F_WRITE,
                       KIND_OK, NIL, OpArray, default_register_codec,
                       encode_ops, history as as_history)

log = logging.getLogger(__name__)

# -- telemetry (doc/observability.md catalogs these) -------------------------
# Per-chunk latency by dispatch site; the streaming layer observes into
# the same family (site='stream') so one histogram covers every device
# chunk the pipeline runs.
_M_CHUNK = _telemetry.histogram(
    "jepsen_tpu_wgl_chunk_seconds",
    "Device chunk dispatch + lagged-sync latency",
    ("site", "family"))
_M_COMPILE = _telemetry.histogram(
    "jepsen_tpu_wgl_compile_seconds",
    "Kernel build (trace/cache miss) and warm-up compile latency",
    ("family", "stage"))
_M_ENGINE = _telemetry.counter(
    "jepsen_tpu_wgl_engine_decisions_total",
    "select_engine outcomes by family, dedup engine, and coarse reason",
    ("family", "dedup", "reason"))
_M_ELEMENTOPS = _telemetry.counter(
    "jepsen_tpu_wgl_modeled_elementops_total",
    "Modeled element-ops of the engines select_engine chose",
    ("family",))
_M_RUNGS = _telemetry.counter(
    "jepsen_tpu_wgl_recovery_rungs_total",
    "Recovery-ladder rung climbs by classified fault kind and site",
    ("kind", "site"))
_M_OPS = _telemetry.counter(
    "jepsen_tpu_wgl_checked_ops_total",
    "History ops decided by device-checking entries",
    ("site",))

# Event kinds (host-side stream construction)
E_INVOKE = 0
E_RETURN = 1


class SlotOverflow(Exception):
    """More concurrent+crashed pending ops than the kernel's P slots."""


# ---------------------------------------------------------------------------
# Device models: vectorized step semantics (mirrors models.device_step_*)
# ---------------------------------------------------------------------------

def _register_step(cas_enabled: bool):
    def step(state, f, a, b):
        import jax.numpy as jnp
        legal = (f == F_READ) & ((a == NIL) | (state == a))
        legal = legal | (f == F_WRITE)
        if cas_enabled:
            cas_ok = (f == F_CAS) & (state == a)
            legal = legal | cas_ok
            new = jnp.where(f == F_WRITE, a, jnp.where(cas_ok, b, state))
        else:
            new = jnp.where(f == F_WRITE, a, state)
        return legal, new
    return step


def _mutex_step(state, f, a, b):
    # f: 0 = acquire, 1 = release. Outputs broadcast over state x f.
    import jax.numpy as jnp
    state, f = jnp.broadcast_arrays(state, f)
    legal = ((f == 0) & (state == 0)) | ((f == 1) & (state == 1))
    new = jnp.where(f == 0, jnp.ones_like(state), jnp.zeros_like(state))
    return legal, new


def mutex_codec(o: dict) -> tuple[int, int, int]:
    f = o["f"]
    if f == "acquire":
        return 0, NIL, NIL
    if f == "release":
        return 1, NIL, NIL
    raise DeviceEncodingError(f"unknown mutex op f={f!r}")


# -- counter: f 0 = read(observed; b=1 iff constrained), 1 = add(delta) ------
# Counters reach negative values routinely, so an observed read of -1
# must NOT collide with the NIL sentinel: b carries an explicit
# "constrained" flag instead.

def _counter_step(state, f, a, b):
    import jax.numpy as jnp
    state, f, a, b = jnp.broadcast_arrays(state, f, a, b)
    legal = ((f == 0) & ((b == 0) | (state == a))) | (f == 1)
    new = jnp.where(f == 1, state + a, state)
    return legal, new


def counter_codec(o: dict) -> tuple[int, int, int]:
    f, v = o["f"], o["value"]
    if f == "read":
        if v is None:
            return 0, 0, 0
        return 0, int(v), 1
    if f == "add":
        return 1, int(v), NIL
    raise DeviceEncodingError(f"unknown counter op f={f!r}")


def _counter_range(init, f, a, b):
    f, a, b = np.asarray(f), np.asarray(a), np.asarray(b)
    deltas = a[f == 1]
    lo = init + int(deltas[deltas < 0].sum()) if deltas.size else init
    hi = init + int(deltas[deltas > 0].sum()) if deltas.size else init
    # completed reads also name reachable values (paranoia: they must
    # equal a state anyway); include them so invalid histories still
    # encode
    reads = a[(f == 0) & (b == 1)]
    if reads.size:
        lo = min(lo, int(reads.min()))
        hi = max(hi, int(reads.max()))
    return lo, hi


# -- grow-only set: f 0 = read(bitmask), 1 = add(element id) -----------------

GSET_MAX_ELEMENTS = 31   # state is an int32 membership bitmask


def _gset_step(state, f, a, b):
    import jax.numpy as jnp
    state, f, a = jnp.broadcast_arrays(state, f, a)
    legal = ((f == 0) & ((a == NIL) | (state == a))) | (f == 1)
    shift = jnp.clip(a, 0, GSET_MAX_ELEMENTS - 1)
    new = jnp.where(f == 1, state | (1 << shift), state)
    return legal, new


def gset_codec(o: dict) -> tuple[int, int, int]:
    f, v = o["f"], o["value"]
    if f == "add":
        v = int(v)
        if not 0 <= v < GSET_MAX_ELEMENTS:
            raise DeviceEncodingError(
                f"g-set element {v} outside [0, {GSET_MAX_ELEMENTS})"
                " — use the host model")
        return 1, v, NIL
    if f == "read":
        if v is None:
            return 0, NIL, NIL
        mask = 0
        for x in v:
            x = int(x)
            if not 0 <= x < GSET_MAX_ELEMENTS:
                raise DeviceEncodingError(
                    f"g-set element {x} outside "
                    f"[0, {GSET_MAX_ELEMENTS}) — use the host model")
            mask |= 1 << x
        return 0, mask, NIL
    raise DeviceEncodingError(f"unknown g-set op f={f!r}")


def _gset_range(init, f, a, b):
    f, a = np.asarray(f), np.asarray(a)
    full = int(init)
    for x in a[f == 1]:
        full |= 1 << int(x)
    for m in a[(f == 0) & (a != NIL)]:
        full |= int(m)
    return 0, full


# -- unordered queue: f 0 = dequeue(v), 1 = enqueue(v) -----------------------
# state: 4-bit per-value multiplicities, values in [0, 7)

from ..history import UQ_COUNT_MAX, UQ_VALUES  # noqa: E402 (shared
# with models.UnorderedQueue.device_state — one copy of the layout)


def _uqueue_step(state, f, a, b):
    import jax.numpy as jnp
    state, f, a = jnp.broadcast_arrays(state, f, a)
    shift = 4 * jnp.clip(a, 0, UQ_VALUES - 1)
    cnt = (state >> shift) & UQ_COUNT_MAX
    ok_a = (a >= 0) & (a < UQ_VALUES)
    legal = jnp.where(f == 1, ok_a & (cnt < UQ_COUNT_MAX),
                      ok_a & (cnt > 0))
    new = jnp.where(legal & (f == 1), state + (1 << shift),
                    jnp.where(legal & (f == 0),
                              state - (1 << shift), state))
    return legal, new


def _uqueue_validate(ops: OpArray, model) -> None:
    """A sound upper bound on any reachable per-value multiplicity:
    initial copies plus enqueues invoked so far, minus ok dequeues
    returned so far, maxed over the event stream. If it can exceed
    the 4-bit digit cap the device multiset would silently saturate
    (carrying into the next value's digit) — raise so the checker
    falls back to the host model."""
    events: list[tuple[int, int, int]] = []
    for r in range(len(ops)):
        v = int(ops.a[r])
        if ops.f[r] == 1:                       # enqueue (incl. crashed)
            events.append((int(ops.inv[r]), 0, v))
        elif ops.kind[r] == KIND_OK:            # ok dequeue
            events.append((int(ops.ret[r]), 1, v))
    events.sort()
    outstanding = [0] * UQ_VALUES
    for (v, _i) in getattr(model, "pending", ()):
        v = int(v)
        if not 0 <= v < UQ_VALUES:
            raise DeviceEncodingError(
                f"initial queue value {v} outside [0, {UQ_VALUES}) — "
                "use the host model")
        outstanding[v] += 1
        if outstanding[v] > UQ_COUNT_MAX:
            raise DeviceEncodingError(
                f"initial queue state has more than {UQ_COUNT_MAX} "
                f"copies of {v} — use the host model")
    for _, kind, v in events:
        if kind == 0:
            outstanding[v] += 1
            if outstanding[v] > UQ_COUNT_MAX:
                raise DeviceEncodingError(
                    f"queue value {v} may have more than "
                    f"{UQ_COUNT_MAX} outstanding copies — the device "
                    "multiset digit would saturate; use the host model")
        else:
            outstanding[v] -= 1


def uqueue_codec(o: dict) -> tuple[int, int, int]:
    f, v = o["f"], o["value"]
    if v is None:
        raise DeviceEncodingError(
            "queue op with unknown value (crashed dequeue?) — the "
            "device multiset can't branch over it; use the host model")
    v = int(v)
    if not 0 <= v < UQ_VALUES:
        raise DeviceEncodingError(
            f"queue value {v} outside [0, {UQ_VALUES}) — use the "
            "host model")
    if f == "enqueue":
        return 1, v, NIL
    if f == "dequeue":
        return 0, v, NIL
    raise DeviceEncodingError(f"unknown queue op f={f!r}")


@dataclasses.dataclass(frozen=True)
class DeviceModel:
    """A model with enumerable int32 state, steppable on device.

    step        (state, f, a, b) -> (legal, new_state), broadcasting
    codec       op dict -> (f, a, b) int encoding
    droppable   f-codes whose pending (crashed) ops constrain nothing
    state_range (init_state, f, a, b arrays) -> inclusive (lo, hi)
                bounds on every reachable state — lets the kernel pack
                a whole config into one u32 sort key when it fits
    """
    step: Callable
    codec: Callable
    droppable: frozenset
    state_range: Callable
    validate: Callable | None = None  # (OpArray, model) -> None | raise

    def __iter__(self):  # legacy tuple shape: (step, codec, droppable)
        return iter((self.step, self.codec, self.droppable))


def _register_range(init, f, a, b):
    a, b = np.asarray(a), np.asarray(b)
    hi, lo = init, min(NIL, init)
    for v in (a[a != NIL], b[b != NIL]):
        if v.size:
            hi = max(hi, int(v.max()))
            lo = min(lo, int(v.min()))
    return lo, hi


DEVICE_MODELS: dict[str, DeviceModel] = {
    "cas-register": DeviceModel(_register_step(True),
                                default_register_codec,
                                frozenset({F_READ}), _register_range),
    "register": DeviceModel(_register_step(False), default_register_codec,
                            frozenset({F_READ}), _register_range),
    "mutex": DeviceModel(_mutex_step, mutex_codec, frozenset(),
                         lambda init, f, a, b: (0, 1)),
    # crashed (pending) reads constrain nothing for counter/g-set and
    # are droppable; queue dequeues are never droppable
    "counter": DeviceModel(_counter_step, counter_codec,
                           frozenset({0}), _counter_range),
    "g-set": DeviceModel(_gset_step, gset_codec,
                         frozenset({0}), _gset_range),
    "unordered-queue": DeviceModel(
        _uqueue_step, uqueue_codec, frozenset(),
        lambda init, f, a, b: (0, (1 << (4 * UQ_VALUES)) - 1),
        validate=_uqueue_validate),
}


# ---------------------------------------------------------------------------
# Host preprocessing: ops -> packed event steps with slot assignment
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Steps:
    """The kernels' input: the history as packed event steps.

    One int32 row of ``x`` per step: ``[ret_mask words (W) | inv_slot |
    f | a | b]``. A step first *completes* every slot in ret_mask, then
    — when inv_slot >= 0 — *invokes* (inv_slot, f, a, b). Merged
    streams (build_steps merge=True) fold each run of consecutive :ok
    completions into the following invoke's step: completions commute
    (clearing distinct bits is injective and preserves frontier
    closure) and configurations cannot change between adjacent events,
    so the merged stream decides exactly the same verdict while nearly
    halving the sequential depth of the device loop. Unmerged streams
    carry one event per step, so the step where the frontier died
    names a single culprit op (used to re-derive blame for invalid
    verdicts). The whole stream is one matrix so a checker call costs
    one host->device transfer, not five.

    ret_row  int32[T] — op row of the step's sole completion (-1 if
             none, or ambiguous because several were merged)
    inv_row  int32[T] — op row of the step's invoke (-1 if none)
    """
    x: np.ndarray        # (T, W+4) int32
    ret_row: np.ndarray
    inv_row: np.ndarray
    w: int
    n: int               # live steps (<= T)

    def pad_to(self, t: int) -> "Steps":
        if len(self.x) == t:
            return self
        assert len(self.x) <= t, "cannot shrink steps"
        m = t - len(self.x)
        pad = np.zeros((m, self.w + 4), np.int32)
        pad[:, self.w] = -1      # no invoke
        pad[:, self.w + 2:] = NIL
        neg = np.full(m, -1, np.int32)
        return Steps(np.concatenate([self.x, pad]),
                     np.concatenate([self.ret_row, neg]),
                     np.concatenate([self.inv_row, neg]), self.w, self.n)

    @classmethod
    def empty(cls, w: int, t: int = 0) -> "Steps":
        z = np.zeros((0, w + 4), np.int32)
        zn = np.zeros(0, np.int32)
        return cls(z, zn, zn, w, 0).pad_to(t)


def event_count(ops: OpArray) -> int:
    """Length of the unmerged event stream (invokes + ok returns) —
    the T capacity that lets merged and unmerged streams share one
    compiled kernel."""
    return len(ops) + int((np.asarray(ops.kind) == KIND_OK).sum())


def required_slots(ops: OpArray) -> int:
    """The peak number of simultaneously-pending ops (crashed ops pend
    forever) — the minimum slot count the kernel needs. Computing it up
    front avoids SlotOverflow escalation recompiles."""
    # same (position, order) tie-break as build_steps: invokes sort
    # before returns at equal positions
    events = []
    for r in range(len(ops)):
        events.append((int(ops.inv[r]), 0, 1))
        if ops.kind[r] == KIND_OK:
            events.append((int(ops.ret[r]), 1, -1))
    events.sort()
    cur = peak = 0
    for _, _, d in events:
        cur += d
        peak = max(peak, cur)
    return max(peak, 1)


def build_steps(ops: OpArray, p: int, merge: bool = True) -> Steps:
    """Lower an OpArray to packed event steps, assigning each op a slot
    in [0, p). Raises SlotOverflow if concurrency + crashed ops exceed
    p."""
    events = []  # (position, order, kind, row)
    for r in range(len(ops)):
        events.append((int(ops.inv[r]), 0, E_INVOKE, r))
        if ops.kind[r] == KIND_OK:
            events.append((int(ops.ret[r]), 1, E_RETURN, r))
    events.sort()
    w = max(1, (p + 31) // 32)
    free = list(range(p))
    heapq.heapify(free)
    slot_of_row: dict[int, int] = {}
    masks: list[list[int]] = []
    rest: list[tuple[int, int, int, int]] = []
    ret_row: list[int] = []
    inv_row: list[int] = []
    pend = [0] * w
    pend_rows: list[int] = []

    def flush(inv_slot: int, f: int, a: int, b: int, row: int) -> None:
        nonlocal pend, pend_rows
        masks.append(pend)
        rest.append((inv_slot, f, a, b))
        ret_row.append(pend_rows[0] if len(pend_rows) == 1 else -1)
        inv_row.append(row)
        pend = [0] * w
        pend_rows = []

    for _, _, k, r in events:
        if k == E_INVOKE:
            if not free:
                raise SlotOverflow(
                    f"more than {p} pending ops at op row {r} "
                    f"(crashed ops hold slots forever); raise p or check "
                    f"on the host")
            s = heapq.heappop(free)
            slot_of_row[r] = s
            flush(s, int(ops.f[r]), int(ops.a[r]), int(ops.b[r]), r)
        else:
            s = slot_of_row.pop(r)
            heapq.heappush(free, s)
            pend[s // 32] |= 1 << (s % 32)
            pend_rows.append(r)
            if not merge:
                flush(-1, 0, NIL, NIL, -1)
    if any(pend):
        flush(-1, 0, NIL, NIL, -1)
    n = len(masks)
    mask_arr = np.asarray(masks, np.uint32).reshape(n, w)
    rest_arr = np.asarray(rest, np.int32).reshape(n, 4)
    return Steps(np.concatenate([mask_arr.view(np.int32), rest_arr],
                                axis=1),
                 np.asarray(ret_row, np.int32),
                 np.asarray(inv_row, np.int32), w, n)


def _bucket(n: int, lo: int = 64) -> int:
    """Round up to a power of two to bound jit recompiles."""
    e = lo
    while e < n:
        e *= 2
    return e


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------

Kernel = collections.namedtuple(
    "Kernel", ["check", "check_batch", "check_chunk", "check_chunk_batch",
               "check_stream_chunk", "init_carry", "summarize", "digest"])


def _mk_digest():
    """Build the jitted carry digest: xor-fold of (component wrap-sum *
    prime_i) over the carry elements in order — the host mirror is
    abft.carry_digest_host, which must stay in lockstep. Verified at
    the chunk boundaries where the carry is fetched anyway (stream
    checkpoints, offline summarize): a mismatch means the carry
    changed between the device's reduction and the fetch."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import abft

    i32 = jnp.int32

    @jax.jit
    def digest(carry):
        h = i32(0)
        for i, c in enumerate(carry):
            c = jnp.asarray(c)
            if c.dtype == jnp.uint32:
                ci = lax.bitcast_convert_type(c, i32)
            else:
                ci = c.astype(i32)
            h = h ^ (jnp.sum(ci, dtype=i32) * i32(abft.prime_i32(i)))
        return h

    return digest


def _pack_params(state_range: tuple[int, int] | None,
                 P: int) -> tuple[int, int] | None:
    """Normalize a state range to the (s_lo, sb_bits) the kernel is
    actually specialized on — or None when packing is impossible — so
    histories differing only in irrelevant value ranges share one
    compiled kernel."""
    if state_range is None or P > 32:
        return None
    s_lo = state_range[0]
    sb_bits = (state_range[1] - state_range[0] + 1).bit_length()
    if P + sb_bits + 1 > 32:
        return None
    return s_lo, sb_bits


def _pallas_enabled(env_var: str, override=None) -> tuple[bool, bool]:
    """Resolve a pallas opt-in/out to (use_pallas, on_tpu): an explicit
    checker option beats the env gate beats the backend default (ON for
    real TPU, interpret-mode opt-in elsewhere). Resolved OUTSIDE the
    kernel caches so flipping the env (or passing pallas=) mid-process
    takes effect on the next call."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if override is not None:
        return bool(override), on_tpu
    flag = os.environ.get(env_var)
    return (flag == "1" or (flag != "0" and on_tpu)), on_tpu


# dedup-engine names (reported in analyses and bench artifacts)
DEDUP_PALLAS = "pallas-hash"
DEDUP_SORT = "xla-sort"
DEDUP_NONE = "dense-table"   # the dense family has no dedup at all


def _hash_gate(F: int, P: int, pack: tuple[int, int] | None,
               on_tpu: bool) -> bool:
    """The ONE gate for the Pallas hash dedup: single-u32 packed
    config (pack resolved, one mask word), the hash working set in
    VMEM, and — on a real TPU — a passing one-time Mosaic compile
    probe (interpret mode is pure JAX and needs none). Shared by the
    kernel build (_kernel_cached) and every reporting site
    (dedup_engine), so the 'dedup' stamped in analyses can never
    drift from the engine the kernel actually ran."""
    if pack is None or (P + 31) // 32 > 1:
        return False
    from . import wgl_dedup
    if not wgl_dedup.eligible(F, P):
        return False
    return wgl_dedup.compiles() if on_tpu else True


def dedup_engine(F: int, P: int, pack: tuple[int, int] | None,
                 pallas=None) -> str:
    """Which dedup the sort-family kernel would run at this shape —
    shapes failing _hash_gate keep the lexicographic sort."""
    use, on_tpu = _pallas_enabled("JEPSEN_TPU_PALLAS_DEDUP", pallas)
    return DEDUP_PALLAS if use and _hash_gate(F, P, pack, on_tpu) \
        else DEDUP_SORT


def _kernel(model_name: str, F: int, P: int, E: int,
            pack: tuple[int, int] | None = None, pallas=None):
    """Build (or fetch) the jitted sort-family checker. The
    Pallas-vs-XLA dedup choice is resolved HERE, outside the cache, so
    flipping JEPSEN_TPU_PALLAS_DEDUP (or a checker's pallas= option)
    mid-process takes effect on the next call instead of being baked
    into a cached kernel — the same contract as _dense_kernel."""
    use_dedup, on_tpu = _pallas_enabled("JEPSEN_TPU_PALLAS_DEDUP",
                                        pallas)
    return _kernel_cached(model_name, F, P, E, pack, use_dedup, on_tpu,
                          attest_enabled())


def _clear_sort_caches():
    """Reset every cache that baked in a sort-kernel build decision
    (tests reach through the _kernel wrapper for this)."""
    _kernel_cached.cache_clear()
    _sharded_runner_cached.cache_clear()


_kernel.cache_clear = _clear_sort_caches


@functools.lru_cache(maxsize=32)
def _kernel_cached(model_name: str, F: int, P: int, E: int,
                   pack: tuple[int, int] | None,
                   use_dedup: bool, on_tpu: bool,
                   use_attest: bool = True):
    """Build the jitted checker for a (model, frontier-size, slots,
    entry-capacity) shape. Returns fn(entry arrays..., n_entries) ->
    (ok, death_entry, overflow, max_frontier).

    use_attest: accumulate ABFT self-check residues in the carry's
    ``att`` element (see the attestation comment on init_carry) —
    resolved from JEPSEN_TPU_ATTEST outside the cache like the pallas
    gates. The att element is ALWAYS present (uniform carry shape for
    checkpoints either way); only the accumulation is gated.

    pack: (s_lo, sb_bits) from _pack_params. When the whole config
    (invalid flag, biased state, P-bit pending mask) fits one uint32,
    dedup packs it into a single sort key; the multi-word
    lexicographic sort is the kernel's dominant cost, so this is the
    difference between sorting one u32 lane and W+2 lanes per entry.

    use_dedup: with a packed config, route the dedup through the
    Pallas open-addressing hash kernel (checker/wgl_dedup.py) instead
    of the sort — same frontier *set* in first-seen order instead of
    key order, so verdicts/summaries/blame are identical (the
    downstream phases are order-invariant). Shapes the hash gate
    rejects keep the sort."""
    # build-latency telemetry lives INSIDE the cached body: lru_cache
    # only runs it on a miss, so every observed sample is a real build
    # (a cache_info().misses delta around the call races under the
    # service's concurrent streams and would record warm hits)
    t_build = _time.monotonic()
    import jax
    import jax.numpy as jnp
    from jax import lax

    step = DEVICE_MODELS[model_name].step
    W = max(1, (P + 31) // 32)
    u32 = jnp.uint32
    i32 = jnp.int32
    if pack is not None:
        s_lo, sb_bits = pack
    else:
        s_lo, sb_bits = 0, 64
    packed = pack is not None and W == 1

    # Pallas hash dedup (the sort-free frontier): _hash_gate is sized
    # for the kernel's LARGEST dedup call (stage B's F*(1+P)
    # candidates) so one kernel never mixes dedup engines.
    hash_dedup = None
    if use_dedup and _hash_gate(F, P, pack, on_tpu):
        from . import wgl_dedup
        hash_dedup = functools.partial(
            wgl_dedup.dedup_fn, F=F, interpret=not on_tpu)

    # per-slot bit-vector table, shared by the completion phase and the
    # expansion stage
    _bits = np.zeros((P, W), np.uint32)
    for _p in range(P):
        _bits[_p, _p // 32] = np.uint32(1) << (_p % 32)
    BITMAT = jnp.asarray(_bits)

    def has_bit(masks, bv):
        return (masks & bv[None, :]).astype(jnp.bool_).any(axis=1)

    def _neq_prev(x):
        return jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), x[1:] != x[:-1]])

    def dedup_packed(masks, states, valid, origin):
        """Single-key dedup: key = invalid<<31 | (state-lo)<<P | mask."""
        key = jnp.where(valid, u32(0), u32(1) << 31) \
            | ((states - s_lo).astype(u32) << P) | masks[:, 0]
        key_s, org_s = lax.sort([key, origin.astype(i32)], num_keys=1,
                                is_stable=True)
        valid_s = (key_s >> 31 == 0) & _neq_prev(key_s)
        overflow = valid_s[F:].any() if len(key) > F else jnp.bool_(False)
        masks_f = (key_s[:F] & u32((1 << P) - 1))[:, None]
        states_f = ((key_s[:F] >> P) & u32((1 << sb_bits) - 1)) \
            .astype(i32) + s_lo
        valid_f = valid_s[:F]
        new_f = valid_f & (org_s[:F] == 1)
        return masks_f, states_f, valid_f, new_f, valid_f.sum(), \
            overflow, i32(0)

    def dedup_hash(masks, states, valid):
        """Sort-free dedup: the packed 31-bit config key goes through
        the Pallas open-addressing hash kernel (wgl_dedup), which
        returns the distinct valid keys compacted in first-seen order
        plus per-slot new flags. Old configs occupy input rows [0, F)
        at both call sites, so first-seen-wins is exactly the stable
        sort's old-configs-first rule and `new` needs no origin lane.
        The frontier is set-equal to the sort path's — downstream is
        order-invariant, so verdicts/summaries/blame are identical.

        ABFT: the pallas kernel also emits its table-occupancy XOR
        digest (xor of claimed keys ^ count mix). When the distinct
        count fits the frontier the same value is recomputed here from
        the compacted OUTPUT (a different store path), and any
        disagreement — a flipped VMEM word, a dropped or
        double-claimed key — is returned as `mism` for the caller's
        att accumulator."""
        key = jnp.where(
            valid,
            ((states - s_lo) << P) | masks[:, 0].astype(i32),
            i32(-1))
        out_keys, new_f, distinct, kdig = hash_dedup(len(key))(key)
        valid_f = out_keys >= 0
        safe = jnp.where(valid_f, out_keys, 0)
        masks_f = (safe & ((1 << P) - 1)).astype(u32)[:, None]
        states_f = (safe >> P) + s_lo
        if use_attest:
            from .wgl_dedup import DIGEST_COUNT_MIX
            exp = lax.reduce(jnp.where(valid_f, safe, 0), i32(0),
                             lax.bitwise_xor, (0,))
            exp = exp ^ (distinct * i32(DIGEST_COUNT_MIX))
            mism = ((exp != kdig) & (distinct <= F)).astype(i32)
        else:
            mism = i32(0)
        return masks_f, states_f, valid_f, new_f & valid_f, \
            valid_f.sum(), distinct > F, mism

    def dedup(masks, states, valid, origin):
        """Sort (N,)-rows lexicographically by (invalid, mask words, state);
        mark duplicate keys invalid (stable sort + old-configs-first makes
        the original config win); truncate to F.

        Returns (masks[F,W], states[F], valid[F], new[F], count,
        overflow, mism) — mism is the hash path's digest-mismatch flag
        (always 0 for the sort variants, whose output IS the sorted
        input: there is no second store path to cross-check).
        """
        if hash_dedup is not None:
            return dedup_hash(masks, states, valid)
        if packed:
            return dedup_packed(masks, states, valid, origin)
        invalid_key = (~valid).astype(u32)
        operands = [invalid_key] + [masks[:, w] for w in range(W)] \
            + [states, origin.astype(i32)]
        out = lax.sort(operands, num_keys=W + 2, is_stable=True)
        inv_s, ms, st_s, org_s = out[0], out[1:1 + W], out[1 + W], out[2 + W]

        first = _neq_prev(inv_s) | _neq_prev(st_s)
        for mw in ms:
            first = first | _neq_prev(mw)
        valid_s = (inv_s == 0) & first
        overflow = valid_s[F:].any() if len(inv_s) > F else jnp.bool_(False)
        masks_f = jnp.stack([mw[:F] for mw in ms], axis=1)
        states_f = st_s[:F]
        valid_f = valid_s[:F]
        new_f = valid_f & (org_s[:F] == 1)
        return masks_f, states_f, valid_f, new_f, valid_f.sum(), \
            overflow, i32(0)

    def expand_full(masks, states, valid, new, slot_f, slot_a, slot_b,
                    slot_occ, overflow, att):
        """Stage B: close the frontier under linearization, expanding only
        from freshly-added configs each round."""

        def cond(c):
            return c[3].any() & ~c[6]  # any new configs & not converged

        def body(c):
            masks, states, valid, new, overflow, att, _ = c
            # candidates: new configs x all pending slots
            legal, cstate = step(states[:, None], slot_f[None, :],
                                 slot_a[None, :], slot_b[None, :])
            already = (masks[:, None, :] & BITMAT[None, :, :]) \
                .astype(jnp.bool_).any(-1)                         # (F,P)
            legal = legal & valid[:, None] & new[:, None] \
                & slot_occ[None, :] & ~already
            any_legal = legal.any()

            def do_sort(_):
                cmasks = (masks[:, None, :] | BITMAT[None, :, :]) \
                    .reshape(F * P, W)
                cstates = cstate.reshape(F * P)
                cvalid = legal.reshape(F * P)
                all_masks = jnp.concatenate([masks, cmasks])
                all_states = jnp.concatenate([states, cstates])
                all_valid = jnp.concatenate([valid, cvalid])
                origin = jnp.concatenate(
                    [jnp.zeros(F, jnp.bool_), jnp.ones(F * P, jnp.bool_)])
                m2, s2, v2, n2, cnt2, ovf2, mism = dedup(
                    all_masks, all_states, all_valid, origin)
                grew = n2.any()
                return m2, s2, v2, n2, overflow | ovf2, att + mism, \
                    ~grew

            def no_sort(_):
                # Derive constants from varying operands so both cond
                # branches carry the same manual-axes tags under shard_map.
                return masks, states, valid, \
                    valid & False, overflow, att, any_legal | True

            return lax.cond(any_legal, do_sort, no_sort, None)

        masks, states, valid, new, overflow, att, _ = lax.while_loop(
            cond, body, (masks, states, valid, new, overflow, att,
                         jnp.bool_(False)))
        return masks, states, valid, overflow, att

    def init_carry(init_state):
        # carry layout: (e, masks, states, valid, slot_f, slot_a,
        # slot_b, slot_occ, overflow, att, count, max_count). att is
        # the ABFT attestation accumulator — in-loop invariant
        # residues (valid configs holding bits of unoccupied slots,
        # hash-dedup digest mismatches) sum into it and it must read 0
        # on host at every chunk boundary (abft.verify_carry); the
        # element is present even with attestation off so carry
        # checkpoints keep one shape.
        masks0 = jnp.zeros((F, W), u32)
        states0 = jnp.full((F,), init_state, i32)
        valid0 = jnp.zeros((F,), jnp.bool_).at[0].set(True)
        return (i32(0), masks0, states0, valid0,
                jnp.zeros((P,), i32), jnp.full((P,), NIL, i32),
                jnp.full((P,), NIL, i32), jnp.zeros((P,), jnp.bool_),
                jnp.bool_(False), i32(0), i32(1), i32(1))

    def summarize(carry):
        # att rides along as the 5th output so EVERY verdict fetch —
        # fused single-call, batch, sharded, stream liveness/finish —
        # sees the in-kernel attestation accumulator, not only the
        # boundaries that fetch the whole carry (_check_att raises
        # on a nonzero value at each consumer)
        (e, _m, _s, _valid, *_slots, overflow, att, count,
         max_count) = carry
        ok = count > 0
        death = jnp.where(ok, i32(-1), e - 1)
        return ok, death, overflow, max_count, att

    def run_range(x, stop, carry):
        """Advance the search from carry's position up to step `stop`
        (or until the frontier dies). Bounded-duration device work: long
        histories run as a sequence of these calls with the frontier
        carried between them — which is also the checkpoint for
        long searches (the carry round-trips through host memory)."""
        def invoke_phase(s, f, a, b, args):
            masks, states, valid, slot_f, slot_a, slot_b, slot_occ, \
                overflow, att = args
            slot_f = slot_f.at[s].set(f)
            slot_a = slot_a.at[s].set(a)
            slot_b = slot_b.at[s].set(b)
            slot_occ = slot_occ.at[s].set(True)
            # stage A: linearize just the new op
            legal, nstate = step(states, f, a, b)
            bv = BITMAT[s]
            cvalid = valid & legal & ~has_bit(masks, bv)
            all_masks = jnp.concatenate([masks, masks | bv[None, :]])
            all_states = jnp.concatenate([states, nstate])
            all_valid = jnp.concatenate([valid, cvalid])
            origin = jnp.concatenate(
                [jnp.zeros(F, jnp.bool_), jnp.ones(F, jnp.bool_)])
            masks, states, valid, new, _, ovf, mism = dedup(
                all_masks, all_states, all_valid, origin)
            overflow = overflow | ovf
            att = att + mism
            # stage B: chase enabled chains
            masks, states, valid, overflow, att = expand_full(
                masks, states, valid, new, slot_f, slot_a, slot_b,
                slot_occ, overflow, att)
            return masks, states, valid, slot_f, slot_a, slot_b, \
                slot_occ, overflow, att

        def cond(c):
            return (c[0] < stop) & (c[10] > 0)

        def body(c):
            (e, masks, states, valid, slot_f, slot_a, slot_b, slot_occ,
             overflow, att, count, max_count) = c
            row = x[e]
            rm = lax.bitcast_convert_type(row[:W], u32)        # (W,)
            s, f, a, b = row[W], row[W + 1], row[W + 2], row[W + 3]
            # completion phase: survivors linearized every returned op.
            # No dedup needed: clearing set bits is injective on masks,
            # so distinct surviving configs stay distinct; closure is
            # preserved, so no re-expansion either. rm == 0 is a no-op.
            have = ((masks & rm[None, :]) == rm[None, :]).all(axis=1)
            valid = valid & have
            masks = masks & ~rm[None, :]
            slot_occ = slot_occ & ~(BITMAT & rm[None, :]) \
                .astype(jnp.bool_).any(axis=1)
            (masks, states, valid, slot_f, slot_a, slot_b, slot_occ,
             overflow, att) = lax.cond(
                s >= 0,
                lambda args: invoke_phase(s, f, a, b, args),
                lambda args: args,
                (masks, states, valid, slot_f, slot_a, slot_b, slot_occ,
                 overflow, att))
            if use_attest:
                # ABFT frontier invariant: a valid configuration may
                # only hold pending bits of OCCUPIED slots (completion
                # clears freed slots from every mask; invoke occupies
                # before setting). A bit-flip in masks/valid/slot_occ
                # violates this with high probability; residues sum
                # into att and are checked host-side at chunk
                # boundaries. Cost: one (F, W) mask op per step.
                occw = jnp.sum(
                    jnp.where(slot_occ[:, None], BITMAT,
                              jnp.zeros_like(BITMAT)), axis=0)   # (W,)
                bad = valid & ((masks & ~occw[None, :]) != 0).any(axis=1)
                att = att + bad.sum().astype(i32)
            count = valid.sum().astype(i32)
            return (e + 1, masks, states, valid, slot_f, slot_a, slot_b,
                    slot_occ, overflow, att, count,
                    jnp.maximum(max_count, count))

        return lax.while_loop(cond, body, carry)

    def make_check(x, n_steps, init_state):
        return summarize(run_range(x, n_steps, init_carry(init_state)))

    @jax.jit
    def check(x, n_steps, init_state):
        return make_check(x, n_steps, init_state)

    @jax.jit
    def check_batch(x, n_steps, init_state):
        return jax.vmap(make_check)(x, n_steps, init_state)

    @jax.jit
    def check_chunk(x, stop, carry):
        return run_range(x, stop, carry)

    @jax.jit
    def check_chunk_batch(x, stops, carry):
        return jax.vmap(run_range)(x, stops, carry)

    @jax.jit
    def check_stream_chunk(x, n, carry):
        # Streaming entry: x holds only THIS chunk's steps, so the
        # carry's absolute event count is rebased to 0 for the range
        # walk and restored afterwards — a growing history streams as
        # fixed-shape chunks through ONE compiled kernel, shipping each
        # step exactly once (the whole-x chunk API re-ships the prefix).
        local = (i32(0),) + tuple(carry[1:])
        out = run_range(x, n, local)
        return (out[0] + carry[0],) + tuple(out[1:])

    k = Kernel(check, check_batch, check_chunk, check_chunk_batch,
               check_stream_chunk, init_carry, summarize,
               _mk_digest())
    _M_COMPILE.labels(family="sort", stage="build").observe(
        _time.monotonic() - t_build)
    return k


# ---------------------------------------------------------------------------
# Dense reachable-set kernel (symbolic model checking on device)
# ---------------------------------------------------------------------------
#
# When the model's state count S and the slot count P are small enough
# that S * 2^P fits in device memory, the *entire* configuration space
# fits a dense boolean table T[state, pending-mask]. Every history entry
# is then a vectorized transform of the whole table:
#
#   * linearizing pending op p from (s, m) reaches (step(s), m | bit_p):
#     a tiny SxS boolean "transition matmul" over the state axis composed
#     with a bit-set gather along the mask axis — for ALL P pending slots
#     at once, as one batched (P, S, C) op, iterated to fixpoint;
#   * an :ok return keeps configs holding the op's bit and clears it —
#     a pure gather;
#   * the history is linearizable iff the table is ever nonempty after
#     the last entry.
#
# No sort, no frontier cap, no overflow, no escalation: verdicts are
# EXACT. The sort-frontier kernel above remains the fallback for
# histories whose peak pending-op count P makes 2^P infeasible. This is
# the idiomatic TPU shape for WGL search: the pending-subset powerset
# that explodes knossos (`checker.clj:213-216`) becomes the lane axis.

DENSE_TABLE_CAP = 1 << 22   # max S * 2^P bools held as the dense table


def _dense_kernel(model_name: str, s_lo: int, S: int, P: int, E: int,
                  pallas=None):
    """Build the jitted dense-table checker for S states x P slots x
    E entry capacity. Same call shapes as the sort kernel.

    The Pallas-vs-XLA closure choice is resolved HERE, outside the
    cache, so flipping JEPSEN_TPU_PALLAS_CLOSURE (or a checker's
    pallas= option) mid-process takes effect on the next call instead
    of being baked into a cached kernel."""
    use_pallas, on_tpu = _pallas_enabled("JEPSEN_TPU_PALLAS_CLOSURE",
                                         pallas)
    return _dense_kernel_cached(model_name, s_lo, S, P, E,
                                use_pallas, on_tpu, attest_enabled())


def _clear_dense_caches():
    """Reset every cache that baked in a dense-kernel build decision
    (tests reach through the _dense_kernel wrapper for this)."""
    _dense_kernel_cached.cache_clear()
    _sharded_runner_cached.cache_clear()


_dense_kernel.cache_clear = _clear_dense_caches


@functools.lru_cache(maxsize=32)
def _dense_kernel_cached(model_name: str, s_lo: int, S: int, P: int,
                         E: int, use_pallas: bool, on_tpu: bool,
                         use_attest: bool = True):
    # miss-only build timing — see the sort kernel's twin comment
    t_build = _time.monotonic()
    import jax
    import jax.numpy as jnp
    from jax import lax

    step = DEVICE_MODELS[model_name].step
    C = 1 << P
    i32 = jnp.int32
    f32 = jnp.float32
    s_vals = s_lo + np.arange(S, dtype=np.int32)           # (S,)
    cols = np.arange(C, dtype=np.int32)                    # (C,)

    S_VALS = jnp.asarray(s_vals)
    COLS = jnp.asarray(cols)
    ARANGE_P = jnp.arange(P)

    # Pallas fused closure round: ON by default on real TPU hardware
    # (2x on the easy 10k headline, 6x on the adversarial P=14 shape —
    # the (P, S, C) intermediates never leave VMEM), opt-in elsewhere
    # (interpret mode keeps it testable on CPU), opt-out via
    # JEPSEN_TPU_PALLAS_CLOSURE=0 (resolved by the _dense_kernel
    # wrapper). Shapes past the VMEM gate fall back to the XLA
    # formulation below.
    pallas_round = None
    if use_pallas:
        from . import wgl_pallas
        if wgl_pallas.eligible(S, P):
            pallas_round = wgl_pallas.closure_round_fn(
                S, P, interpret=not on_tpu)

    def closure(table, slot_f, slot_a, slot_b, slot_occ):
        """Close the table under linearization of every occupied slot."""
        legal, new = step(S_VALS[None, :], slot_f[:, None],
                          slot_a[:, None], slot_b[:, None])     # (P, S)
        legal = legal & slot_occ[:, None]
        # M[p, s, s2]: linearizing slot p moves state s to s2
        M = (legal[:, :, None]
             & (new[:, :, None] == S_VALS[None, None, :]))      # (P,S,S2)
        Mf = M.astype(f32)

        if pallas_round is not None:
            # fused VMEM round (default on TPU): transition product +
            # butterfly + OR-accumulate in one kernel, no HBM
            # intermediates
            MfT = jnp.swapaxes(Mf, 1, 2)

            def pcond(c):
                _tb, cnt, prev = c
                return cnt != prev

            def pbody(c):
                tb, cnt, _ = c
                tb = pallas_round(tb, MfT)
                return tb, tb.sum().astype(i32), cnt

            tbf, _, _ = lax.while_loop(
                pcond, pbody,
                (table.astype(f32), table.sum().astype(i32), i32(-1)))
            return tbf > 0

        # fixpoint: iterate while the popcount grows. M (the P x S x S
        # transition tensor) is computed once per invoke above, outside
        # the loop — XLA hoists it as a loop constant; only the table
        # changes per round.
        def wcond(c):
            tb, cnt, prev = c
            return cnt != prev

        def wbody(c):
            tb, cnt, _ = c
            moved = jnp.einsum("psq,sc->pqc", Mf,
                               tb.astype(f32)) > 0               # (P,S2,C)
            # destination (s2, c | bit_p) comes from source col c (bit_p
            # clear): a butterfly along the mask axis — per-p static
            # reshape + concat, which XLA lowers as layout moves instead
            # of the lane gather take_along_axis would emit
            for p in range(P):
                b = 1 << p
                m = moved[p].reshape(S, C // (2 * b), 2, b)
                cand = jnp.concatenate(
                    [jnp.zeros_like(m[:, :, :1, :]), m[:, :, :1, :]],
                    axis=2)
                tb = tb | cand.reshape(S, C)
            return tb, tb.sum().astype(i32), cnt

        table, _, _ = lax.while_loop(
            wcond, wbody,
            (table, table.sum().astype(i32), i32(-1)))
        return table

    def init_carry(init_state):
        # carry layout: (e, table, slot_f, slot_a, slot_b, slot_occ,
        # att, count, max_count) — att is the ABFT attestation
        # accumulator (see the sort kernel's twin): table-occupancy
        # invariant residues sum into it and it must read 0 on host
        # at every chunk boundary (abft.verify_carry).
        table = jnp.zeros((S, C), jnp.bool_)
        table = table.at[init_state - s_lo, 0].set(True)
        return (i32(0), table,
                jnp.zeros((P,), i32), jnp.full((P,), NIL, i32),
                jnp.full((P,), NIL, i32), jnp.zeros((P,), jnp.bool_),
                i32(0), i32(1), i32(1))

    def summarize(carry):
        # att as the 5th output — see the sort kernel's twin
        (e, table, _sf, _sa, _sb, _occ, att, count,
         max_count) = carry
        ok = count > 0
        death = jnp.where(ok, i32(-1), e - 1)
        # the dense table never drops configurations: overflow is
        # impossible and every verdict is exact
        return ok, death, jnp.bool_(False), max_count, att

    def run_range(x, stop, carry):
        def invoke_phase(s, f, a, b, args):
            table, slot_f, slot_a, slot_b, slot_occ = args
            slot_f = slot_f.at[s].set(f)
            slot_a = slot_a.at[s].set(a)
            slot_b = slot_b.at[s].set(b)
            slot_occ = slot_occ.at[s].set(True)
            table = closure(table, slot_f, slot_a, slot_b, slot_occ)
            return table, slot_f, slot_a, slot_b, slot_occ

        def cond(c):
            return (c[0] < stop) & (c[7] > 0)

        def body(c):
            (e, table, slot_f, slot_a, slot_b, slot_occ, att, count,
             maxc) = c
            row = x[e]
            # the dense table caps P well below 31, so the completion
            # mask fits a non-negative int32 — no bitcast needed
            rm = row[0]
            s, f, a, b = row[1], row[2], row[3], row[4]
            # completion phase: survivors hold every returned bit; the
            # new config is the same mask with them cleared (injective:
            # no dedup, and closure is preserved, so no re-expansion).
            # table'[c] = table[c | rm] iff c ∩ rm = ∅; rm = 0 is the
            # identity gather.
            table = jnp.take(table, COLS | rm, axis=1) \
                & ((COLS & rm) == 0)[None, :]
            slot_occ = slot_occ & \
                ~((rm >> ARANGE_P) & 1).astype(jnp.bool_)
            table, slot_f, slot_a, slot_b, slot_occ = lax.cond(
                s >= 0,
                lambda args: invoke_phase(s, f, a, b, args),
                lambda args: args,
                (table, slot_f, slot_a, slot_b, slot_occ))
            if use_attest:
                # ABFT table invariant: a configuration column whose
                # mask holds a bit of an UNOCCUPIED slot is
                # unreachable (completions gather those columns away;
                # the closure only sets occupied bits) — any true cell
                # there is a bit-flip. Cost: one (S, C) mask-and-sum
                # per step, the same shape as the count reduction.
                occ_bits = jnp.sum(
                    jnp.where(slot_occ, 1 << ARANGE_P,
                              jnp.zeros_like(ARANGE_P)),
                    dtype=i32)
                badc = (COLS & ~occ_bits) != 0                  # (C,)
                att = att + jnp.sum(table & badc[None, :], dtype=i32)
            count = table.sum().astype(i32)
            return (e + 1, table, slot_f, slot_a, slot_b, slot_occ,
                    att, count, jnp.maximum(maxc, count))

        return lax.while_loop(cond, body, carry)

    def make_check(x, n_steps, init_state):
        return summarize(run_range(x, n_steps, init_carry(init_state)))

    @jax.jit
    def check(x, n_steps, init_state):
        return make_check(x, n_steps, init_state)

    @jax.jit
    def check_batch(x, n_steps, init_state):
        return jax.vmap(make_check)(x, n_steps, init_state)

    @jax.jit
    def check_chunk(x, stop, carry):
        return run_range(x, stop, carry)

    @jax.jit
    def check_chunk_batch(x, stops, carry):
        return jax.vmap(run_range)(x, stops, carry)

    @jax.jit
    def check_stream_chunk(x, n, carry):
        # streaming rebase — see the sort kernel's twin for the contract
        local = (i32(0),) + tuple(carry[1:])
        out = run_range(x, n, local)
        return (out[0] + carry[0],) + tuple(out[1:])

    k = Kernel(check, check_batch, check_chunk, check_chunk_batch,
               check_stream_chunk, init_carry, summarize,
               _mk_digest())
    _M_COMPILE.labels(family="dense", stage="build").observe(
        _time.monotonic() - t_build)
    return k


DENSE_STATE_CAP = 512  # closure() is O(P * S^2 * C): bound S too


def _dense_shape(srange: tuple[int, int],
                 p_exact: int) -> tuple[int, int, int] | None:
    """(s_lo, S_bucketed, P_exact) if the dense table fits the caps,
    else None. S is bucketed to a power of two so histories differing
    only in value range share a compiled kernel — the padding rows are
    unreachable states and never become true."""
    lo, hi = srange
    S = hi - lo + 1
    if S > DENSE_STATE_CAP:
        return None
    S = _bucket(S, lo=4)
    if S * (1 << p_exact) <= DENSE_TABLE_CAP:
        return lo, S, p_exact
    return None


# ---------------------------------------------------------------------------
# Engine cost model: sort vs dense vs pallas variants
# ---------------------------------------------------------------------------
#
# The two kernel families are now both tunable (dense: XLA butterfly vs
# Pallas closure round; sort: XLA lex-sort vs Pallas hash dedup), so
# 'auto' picks by a small per-event work model instead of
# "dense-whenever-it-fits". Units are abstract element-ops with a
# single cross-family constant (MXU_ADVANTAGE) for work the MXU eats;
# the constants are calibrated against the r05 hardware numbers
# (dense 2-6x over sort on the small-S register shapes) and exposed
# here so a future hardware round can re-fit them in one place.

MXU_ADVANTAGE = 256     # batched-matmul element-ops per VPU-op
CLOSURE_ROUNDS = 2      # typical stage-B fixpoint depth per invoke
HASH_PROBE_COST = 6     # serial probe+claim cost per candidate key
DENSE_EXACT_BIAS = 8.0  # dense verdicts are exact (no frontier, no
#                         escalation re-runs): prefer dense until its
#                         modeled cost exceeds the sort family's by
#                         this factor


@dataclasses.dataclass(frozen=True)
class EngineDecision:
    """A resolved engine choice for one kernel shape."""
    family: str                 # 'dense' | 'sort'
    dense: tuple | None         # (s_lo, S, P) when family == 'dense'
    dedup: str                  # DEDUP_* (sort family's dedup engine)
    reason: str
    costs: dict                 # modeled per-history element-ops
    # measured per-history device-seconds per compared variant, when a
    # ready calibration priced the decision (see jepsen_tpu.calibrate)
    seconds: dict | None = None


def engine_variant(dec: "EngineDecision") -> str:
    """The calibration variant a decision actually runs: 'dense', or
    the sort family at its resolved dedup engine ('hash' for the
    Pallas kernel, 'sort' for the XLA lex-sort)."""
    if dec.family == "dense":
        return "dense"
    return "hash" if dec.dedup == DEDUP_PALLAS else "sort"


def engine_cost(dec: "EngineDecision") -> float:
    """The chosen engine's modeled element-ops — the single place the
    family/dedup -> costs-key mapping lives (the screen's escalation
    pricing and the service's chunk budget both use it)."""
    return float(dec.costs.get(engine_variant(dec)) or 0.0)


def _family_costs(S: int, p_dense: int, p_sort: int, F: int,
                  n_events: int) -> dict:
    """Modeled total element-ops per engine variant for a history of
    n_events over S states and an F frontier. The two families run at
    DIFFERENT slot counts — the dense table is exact-P (2^p_dense
    wide) while the sort kernel buckets its slots up (p_sort) — so
    each row is priced at the count its kernel actually runs."""
    n = max(int(n_events), 1)
    C = 1 << min(p_dense, 31)
    K = F * (1 + p_sort)                  # stage-B dedup candidates
    W = max(1, (p_sort + 31) // 32)
    # dense: per invoke, CLOSURE_ROUNDS of the (P,S,S)x(S,C) product
    # (MXU) + the butterfly OR-accumulate over the table (VPU); plus
    # the one-off table allocation/init
    dense = n * CLOSURE_ROUNDS * (p_dense * S * S * C / MXU_ADVANTAGE
                                  + S * C) + S * C
    # sort family: per invoke, one lex sort of K rows on (W+2) lanes
    srt = n * (W + 2) * K * max(np.log2(K), 1.0)
    # hash dedup: per invoke, one serial probe pass over K keys
    hsh = n * HASH_PROBE_COST * K
    return {"dense": dense, "sort": srt, "hash": hsh}


def _note_engine(dec: "EngineDecision", reason: str) -> "EngineDecision":
    """Count a select_engine outcome. `reason` is the COARSE bucket
    (forced | slot-cap | dense-caps | cost-model | calibrated) — the
    free-text dec.reason would blow up label cardinality. Also accumulates the
    chosen engine's modeled element-ops, so rate(elementops)/rate(
    chunk_seconds) is the pipeline's modeled throughput."""
    _M_ENGINE.labels(family=dec.family, dedup=dec.dedup,
                     reason=reason).inc()
    cost = engine_cost(dec)
    if cost:
        _M_ELEMENTOPS.labels(family=dec.family).inc(cost)
    return dec


def select_engine(srange: tuple[int, int], p_exact: int, n_events: int,
                  *, slots: int | None = None, frontier: int = 256,
                  engine: str = "auto", dense_slot_cap: int | None = None,
                  pallas=None, calibration=None) -> EngineDecision:
    """Pick the kernel family (and the sort family's dedup engine) for
    one history shape. engine='dense'/'sort' force a family ('dense'
    raises _dense_caps_error when the table cannot fit, the offline
    contract); 'auto' runs the cost model. dense_slot_cap bounds the
    slot count the dense table may be asked to absorb (each slot
    doubles the table; a checker that knows its histories' tail
    concurrency can cap the blowup early). pallas=True/False forces
    the Pallas variants on/off (None = env gate / backend default).

    calibration: a `jepsen_tpu.calibrate.Calibration` (None = the
    process-wide active one, usually nothing). When it holds trusted
    measured coefficients for BOTH compared variants, the dense-vs-
    sort comparison runs in measured device-seconds instead of raw
    modeled element-ops — the same DENSE_EXACT_BIAS preference for
    exact verdicts, applied to ground truth."""
    if engine not in ("auto", "dense", "sort"):
        raise ValueError(f"unknown WGL engine {engine!r}")
    if slots is None:
        slots = _bucket(p_exact, lo=8)
    S = _bucket(srange[1] - srange[0] + 1, lo=4)
    costs = _family_costs(S, p_exact, slots, frontier, n_events)
    dedup = dedup_engine(frontier, slots, _pack_params(srange, slots),
                         pallas)
    # the sort family's modeled cost is whichever dedup it will
    # actually run at this shape — the kernel never mixes engines
    sort_variant = "hash" if dedup == DEDUP_PALLAS else "sort"
    sort_cost = costs[sort_variant]
    cal = calibration if calibration is not None \
        else _calibrate.active()
    seconds = None
    if cal is not None and cal.ready("dense", sort_variant):
        seconds = {
            "dense": cal.seconds("dense", costs["dense"]),
            sort_variant: cal.seconds(sort_variant, sort_cost)}
    dense = None
    if engine in ("auto", "dense"):
        if dense_slot_cap is not None and p_exact > dense_slot_cap:
            if engine == "dense":
                raise ValueError(
                    f"dense engine requested but the history needs "
                    f"{p_exact} slots, over dense_slot_cap="
                    f"{dense_slot_cap}")
            return _note_engine(EngineDecision(
                "sort", None, dedup,
                f"p={p_exact} over dense_slot_cap={dense_slot_cap}",
                costs), "slot-cap")
        dense = _dense_shape(srange, p_exact)
        if dense is None and engine == "dense":
            raise _dense_caps_error(srange, p_exact)
    if engine == "sort" or dense is None:
        why = ("forced" if engine == "sort"
               else f"S={S} x 2^{p_exact} exceeds the dense caps")
        return _note_engine(
            EngineDecision("sort", None, dedup, why, costs, seconds),
            "forced" if engine == "sort" else "dense-caps")
    if seconds is not None:
        # measured comparison: same exactness bias, ground-truth units
        dense_v, sort_v = seconds["dense"], seconds[sort_variant]
        if engine == "dense" or dense_v <= DENSE_EXACT_BIAS * sort_v:
            why = ("forced" if engine == "dense" else
                   f"measured dense {dense_v:.3g}s <= "
                   f"{DENSE_EXACT_BIAS:g}x {dedup} {sort_v:.3g}s")
            return _note_engine(
                EngineDecision("dense", dense, DEDUP_NONE, why, costs,
                               seconds),
                "forced" if engine == "dense" else "calibrated")
        return _note_engine(EngineDecision(
            "sort", None, dedup,
            f"measured dense {dense_v:.3g}s > {DENSE_EXACT_BIAS:g}x "
            f"{dedup} {sort_v:.3g}s", costs, seconds), "calibrated")
    if engine == "dense" or \
            costs["dense"] <= DENSE_EXACT_BIAS * sort_cost:
        why = ("forced" if engine == "dense" else
               f"dense {costs['dense']:.3g} <= {DENSE_EXACT_BIAS:g}x "
               f"{dedup} {sort_cost:.3g}")
        return _note_engine(
            EngineDecision("dense", dense, DEDUP_NONE, why, costs),
            "forced" if engine == "dense" else "cost-model")
    return _note_engine(EngineDecision(
        "sort", None, dedup,
        f"dense {costs['dense']:.3g} > {DENSE_EXACT_BIAS:g}x "
        f"{dedup} {sort_cost:.3g}", costs), "cost-model")


# ---------------------------------------------------------------------------
# Device-fault recovery ladder (shared by every device-checking entry)
# ---------------------------------------------------------------------------
#
# A backend failure mid-check used to be terminal: check_safe mapped the
# RuntimeError to {'valid?': 'unknown', 'degraded': True} and the run
# lost its verdict. Every public entry below now runs under a ladder
# instead — detect cheaply (classify_backend_error), recover from the
# last good state, re-verify only what's lost (the GCN-ABFT / A-QED
# posture, PAPERS.md):
#
#   oom          shrink the device working set (halve chunk_entries;
#                under 'auto', re-select the engine with dense_slot_cap
#                0, i.e. the sort family — the dense table is the
#                memory hog) — batch entries additionally SPLIT the
#                batch in half and recover each half independently
#   device-lost  one backend re-init (jax.clear_caches + drop this
#                module's kernel LRUs, whose jitted fns hold
#                executables bound to the lost device), then retry
#   compile      retry without the Pallas kernel variants (the usual
#                compile-failure source is a Mosaic rejection)
#   wedged       plain bounded retry (includes watchdog'd syncs and any
#                backend error the classifier can't place)
#
# and when the budget is spent, the FINAL rung decides on the host
# mirror (exact, slow) for histories under HOST_FALLBACK_MAX_OPS
# instead of reporting unknown. Results that went through the ladder
# carry a 'recovered' trail; only a ladder that fell off the bottom
# reports 'degraded'.

MAX_RECOVERY_RETRIES = 3
HOST_FALLBACK_MAX_OPS = 20_000


class _RecoveryTrail:
    """Bookkeeping for one checking entry's ladder: classify each
    backend fault, enforce the retry budget, back off with
    control.retry's decorrelated jitter between attempts, and stamp
    the 'recovered' trail on the eventual result. Exceptions the
    classifier rejects re-raise immediately — a checker bug must never
    look like a device fault."""

    def __init__(self, max_retries: int | None = None):
        self.max = (MAX_RECOVERY_RETRIES if max_retries is None
                    else max(0, int(max_retries)))
        self.faults: list[str] = []
        self._delays = None

    def absorb(self, exc: BaseException, site: str) -> bool:
        """Record exc's bucket; True when another retry is allowed
        (after the backoff sleep), False when the budget is spent and
        the caller must take the final rung."""
        kind = classify_backend_error(exc)
        if kind is None:
            raise exc
        self.faults.append(kind)
        _M_RUNGS.labels(kind=kind, site=site).inc()
        if len(self.faults) > self.max:
            log.warning("%s: %s fault after %d recovery retries; "
                        "taking the final rung (%s)", site, kind,
                        self.max, exc)
            return False
        if self._delays is None:
            from ..control.retry import backoff
            self._delays = backoff()
        delay = next(self._delays)
        log.warning("%s: %s fault (%s); recovering, retry %d/%d in "
                    "%.2fs", site, kind, exc, len(self.faults),
                    self.max, delay)
        _time.sleep(delay)
        return True

    def stamp(self, result) -> None:
        """Mark a decided result as recovered (no-op when the entry
        never faulted)."""
        if self.faults and isinstance(result, dict):
            result["recovered"] = {"faults": list(self.faults),
                                   "retries": len(self.faults)}


def _apply_recovery_rung(kind: str, kw: dict) -> None:
    """Mutate a retry's kwargs per the fault bucket (only the knobs the
    entry actually accepts — `kw` is the exact kwargs of the next
    attempt)."""
    if kind == FAULT_OOM:
        if "chunk_entries" in kw:
            kw["chunk_entries"] = max(
                256, int(kw["chunk_entries"] or 4096) // 2)
        if kw.get("engine") != "dense":
            # re-run select_engine under the tightest dense_slot_cap:
            # every slot doubles the dense table, so cap 0 routes the
            # retry to the sort family (a forced 'dense' keeps its
            # contract and relies on the other rungs / the final rung)
            kw["dense_slot_cap"] = 0
    elif kind == FAULT_DEVICE_LOST:
        _device_reinit()
    elif kind == FAULT_COMPILE:
        kw["pallas"] = False
    # FAULT_CORRUPT (an ABFT attestation mismatch) needs no knob
    # mutation: the retry re-stages every device buffer from canonical
    # host data, which IS the rung — like FAULT_WEDGED, a plain
    # bounded retry


def _device_reinit() -> None:
    """The device-lost rung: drop jax's executable caches AND this
    module's kernel LRUs — their jitted fns hold compiled executables
    bound to the lost device — so the retry rebuilds device state
    from scratch."""
    backend_reinit()
    _clear_sort_caches()
    _clear_dense_caches()


def _final_rung(model, hist, trail: _RecoveryTrail,
                exc: BaseException, budget_s: float | None = None,
                cancel=None) -> dict:
    """The ladder's last rung: the host mirror decides histories under
    HOST_FALLBACK_MAX_OPS (exact, device-free); longer ones report a
    degraded 'unknown' carrying the fault trail — still strictly more
    informative than the old blanket degradation."""
    h = as_history(hist)
    if len(h) <= HOST_FALLBACK_MAX_OPS:
        from .linear import analysis_host
        a = analysis_host(model, h, budget_s=budget_s, cancel=cancel)
        a["analyzer"] = "host-jit-linear (backend-fault fallback)"
        trail.stamp(a)
        a["recovered"]["fallback"] = "host"
        return a
    return {
        "valid?": "unknown", "analyzer": "tpu-wgl", "degraded": True,
        "op-count": len(h),
        "error": (f"backend faults exhausted the recovery budget "
                  f"(trail: {trail.faults}) and the history exceeds "
                  f"the {HOST_FALLBACK_MAX_OPS}-op host-fallback cap; "
                  f"last fault: {exc}"),
        "recovery-failed": {"faults": list(trail.faults),
                            "retries": trail.max},
        "configs": [], "final-paths": [],
    }


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def encode_ops_for_model(model, hist) -> OpArray:
    """Encode a history with the model's value codec, honoring the model's
    rules about which pending ops are droppable. Raises ValueError when
    the history exceeds the device encoding (checkers fall back to the
    host model)."""
    name = model.device_model
    if name is None or name not in DEVICE_MODELS:
        raise ValueError(f"model {model!r} has no device form")
    dm = DEVICE_MODELS[name]
    try:
        ops = encode_ops(as_history(hist), dm.codec, dm.droppable)
    except OverflowError as e:   # value outside int32
        raise DeviceEncodingError(str(e)) from e
    if dm.validate is not None:
        dm.validate(ops, model)
    return ops


def analysis_tpu(model, hist, frontier: int = 256, slots: int | None = None,
                 max_frontier: int = 65536,
                 chunk_entries: int = 4096,
                 budget_s: float | None = None,
                 cancel=None,
                 explain: bool = True,
                 slot_overflow_fallback: bool = True,
                 engine: str = "auto",
                 dense_slot_cap: int | None = None,
                 pallas=None,
                 max_recovery_retries: int | None = None) -> dict:
    """Check one history on the device, under the device-fault recovery
    ladder (see the ladder comment above): a classified backend fault
    (oom / device-lost / compile / wedged) re-runs the search down the
    appropriate rung instead of surfacing as a degraded 'unknown', and
    a decided result that went through the ladder reports its
    'recovered' trail. max_recovery_retries bounds the ladder (None =
    MAX_RECOVERY_RETRIES); past it, histories under
    HOST_FALLBACK_MAX_OPS are decided on the host mirror.

    See _analysis_tpu_once for the search itself and the remaining
    knobs."""
    kw = dict(frontier=frontier, slots=slots, max_frontier=max_frontier,
              chunk_entries=chunk_entries, budget_s=budget_s,
              cancel=cancel, explain=explain,
              slot_overflow_fallback=slot_overflow_fallback,
              engine=engine, dense_slot_cap=dense_slot_cap,
              pallas=pallas)
    trail = _RecoveryTrail(max_recovery_retries)
    while True:
        try:
            a = _analysis_tpu_once(model, hist, **kw)
        except RuntimeError as e:
            if not trail.absorb(e, "offline"):
                return _final_rung(model, hist, trail, e,
                                   budget_s=budget_s, cancel=cancel)
            _apply_recovery_rung(trail.faults[-1], kw)
            continue
        trail.stamp(a)
        return a


def _analysis_tpu_once(model, hist, frontier: int = 256,
                       slots: int | None = None,
                       max_frontier: int = 65536,
                       chunk_entries: int = 4096,
                       budget_s: float | None = None,
                       cancel=None,
                       explain: bool = True,
                       slot_overflow_fallback: bool = True,
                       engine: str = "auto",
                       dense_slot_cap: int | None = None,
                       pallas=None) -> dict:
    """Check one history on the device. The slot count is sized to the
    history's actual peak concurrency; long histories run as a sequence
    of bounded-duration chunked kernel calls with the frontier carried
    (and checkpointable) between them, so a 100k-op search never holds
    the device in one multi-minute call. Escalates the frontier on
    overflow-with-invalid (a dropped config could have been the
    witness); falls back to the host search past 256 slots.

    budget_s caps total wall time: past it, an undecided search returns
    'unknown' instead of escalating further (histories with many
    crashed mutating ops are genuinely exponential — the reference's
    checker hits the same wall as an OOM or its 1 h timeout).

    cancel: zero-arg callable polled between chunks — truthy stops the
    search with 'unknown' (competition racing). explain: on a definite
    invalid verdict, re-run the host oracle on the prefix ending at the
    culprit op to reconstruct configs and final-paths (the reference
    renders these via knossos.linear.report, `checker.clj:205-216`).

    engine: 'auto' picks by the cost model (see select_engine) over
    the dense reachable-set kernel (exact verdicts, no frontier,
    eligible when S x 2^P fits DENSE_TABLE_CAP) and the sort-frontier
    family; 'dense' / 'sort' force one. dense_slot_cap bounds the slot
    count 'auto' lets the dense table absorb; pallas=True/False forces
    the Pallas kernel variants (dense closure round, sort-family hash
    dedup) on/off, None defers to the JEPSEN_TPU_PALLAS_* env gates
    (default ON for real TPU backends).

    Latency shape: the event stream ships as ONE packed matrix (one
    host->device transfer), and histories that fit a single chunk run
    as ONE fused device call (init + search + verdict) — the
    small-history path costs two round-trips total, not a dozen. The
    kernel consumes the merged step stream (see Steps); definite
    invalid verdicts re-run the unmerged stream through the same
    compiled kernel to name the culprit op."""
    import jax
    import jax.numpy as jnp

    t0 = _time.monotonic()
    name = model.device_model
    ops = encode_ops_for_model(model, hist)
    p_exact = required_slots(ops)
    if slots is None or p_exact > slots:
        slots = _bucket(p_exact, lo=8)
    if slots > 256:
        if not slot_overflow_fallback:
            # competition racing: a parallel host thread is already
            # running this search — don't duplicate it
            return {"valid?": "unknown", "analyzer": "tpu-wgl",
                    "error": f"slot overflow ({slots} slots needed)"}
        from .linear import analysis_host
        a = analysis_host(model, hist, budget_s=budget_s, cancel=cancel)
        a["analyzer"] = "host-jit-linear (slot overflow)"
        return a
    srange = _state_range(name, model, [ops])
    decision = select_engine(srange, p_exact, event_count(ops),
                             slots=slots, frontier=frontier,
                             engine=engine,
                             dense_slot_cap=dense_slot_cap,
                             pallas=pallas)
    dense = decision.dense
    if dense is not None:
        slots = dense[2]   # exact-P: the dense table is 2^P wide
    steps = build_steps(ops, slots)
    # capacity covers the unmerged stream so the blame re-run below
    # shares this compiled kernel
    E = _bucket(max(event_count(ops), 1))
    steps = steps.pad_to(E)
    # ABFT staged-buffer attestation: ship (possibly bitflip-injected)
    # data, then compare a device-side digest of the shipped buffer
    # with the host digest of the canonical one — corruption on the
    # staging/DMA path raises CorruptDeviceResult, which the recovery
    # ladder absorbs by re-staging from the canonical host copy.
    attest_on = attest_enabled()
    x = jnp.asarray(maybe_corrupt("offline", steps.x))
    att_info = None
    if attest_on:
        from . import abft
        abft.verify_steps("offline", guarded_device_get(
            abft.digest_device(x), site="offline attest"),
            abft.digest_host(steps.x))
        att_info = {"steps": 1, "carry": 0}
    init_state = jnp.int32(model.device_state())
    F = frontier
    timed_out = cancelled = False
    while True:
        if dense is not None:
            k = _dense_kernel(name, dense[0], dense[1], dense[2], E,
                              pallas=pallas)
        else:
            k = _kernel(name, F, slots, E, _pack_params(srange, slots),
                        pallas=pallas)
        fam = "dense" if dense is not None else "sort"
        chunk_obs = _M_CHUNK.labels(site="offline", family=fam)
        if steps.n <= chunk_entries:
            # single fused call: init + full search + verdict
            maybe_inject_fault("offline")
            with chunk_obs.time(), \
                    _telemetry.profile_section("wgl.offline.check"):
                ok, death, overflow, max_count, att = \
                    guarded_device_get(
                        k.check(x, jnp.int32(steps.n), init_state),
                        site="offline check")
            _check_att(att, "offline")
        else:
            carry = k.init_carry(init_state)
            # Pipelined chunk loop: enqueue chunk i (dispatch is async),
            # THEN read chunk i-1's liveness flag — the device computes
            # chunk i while the host waits on the already-finished
            # flag, so the per-chunk host<->device sync overlaps with
            # compute instead of serializing after it.  Safe to
            # speculate one chunk past a death: an empty frontier stays
            # empty, and on death we discard the speculated carry.
            e = 0
            # measured-cost-model feed: modeled element-ops per step
            # entry, so each chunk's latency pairs with its share of
            # the decision's modeled cost (both linear in entries)
            cal_ops_per_entry = engine_cost(decision) / max(steps.n, 1)
            chunk_i = 0
            prev_span = 0
            while e < steps.n:
                e0 = e
                stop = min(e + chunk_entries, steps.n)
                maybe_inject_fault("offline")
                t_chunk = _time.monotonic()
                with _telemetry.profile_section("wgl.offline.chunk"):
                    nxt = k.check_chunk(x, jnp.int32(stop), carry)
                    prev, carry = carry, nxt
                    e = stop
                    dead = int(guarded_device_get(
                        prev[-2], site="offline liveness")) == 0
                dt_chunk = _time.monotonic() - t_chunk
                chunk_obs.observe(dt_chunk)
                if chunk_i >= 2:
                    # the blocking flag read is one chunk behind, so
                    # dt_chunk measures chunk i-1: pair it with THAT
                    # chunk's op share, and start at i>=2 so chunk 0
                    # (which carries the compile) never enters the fit
                    _calibrate.observe(engine_variant(decision),
                                       cal_ops_per_entry * prev_span,
                                       dt_chunk)
                prev_span = stop - e0
                chunk_i += 1
                if dead:
                    carry = prev   # frontier died last chunk: definite
                    break
                # only give up when chunks remain — a search that just
                # finished is definitive regardless of elapsed time
                if e < steps.n:
                    over = budget_s is not None and \
                        _time.monotonic() - t0 > budget_s
                    stop_req = cancel is not None and cancel()
                    if over or stop_req:
                        # the in-flight chunk may already have decided:
                        # block on its flag before downgrading a
                        # definite death to 'unknown'
                        if int(guarded_device_get(
                                carry[-2], site="offline liveness")) == 0:
                            break
                        timed_out = True
                        cancelled = stop_req and not over
                        break
            if attest_on:
                # chunk-boundary carry attestation: fetch the carry
                # with its device-computed digest, recompute on host,
                # and check the structural invariants (att == 0) —
                # silent corruption of the frontier in HBM or on the
                # fetch path surfaces here instead of in the verdict
                from . import abft
                hc, hd = guarded_device_get(
                    (carry, k.digest(carry)), site="offline attest")
                abft.verify_carry("offline", hd, hc)
                att_info["carry"] += 1
            ok, death, overflow, max_count, att = guarded_device_get(
                k.summarize(carry), site="offline summarize")
            _check_att(att, "offline")
        ok = bool(ok) and not timed_out
        overflow = bool(overflow) or timed_out
        if ok or not overflow or F >= max_frontier or timed_out:
            break
        if budget_s is not None and _time.monotonic() - t0 > budget_s:
            timed_out = True
            break
        F *= 4  # invalid + overflow: the witness may have been dropped
    _M_OPS.labels(site="offline").inc(len(ops))
    out = {
        "valid?": (True if ok else
                   "unknown" if overflow else False),
        "analyzer": "tpu-wgl-dense" if dense is not None else "tpu-wgl",
        # the dedup engine the FINAL kernel ran (escalation grows F,
        # which can push the hash working set out of VMEM mid-search)
        "dedup": (DEDUP_NONE if dense is not None else
                  dedup_engine(F, slots, _pack_params(srange, slots),
                               pallas)),
        "engine-reason": decision.reason,
        "op-count": len(ops),
        "max-frontier": int(max_count),
        "frontier-size": F,
        "duration-ms": (_time.monotonic() - t0) * 1e3,
        "configs": [],
        "final-paths": [],
    }
    if att_info is not None:
        out["attested"] = att_info
    if not ok:
        if cancelled:
            out["error"] = "search cancelled (competition loser)"
        elif timed_out:
            out["error"] = (
                f"search exceeded the {budget_s} s budget at frontier "
                f"{F}; verdict unknown")
        elif overflow:
            # The death point is an artifact of dropped configs — do not
            # name a culprit op for an 'unknown' verdict.
            out["error"] = (
                f"frontier overflowed at {F} configs; verdict unknown "
                f"(re-run with a larger frontier or the host checker)")
        else:
            # the merged stream can't name a single culprit op: re-run
            # the unmerged stream (same T capacity -> same compiled
            # kernel); it dies at the same event, cheaply
            row = _death_row(k, ops, slots, E, init_state)
            if row >= 0:
                src_index = int(ops.index[row])
                out["op"] = _find_op(hist, src_index)
                out["op-index"] = src_index
                if explain:
                    from .linear import explain_failure
                    ex = explain_failure(model, hist, src_index)
                    if ex is not None:
                        out["configs"] = ex["configs"]
                        out["final-paths"] = ex["final-paths"]
                        if ex.get("previous-ok") is not None:
                            out["previous-ok"] = ex["previous-ok"]
    return out


def _death_row(k: Kernel, ops: OpArray, slots: int, E: int,
               init_state) -> int:
    """Op row where the frontier died, from an unmerged re-run."""
    import jax
    import jax.numpy as jnp

    steps = build_steps(ops, slots, merge=False).pad_to(E)
    ok, death, *_ = guarded_device_get(
        k.check(jnp.asarray(steps.x), jnp.int32(steps.n), init_state),
        site="offline blame")
    d = int(death)
    if bool(ok) or d < 0:
        return -1
    row = int(steps.inv_row[d])
    return row if row >= 0 else int(steps.ret_row[d])


def _find_op(hist, index: int):
    """The completion op for the invocation with the given :index (the
    completion carries the observed value; knossos reports it too)."""
    hist = as_history(hist)
    if hist.ops and "index" not in hist.ops[0]:
        hist = hist.index()
    for pos, o in enumerate(hist.ops):
        if o.get("index") == index:
            comp = hist.completion(pos)
            return comp if comp is not None else o
    return None


def _state_range(name: str, model, entries_list) -> tuple[int, int]:
    """Combined inclusive state bounds over a batch of entry streams."""
    lo = hi = int(model.device_state())
    rng = DEVICE_MODELS[name].state_range
    for e in entries_list:
        l2, h2 = rng(int(model.device_state()), e.f, e.a, e.b)
        lo, hi = min(lo, l2), max(hi, h2)
    return int(lo), int(hi)


def _slot_bucket(p: int, p_max: int | None = None) -> int:
    """Bucket a slot count UP to the next even P so nearby keys share
    one compiled kernel, floored at 4 (the smallest dense table worth
    dispatching) and capped at the batch's true max so rounding never
    exceeds what any key actually needs. The cap itself respects the
    floor, so a batch of all-tiny keys still coalesces into one P=4
    group instead of splitting per exact P."""
    pg = max(4, ((p + 1) // 2) * 2)
    return min(pg, max(p_max, 4)) if p_max is not None else pg


def _dense_caps_error(srange, p: int, key=None) -> ValueError:
    """The forced-dense contract violation (one message, three raise
    sites: scalar, batch plain path, batch group split)."""
    who = f"key {key}'s" if key is not None else "the"
    return ValueError(
        f"dense engine requested but {who} {srange} state range x "
        f"2^{p} table exceeds the dense caps")


def _check_att(att, site: str) -> None:
    """Raise the corrupt fault when a fetched attestation accumulator
    is nonzero — an in-kernel invariant (frontier/table occupancy,
    hash-dedup digest) failed on device. att is constant 0 when
    attestation is disabled, so the check is unconditional."""
    a = int(np.asarray(att))
    if a != 0:
        from . import abft
        from .._platform import CorruptDeviceResult
        abft.note_failure("att")
        raise CorruptDeviceResult(
            site, f"in-kernel attestation accumulator = {a} — a "
                  f"frontier/table invariant or dedup digest failed "
                  f"on device")


def _unknown_result(ops, error: str, t0: float) -> dict:
    """The batch paths' 'unknown' verdict shape (one definition so the
    grouped and plain paths can't drift)."""
    return {"valid?": "unknown", "analyzer": "tpu-wgl-batch",
            "op-count": len(ops), "error": error,
            "configs": [], "final-paths": [],
            "duration-ms": (_time.monotonic() - t0) * 1e3}


def _dispatch_groups(srange, p_req: list[int], engine: str,
                     n_events: int = 1, frontier: int = 1024,
                     dense_slot_cap: int | None = None, pallas=None):
    """Partition a batch's key indices into slot-bucketed dense dispatch
    groups plus one shared sort-frontier group.

    The dense table is S * 2^P wide, so padding every key to the worst
    key's slot count multiplies the whole batch's device work by
    2^(Pmax - P_key); bucketing nearby keys into one compiled kernel
    each recovers that while adding only a few sub-ms dispatches.
    Dense-ineligible keys gain nothing from grouping (the sort frontier
    isn't 2^P-sized), so they spill into a single sort group instead of
    paying one sort-kernel compile per bucket — or, under a forced
    dense engine, raise. Under 'auto' the cost model (select_engine)
    can also route a dense-*eligible* bucket to the sort family when
    its table work is modeled slower; n_events is the batch's largest
    event stream (per-key streams share the verdict of the comparison,
    which is length-invariant except for the one-off table init).

    Returns (dense_groups: {P: (dense_shape, [key indices])},
    sort_idx: [key indices])."""
    if engine == "sort":
        return {}, list(range(len(p_req)))
    sort_idx: list[int] = []
    dense_groups: dict[int, tuple[tuple, list[int]]] = {}
    p_max = max(p_req)
    for i, p in enumerate(p_req):
        pg = _slot_bucket(p, p_max)
        d = _dense_shape(srange, pg) or _dense_shape(srange, p)
        if d is not None and engine == "auto":
            dec = select_engine(srange, d[2], n_events,
                                frontier=frontier,
                                dense_slot_cap=dense_slot_cap,
                                pallas=pallas)
            if dec.family != "dense":
                d = None
        if d is None:
            if engine == "dense":
                raise _dense_caps_error(srange, p, key=i)
            sort_idx.append(i)
        else:
            if d[2] in dense_groups:
                dense_groups[d[2]][1].append(i)
            else:
                dense_groups[d[2]] = (d, [i])
    return dense_groups, sort_idx


def analysis_tpu_batch(model, hists: list, frontier: int = 1024,
                       slots: int = 32, chunk_entries: int = 4096,
                       budget_s: float | None = None,
                       cancel=None, engine: str = "auto",
                       max_frontier: int = 65536,
                       dense_slot_cap: int | None = None,
                       pallas=None,
                       max_recovery_retries: int | None = None,
                       _pre: list | None = None,
                       _dense=False,
                       _preq: list | None = None) -> list[dict]:
    """Recovery wrapper around _analysis_tpu_batch_once (which holds
    the batching contract — see its docstring): a classified backend
    fault re-runs the batch down the standard ladder, except the OOM
    rung SPLITS the batch in half (halving the vmapped working set)
    and recovers each half independently; the final rung decides each
    history via _final_rung (host mirror under the size cap). Results
    that went through the ladder carry a 'recovered' trail."""
    kw = dict(frontier=frontier, slots=slots,
              chunk_entries=chunk_entries, budget_s=budget_s,
              cancel=cancel, engine=engine, max_frontier=max_frontier,
              dense_slot_cap=dense_slot_cap, pallas=pallas,
              _pre=_pre, _dense=_dense, _preq=_preq)
    trail = _RecoveryTrail(max_recovery_retries)
    while True:
        try:
            rs = _analysis_tpu_batch_once(model, hists, **kw)
        except RuntimeError as e:
            if not trail.absorb(e, "batch"):
                return [_final_rung(model, h, trail, e,
                                    budget_s=budget_s, cancel=cancel)
                        for h in hists]
            kind = trail.faults[-1]
            if kind == FAULT_OOM and len(hists) > 1:
                # split/retry: each half re-enters the wrapped entry
                # with the full ladder (and half the device working
                # set); their own recovery trails merge with this one
                mid = len(hists) // 2
                log.warning("batch: splitting %d histories into "
                            "%d + %d after OOM", len(hists), mid,
                            len(hists) - mid)

                def sub(lo, hi):
                    return analysis_tpu_batch(
                        model, hists[lo:hi], frontier=frontier,
                        slots=slots, chunk_entries=kw["chunk_entries"],
                        budget_s=budget_s, cancel=cancel,
                        engine=kw["engine"], max_frontier=max_frontier,
                        dense_slot_cap=kw["dense_slot_cap"],
                        pallas=kw["pallas"],
                        max_recovery_retries=max_recovery_retries,
                        _pre=_pre[lo:hi] if _pre is not None else None,
                        _dense=_dense,
                        _preq=_preq[lo:hi] if _preq is not None
                        else None)

                rs = sub(0, mid) + sub(mid, len(hists))
                for r in rs:
                    # merge this level's trail into each sub-result —
                    # but never stamp 'recovered' on a half that fell
                    # off its own ladder (degraded + recovered is a
                    # contradiction; its fault list lives under
                    # 'recovery-failed'), and keep sub-trail markers
                    # like {'fallback': 'host'}
                    if not isinstance(r, dict):
                        continue
                    if r.get("degraded"):
                        rf = r.get("recovery-failed")
                        if isinstance(rf, dict):
                            rf["faults"] = list(trail.faults) \
                                + list(rf.get("faults", []))
                        continue
                    inner = r.get("recovered")
                    inner = dict(inner) if isinstance(inner, dict) \
                        else {}
                    faults = list(trail.faults) \
                        + list(inner.get("faults", []))
                    inner.update(faults=faults, retries=len(faults),
                                 split=True)
                    r["recovered"] = inner
                return rs
            _apply_recovery_rung(kind, kw)
            continue
        for r in rs:
            trail.stamp(r)
        return rs


def _analysis_tpu_batch_once(model, hists: list, frontier: int = 1024,
                             slots: int = 32, chunk_entries: int = 4096,
                             budget_s: float | None = None,
                             cancel=None, engine: str = "auto",
                             max_frontier: int = 65536,
                             dense_slot_cap: int | None = None,
                             pallas=None,
                             _pre: list | None = None,
                             _dense=False,
                             _preq: list | None = None) -> list[dict]:
    """Check a batch of independent histories (e.g. per-key subhistories
    from the independent workload) in vmapped device calls. Long batches
    run as bounded-duration chunks with the vmapped frontier carried
    between calls, polling budget_s / cancel like the scalar path —
    a pathological key can no longer stall an independent batch
    unboundedly. Undecided keys at the budget report 'unknown'.

    Escalation is batched: every overflow-suspect key re-runs together
    in one vmapped call at 4x the frontier (recursively), instead of
    degrading to serial per-key searches; likewise culprit-op blame for
    definite invalids runs as one vmapped unmerged pass.

    _pre: internal — pre-encoded OpArrays (one per history), passed by
    the group-split recursion so each history is encoded exactly once.
    _dense: internal — the group's dense shape from _dispatch_groups
    (False = derive it here), so bucketed groups share the bucket's
    compiled kernel instead of re-deriving a data-dependent shape from
    the group-local state range. _preq: internal — the group's
    required_slots values, already scanned by the parent (the
    group-local state range is deliberately NOT passed: recomputing it
    over a narrower group can make a spilled sort group dense-eligible)."""
    import jax
    import jax.numpy as jnp

    t0 = _time.monotonic()

    def _remaining():
        if budget_s is None:
            return None
        return max(0.0, budget_s - (_time.monotonic() - t0))

    name = model.device_model
    pre = (_pre if _pre is not None
           else [encode_ops_for_model(model, h) for h in hists])
    _srange = _p_needs = None   # pre-pass reuse for the one-bucket case
    if engine in ("auto", "dense") and len(hists) > 1 and _pre is None:
        # Slot-bucketed dispatch groups (see _dispatch_groups): recurse
        # per group — each group is then bucket-uniform and runs the
        # plain batched path below. Dense groups run cheapest-first and
        # the sort group last, so a pathological dense-ineligible key
        # can only starve itself of budget, not the cheap keys.
        p_req = [required_slots(ops) for ops in pre]
        srange_all = _state_range(name, model, pre)
        dense_groups, sort_idx = _dispatch_groups(
            srange_all, p_req, engine,
            n_events=max((event_count(o) for o in pre), default=1),
            frontier=frontier, dense_slot_cap=dense_slot_cap,
            pallas=pallas)
        group_list = [dense_groups[pg] for pg in sorted(dense_groups)]
        if sort_idx:
            group_list.append((False, sort_idx))
        if len(group_list) > 1:
            grouped: list[dict | None] = [None] * len(hists)
            for d, idx in group_list:
                rem = _remaining()
                if (rem == 0.0) or (cancel is not None and cancel()):
                    # budget gone: report the remaining groups without
                    # dispatching even one chunk for them
                    for i in idx:
                        grouped[i] = _unknown_result(
                            pre[i], "batch budget exhausted/cancelled "
                            "before this key's search started", t0)
                    continue
                sub = analysis_tpu_batch(
                    model, [hists[i] for i in idx], frontier=frontier,
                    slots=slots, chunk_entries=chunk_entries,
                    budget_s=rem, cancel=cancel, engine=engine,
                    max_frontier=max_frontier,
                    dense_slot_cap=dense_slot_cap, pallas=pallas,
                    _pre=[pre[i] for i in idx], _dense=d,
                    _preq=[p_req[i] for i in idx])
                for t, i in enumerate(idx):
                    grouped[i] = sub[t]
            return grouped
        # one bucket: fall through to the plain path, reusing the
        # pre-pass instead of rescanning every history
        if group_list and group_list[0][0] is not False:
            _dense = group_list[0][0]
        else:
            _srange, _p_needs = srange_all, dict(enumerate(p_req))

    results: list[dict | None] = [None] * len(hists)
    encoded = list(enumerate(pre))
    items = []           # (orig index, ops, steps)
    if encoded:
        if _dense is not False:
            # the bucket's shape, shared group-wide; the group-local
            # state range and slot needs would be dead recomputation
            # (the dense kernel's shape carries both)
            dense, srange, p_needs = _dense, None, None
        else:
            srange = (_srange if _srange is not None else
                      _state_range(name, model, [o for _, o in encoded]))
            if _p_needs is not None:
                p_needs = _p_needs
            elif _preq is not None:
                p_needs = dict(enumerate(_preq))
            else:
                p_needs = {i: required_slots(o) for i, o in encoded}
            dense = None
            if engine in ("auto", "dense"):
                # same contract as the scalar path and the multi-key
                # grouped split: a forced dense engine never silently
                # degrades to the sort kernel (select_engine raises).
                # Decided BEFORE the budget early-exit below so the
                # contract violation surfaces identically for
                # zero-budget calls.
                dense = select_engine(
                    srange, max(p_needs.values()),
                    max((event_count(o) for _, o in encoded),
                        default=1),
                    frontier=frontier, engine=engine,
                    dense_slot_cap=dense_slot_cap,
                    pallas=pallas).dense
        if dense is not None:
            slots = dense[2]
        if ((_remaining() == 0.0) or (cancel is not None and cancel())):
            # budget already gone: report unknown before the per-key
            # scalar fallback below can dispatch full searches
            for i, ops in encoded:
                results[i] = _unknown_result(
                    ops, "batch budget exhausted/cancelled before "
                    "this key's search started", t0)
            encoded = []
        for i, ops in encoded:
            if dense is None and p_needs[i] > slots:
                # this key alone exceeds the batch's slot budget:
                # scalar path re-sizes (and host-falls-back past 256)
                results[i] = analysis_tpu(
                    model, hists[i], frontier, budget_s=_remaining(),
                    cancel=cancel, engine=engine,
                    dense_slot_cap=dense_slot_cap, pallas=pallas)
            else:
                items.append((i, ops, build_steps(ops, slots)))
    if items and ((_remaining() == 0.0)
                  or (cancel is not None and cancel())):
        # budget already gone: report unknown without dispatching even
        # the first chunk (the chunk loop below always runs one)
        for i, ops, _st in items:
            results[i] = _unknown_result(
                ops, "batch budget exhausted/cancelled before "
                "this key's search started", t0)
        items = []
    if items:
        E = _bucket(max(max(event_count(ops) for _, ops, _ in items), 1))
        padded = [st.pad_to(E) for _, _, st in items]
        # bucket the batch axis like E: the vmapped kernels are jitted
        # per (B, E) shape, so an exact B would recompile the whole
        # family for every distinct key count — pad with zero-step
        # entries (n=0: never consumed, frontier stays at the initial
        # config), skipped by the per-item j < len(items) reads below
        padded += [Steps.empty(padded[0].w, E)] * (
            _bucket(len(padded), lo=1) - len(padded))
        if dense is not None:
            k = _dense_kernel(name, dense[0], dense[1], dense[2], E,
                              pallas=pallas)
        else:
            k = _kernel(name, frontier, slots, E,
                        _pack_params(srange, slots), pallas=pallas)
        x_np = np.stack([st.x for st in padded])
        attest_on = attest_enabled()
        x = jnp.asarray(maybe_corrupt("batch", x_np))
        if attest_on:
            # staged-buffer attestation (see the offline twin): the
            # whole vmapped stack ships as one buffer, one digest
            from . import abft
            abft.verify_steps("batch", guarded_device_get(
                abft.digest_device(x), site="batch attest"),
                abft.digest_host(x_np))
        ns = np.asarray([st.n for st in padded], np.int32)
        s0 = jnp.full(len(padded), model.device_state(), jnp.int32)
        carry = jax.vmap(k.init_carry)(s0)
        e = 0
        n_max = int(ns.max())
        # pipelined like the scalar loop: enqueue the next vmapped
        # chunk, then read the PREVIOUS chunk's frontier counts while
        # the device computes — all-dead detection lags one chunk
        # (safe: dead frontiers stay dead) in exchange for overlapping
        # the per-chunk sync with compute
        chunk_obs = _M_CHUNK.labels(
            site="batch", family="dense" if dense is not None
            else "sort")
        while e < n_max:
            stop = min(e + chunk_entries, n_max)
            maybe_inject_fault("batch")
            t_chunk = _time.monotonic()
            with _telemetry.profile_section("wgl.batch.chunk"):
                nxt = k.check_chunk_batch(
                    x, jnp.asarray(np.minimum(ns, stop)), carry)
                prev, carry = carry, nxt
                e = stop
                # pad entries never consume, so their frontiers stay
                # alive forever — only the real items' liveness counts
                all_dead = not np.asarray(guarded_device_get(
                    prev[-2],
                    site="batch liveness"))[:len(items)].any()
            chunk_obs.observe(_time.monotonic() - t_chunk)
            if all_dead:
                carry = prev   # every frontier died: all definite
                break
            if e < n_max:
                if (budget_s is not None
                        and _time.monotonic() - t0 > budget_s) \
                        or (cancel is not None and cancel()):
                    break
        if attest_on:
            # per-key carry attestation at the batch's final boundary
            from . import abft
            hc, hd = guarded_device_get(
                (carry, jax.vmap(k.digest)(carry)), site="batch attest")
            for bi in range(len(np.asarray(hd))):
                abft.verify_carry(
                    "batch", np.asarray(hd)[bi],
                    tuple(np.asarray(a)[bi] for a in hc))
        # ONE guarded fetch for the verdicts AND the carry components
        # the decided-mask below needs: the consumed/count buffers were
        # previously pulled via raw np.asarray — an unguarded implicit
        # sync (JTS103) and a second device round-trip
        (ok, death, overflow, max_count, att), consumed, counts = \
            guarded_device_get(
                (jax.vmap(k.summarize)(carry), carry[0], carry[-2]),
                site="batch summarize")
        _check_att(np.asarray(att).sum(), "batch")
        _M_OPS.labels(site="batch").inc(
            sum(len(o) for _, o, _ in items))
        batch_dedup = (DEDUP_NONE if dense is not None else
                       dedup_engine(frontier, slots,
                                    _pack_params(srange, slots),
                                    pallas))
        # a key is decided if it consumed all entries or its frontier
        # died (death is definitive no matter how many entries remain)
        decided = (np.asarray(consumed) >= ns) | (counts == 0)
        suspects = []    # overflow + invalid: escalate together
        invalids = []    # definite invalid: blame together
        for j, (i, ops, st) in enumerate(items):
            if not bool(decided[j]):
                results[i] = _unknown_result(
                    ops, "batch budget exhausted/cancelled before "
                    "this key's search finished", t0)
            elif bool(ok[j]):
                results[i] = {
                    "valid?": True, "analyzer": "tpu-wgl-batch",
                    "dedup": batch_dedup,
                    "op-count": len(ops),
                    "max-frontier": int(max_count[j]),
                    "configs": [], "final-paths": []}
            elif bool(overflow[j]):
                suspects.append((i, ops))
            else:
                invalids.append((j, i, ops))
        if invalids:
            # one vmapped unmerged pass names every culprit op (the
            # unmerged streams fit E by construction)
            st2s = [build_steps(ops, slots, merge=False).pad_to(E)
                    for _, _, ops in invalids]
            st2s += [Steps.empty(st2s[0].w, E)] * (
                _bucket(len(st2s), lo=1) - len(st2s))
            okb, deathb, *_ = guarded_device_get(k.check_batch(
                jnp.asarray(np.stack([s.x for s in st2s])),
                jnp.asarray(np.asarray([s.n for s in st2s], np.int32)),
                jnp.full(len(st2s), model.device_state(), jnp.int32)))
            for t, (j, i, ops) in enumerate(invalids):
                r = {"valid?": False, "analyzer": "tpu-wgl-batch",
                     "dedup": batch_dedup,
                     "op-count": len(ops),
                     "max-frontier": int(max_count[j]),
                     "configs": [], "final-paths": []}
                d = int(deathb[t])
                if not bool(okb[t]) and d >= 0:
                    row = int(st2s[t].inv_row[d])
                    if row < 0:
                        row = int(st2s[t].ret_row[d])
                    if row >= 0:
                        src = int(ops.index[row])
                        r["op"] = _find_op(hists[i], src)
                        r["op-index"] = src
                results[i] = r
        if suspects:
            if frontier < max_frontier:
                sub = analysis_tpu_batch(
                    model, [hists[i] for i, _ in suspects],
                    frontier=frontier * 4, slots=slots,
                    chunk_entries=chunk_entries, budget_s=_remaining(),
                    cancel=cancel, engine=engine,
                    max_frontier=max_frontier,
                    dense_slot_cap=dense_slot_cap, pallas=pallas)
                for t, (i, _ops) in enumerate(suspects):
                    results[i] = sub[t]
            else:
                for i, ops in suspects:
                    results[i] = _unknown_result(
                        ops, f"frontier overflowed at {frontier}; "
                        f"escalation cap {max_frontier} reached — "
                        "verdict unknown", t0)
        if attest_on:
            for i, _ops, _st in items:
                r = results[i]
                if isinstance(r, dict):
                    r.setdefault("attested", {"steps": 1, "carry": 1})
    dur = (_time.monotonic() - t0) * 1e3
    for r in results:
        if r is not None:
            r.setdefault("duration-ms", dur)
    return results  # type: ignore[return-value]


def _sharded_runner(name, dense, frontier, slots, srange, E, mesh, axis,
                    pallas=None):
    """The jitted, mesh-sharded batch checker for one kernel shape.

    Cached on the full compilation key (kernel shape + mesh) so repeated
    check_batch_sharded calls — and the several per-slot-bucket dispatch
    groups inside one call — reuse one traced+compiled executable per
    shape. A fresh closure per call would force shard_map to re-trace
    and XLA to recompile every time, which on the remote-relay TPU costs
    seconds per dispatch and was the bulk of the sharded path's wall
    time. The dense kernel ignores frontier/slots/srange, so they are
    normalized out of the cache key here — spurious misses can't be
    reintroduced by a call site. The Pallas-vs-XLA choices (closure
    round for the dense family, hash dedup for the sort family) are
    resolved here and included in the key, so flipping the
    JEPSEN_TPU_PALLAS_* gates mid-process affects sharded checks the
    same way it affects scalar/batch ones.
    """
    if dense is not None:
        frontier = slots = srange = None
        use_pallas, on_tpu = _pallas_enabled(
            "JEPSEN_TPU_PALLAS_CLOSURE", pallas)
    else:
        use_pallas, on_tpu = _pallas_enabled(
            "JEPSEN_TPU_PALLAS_DEDUP", pallas)
    return _sharded_runner_cached(name, dense, frontier, slots, srange,
                                  E, mesh, axis, use_pallas, on_tpu,
                                  attest_enabled())


@functools.lru_cache(maxsize=256)
def _sharded_runner_cached(name, dense, frontier, slots, srange, E,
                           mesh, axis, use_pallas, on_tpu,
                           use_attest=True):
    import jax
    from functools import partial
    from jax.sharding import PartitionSpec as P

    if dense is not None:
        check_batch = _dense_kernel_cached(
            name, dense[0], dense[1], dense[2], E,
            use_pallas, on_tpu, use_attest).check_batch
    else:
        check_batch = _kernel_cached(name, frontier, slots, E,
                                     _pack_params(srange, slots),
                                     use_pallas, on_tpu,
                                     use_attest).check_batch

    # check_vma=False: the kernel's inner lax loops create fresh constants
    # whose varying-manual-axes tags can't match the sharded carries; the
    # math is still replication-safe (the only cross-shard op is the psum).
    try:
        shard_map = partial(jax.shard_map, check_vma=False)
    except AttributeError:
        from jax.experimental.shard_map import shard_map as _sm
        shard_map = partial(_sm, check_rep=False)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis)),
             out_specs=(P(), P(axis), P(axis), P()))
    def run(x, n, s0):
        ok, death, overflow, max_count, att = check_batch(x, n, s0)
        # every shard's verdict, reduced over ICI: 1 iff all keys valid
        bad = (~ok).sum()
        total_bad = jax.lax.psum(bad, axis)
        # attestation accumulators reduced the same way: the host
        # checks one scalar per group instead of gathering per-key atts
        total_att = jax.lax.psum(att.sum(), axis)
        return (total_bad == 0)[None], ok, overflow, total_att[None]

    return jax.jit(run)


def check_batch_sharded(model, hists: list, mesh=None, axis: str = "keys",
                        frontier: int = 1024, slots: int = 32,
                        engine: str = "auto",
                        dense_slot_cap: int | None = None,
                        pallas=None, return_info: bool = False,
                        max_recovery_retries: int | None = None):
    """Recovery wrapper around _check_batch_sharded_once (which holds
    the sharding contract — see its docstring): a classified backend
    fault re-runs the dispatch down the standard ladder, the OOM rung
    splits the key batch in half (each half re-shards over the same
    mesh), and the final rung delegates every key to
    analysis_tpu_batch — whose own ladder ends at the host mirror —
    so an exhausted sharded ladder still yields verdicts. Keys the
    fallback could not decide report False under the boolean contract
    (conservative: unverified, not a proven anomaly) and are named in
    info['unknown-keys'] with info['degraded']=True. The trail is
    surfaced via return_info=True (info['recovered'], or
    info['recovery-failed'] when verdicts were lost)."""
    kw = dict(mesh=mesh, axis=axis, frontier=frontier, slots=slots,
              engine=engine, dense_slot_cap=dense_slot_cap,
              pallas=pallas)
    trail = _RecoveryTrail(max_recovery_retries)
    while True:
        try:
            all_ok, per_key, info = _check_batch_sharded_once(
                model, hists, return_info=True, **kw)
        except RuntimeError as e:
            if not trail.absorb(e, "sharded"):
                # hand the batch fallback the rung-mutated knobs, not
                # the originals — a persistent compile fault already
                # taught this ladder pallas=False; re-learning it
                # would burn the batch entry's own retry budget
                subs = analysis_tpu_batch(
                    model, hists, frontier=frontier, slots=slots,
                    engine=kw["engine"],
                    dense_slot_cap=kw["dense_slot_cap"],
                    pallas=kw["pallas"],
                    max_recovery_retries=max_recovery_retries)
                per_key = np.asarray(
                    [r["valid?"] is True for r in subs], bool)
                info = {"groups": []}
                trail_d = {"faults": list(trail.faults),
                           "retries": len(trail.faults),
                           "fallback": "batch"}
                unknown = [i for i, r in enumerate(subs)
                           if r.get("valid?") not in (True, False)]
                if unknown:
                    # keys the fallback never decided (over the host
                    # cap + spent budget): the boolean contract has no
                    # third value, so per_key conservatively reports
                    # them False — but they are NOT proven anomalies.
                    # Surface the distinction for return_info callers
                    # and keep the trail under recovery-failed (this
                    # aggregate lost verdicts: degraded, not recovered)
                    log.warning(
                        "sharded: %d key(s) undecided after the "
                        "recovery budget; per-key False for them is "
                        "'unverified', not a found anomaly: %s",
                        len(unknown), unknown)
                    info["degraded"] = True
                    info["unknown-keys"] = unknown
                    info["recovery-failed"] = trail_d
                else:
                    info["recovered"] = trail_d
                all_ok = bool(per_key.all())
                break
            kind = trail.faults[-1]
            if kind == FAULT_OOM and len(hists) > 1:
                mid = len(hists) // 2
                log.warning("sharded: splitting %d keys into %d + %d "
                            "after OOM", len(hists), mid,
                            len(hists) - mid)
                l_ok, l_pk, l_info = check_batch_sharded(
                    model, hists[:mid], return_info=True,
                    max_recovery_retries=max_recovery_retries, **kw)
                r_ok, r_pk, r_info = check_batch_sharded(
                    model, hists[mid:], return_info=True,
                    max_recovery_retries=max_recovery_retries, **kw)
                per_key = np.concatenate([l_pk, r_pk])

                def _half_faults(i):
                    # a half's trail lives under 'recovered' when it
                    # healed, 'recovery-failed' when it fell off
                    return list((i.get("recovered")
                                 or i.get("recovery-failed")
                                 or {}).get("faults", []))

                faults = list(trail.faults) \
                    + _half_faults(l_info) + _half_faults(r_info)
                trail_d = {"faults": faults, "retries": len(faults),
                           "split": True}
                info = {"groups": l_info["groups"] + r_info["groups"]}
                unknown = list(l_info.get("unknown-keys", [])) \
                    + [mid + i for i in r_info.get("unknown-keys", [])]
                if l_info.get("degraded") or r_info.get("degraded"):
                    # a half lost verdicts: the aggregate is degraded,
                    # not recovered — keep the undecided-key list
                    # (right half re-indexed) so per-key False stays
                    # distinguishable from a found anomaly
                    info["degraded"] = True
                    if unknown:
                        info["unknown-keys"] = unknown
                    info["recovery-failed"] = trail_d
                else:
                    info["recovered"] = trail_d
                all_ok = bool(l_ok and r_ok)
                break
            _apply_recovery_rung(kind, kw)
            continue
        if trail.faults:
            info = dict(info)
            info["recovered"] = {"faults": list(trail.faults),
                                 "retries": len(trail.faults)}
        break
    if return_info:
        return all_ok, per_key, info
    return all_ok, per_key


def _check_batch_sharded_once(model, hists: list, mesh=None,
                              axis: str = "keys",
                              frontier: int = 1024, slots: int = 32,
                              engine: str = "auto",
                              dense_slot_cap: int | None = None,
                              pallas=None, return_info: bool = False):
    """Shard a batch of independent histories across a device mesh and
    reduce the aggregate verdict with a psum-OR over ICI.

    Returns (all_valid: bool, per_key_ok: np.ndarray[bool]). The per-key
    verdicts stay sharded until fetched; the scalar verdict is computed
    with an explicit collective so multi-chip runs never gather full
    frontiers to one chip.

    engine / dense_slot_cap / pallas: the same autoselect knobs as
    analysis_tpu, applied per dispatch group. return_info=True appends
    a third element: {'groups': [{family, dedup, keys, slots}, ...]} —
    which engine each slot-bucketed group actually ran (bench artifacts
    report this).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    name = model.device_model
    if mesh is None:
        devs = np.array(jax.devices())
        mesh = Mesh(devs, (axis,))
    n_dev = mesh.shape[axis]
    k = len(hists)
    if k == 0:
        if return_info:
            return True, np.zeros(0, bool), {"groups": []}
        return True, np.zeros(0, bool)
    pad_k = -(-k // n_dev) * n_dev

    all_ops = [encode_ops_for_model(model, h) for h in hists]
    # OpArray exposes the same f/a/b arrays _state_range reads, so
    # eligibility costs no extra stream builds
    srange = _state_range(name, model, all_ops)
    p_req = [required_slots(ops) for ops in all_ops]

    # Slot-bucketed dispatch groups (see _dispatch_groups): on the
    # hazelcast bench shape (100 keys, ~2.5 crashes/key) the max-padded
    # table sums to 14x the per-key need; grouping recovers it for a
    # couple of extra sub-ms dispatches.
    dense_groups, sort_idx = _dispatch_groups(
        srange, p_req, engine,
        n_events=max((event_count(o) for o in all_ops), default=1),
        frontier=frontier, dense_slot_cap=dense_slot_cap, pallas=pallas)
    group_info: list[dict] = []

    def run_group(idx: list[int], dense):
        """One vmapped + mesh-sharded dispatch over the keys in idx."""
        if dense is not None:
            g_slots = dense[2]
        else:
            # the sort group sizes itself to its own keys — never below
            # the caller's slots, never a SlotOverflow on a key the
            # dense caps rejected
            g_slots = max(slots, _bucket(max(p_req[i] for i in idx),
                                         lo=8))
        steps_list = [build_steps(all_ops[i], g_slots) for i in idx]
        E = _bucket(max(max(st.n for st in steps_list), 1))
        w = steps_list[0].w
        gk = len(idx)
        g_pad = -(-gk // n_dev) * n_dev
        padded = [st.pad_to(E) for st in steps_list]
        padded += [Steps.empty(w, E)] * (g_pad - gk)

        group_info.append({
            "family": "dense" if dense is not None else "sort",
            "dedup": (DEDUP_NONE if dense is not None else
                      dedup_engine(frontier, g_slots,
                                   _pack_params(srange, g_slots),
                                   pallas)),
            "keys": gk, "slots": g_slots})
        run = _sharded_runner(name, dense, frontier, g_slots, srange,
                              E, mesh, axis, pallas=pallas)
        maybe_inject_fault("sharded")
        x_np = np.stack([st.x for st in padded])
        xj = jnp.asarray(maybe_corrupt("sharded", x_np))
        # staged-buffer attestation: the digest reduction runs on the
        # SAME device buffer the sharded kernel consumes; its scalar
        # is fetched with the group's verdicts below, so detection
        # costs no extra sync
        att = None
        if attest_on:
            from . import abft
            att = (abft.digest_device(xj), abft.digest_host(x_np))
        # async dispatch: return the device arrays unfetched so every
        # group's kernel is enqueued before the first blocking fetch —
        # on a remote relay each synchronous fetch is a full
        # round-trip, so serializing dispatch+fetch per group would
        # re-add the latency the grouping saved
        all_ok_g, ok_g, ov_g, att_g = run(
            xj,
            jnp.asarray(np.asarray([st.n for st in padded], np.int32)),
            jnp.asarray(np.full(g_pad, model.device_state(), np.int32)))
        return all_ok_g, ok_g, ov_g, att_g, att

    attest_on = attest_enabled()
    pending = [(idx, run_group(idx, d))
               for d, idx in (dense_groups[pg]
                              for pg in sorted(dense_groups))]
    if sort_idx:
        pending.append((sort_idx, run_group(sort_idx, None)))
    per_key = np.zeros(k, bool)
    overflow = np.zeros(k, bool)
    all_ok = True
    for gi, (idx, handles) in enumerate(pending):
        t_fetch = _time.monotonic()
        all_ok_g, ok_g, ov_g, att_g, att = guarded_device_get(
            handles, site="sharded fetch")
        _M_CHUNK.labels(site="sharded",
                        family=group_info[gi]["family"]).observe(
            _time.monotonic() - t_fetch)
        _check_att(np.asarray(att_g)[0], "sharded")
        if att is not None:
            from . import abft
            abft.verify_steps("sharded", att[0], att[1])
        all_ok &= bool(np.asarray(all_ok_g)[0])
        per_key[idx] = np.asarray(ok_g)[:len(idx)]
        overflow[idx] = np.asarray(ov_g)[:len(idx)]
    _M_OPS.labels(site="sharded").inc(
        sum(len(o) for o in all_ops))
    # An 'invalid' under frontier overflow is unsound (the witness config
    # may have been dropped): escalate those keys — together, as one
    # vmapped batch at 4x the frontier (recursing upward), never a
    # serial per-key degradation — and report 'unknown' keys as invalid
    # here (the boolean contract has no third value).
    suspect = ~per_key & overflow
    if suspect.any():
        idx = np.flatnonzero(suspect)
        subs = analysis_tpu_batch(model, [hists[int(i)] for i in idx],
                                  frontier=frontier * 4, slots=slots,
                                  engine=engine,
                                  dense_slot_cap=dense_slot_cap,
                                  pallas=pallas)
        per_key = per_key.copy()
        for t, i in enumerate(idx):
            per_key[i] = subs[t]["valid?"] is True
        all_ok = bool(per_key.all())
    if return_info:
        info = {"groups": group_info}
        if attest_on:
            # steps: one staged-buffer digest per group; carry: one
            # psum-reduced att check per group (see _sharded_runner)
            info["attested"] = {"steps": len(pending),
                                "carry": len(pending)}
        return all_ok, per_key, info
    return all_ok, per_key
