"""TPU linearizability kernel: a JIT-linearization frontier search in XLA.

This replaces the reference's CPU-bound Knossos search (consumed via
`jepsen/src/jepsen/checker.clj:185-216`; `knossos.linear` / `knossos.wgl`),
which needs a 32 GB heap and "can take hours" on 10k-op histories. The
algorithm here is the same just-in-time linearization search, re-shaped for
a systolic/vector machine:

**Configurations are fixed-width.** A configuration is (model state: int32,
linearized-pending-ops bitmask: uint32[W]). Each in-flight operation holds a
*slot* in [0, P); slots are assigned host-side by scanning the history
(freed at completion, held forever by crashed :info ops), so the bitmask
width is bounded by real concurrency, not history length.

**The search is a frontier, not a stack.** The frontier is a dense array of
F configurations. We process history entries in order inside one
`lax.while_loop`:

  * *invoke*: the op occupies its slot. The frontier is closed under
    linearization (invariant), so only sequences beginning with the new op
    can add configurations: stage A linearizes just the new op against all
    F configs (one small sort to dedup); stage B repeatedly expands from
    freshly-added configs against all P pending slots (F*P candidates)
    until closure — in typical histories stage B's legality mask is empty
    and its sort never runs.
  * *complete*: every configuration must have linearized the op (its
    linearization point precedes its completion); survivors clear the bit
    and the slot is recycled.

Dedup is a multi-word lexicographic `lax.sort` + neighbor-equality mask;
stable sort with old-configs-first makes "new config" detection exact.
The history is linearizable iff any configuration survives every entry.

Soundness under resource caps: frontier overflow (> F live configs) only
*drops* candidate linearizations, so a 'valid' verdict is always sound; an
'invalid' verdict under overflow is reported as 'unknown' and escalated.
Slot overflow (> P concurrent+crashed pending ops) is detected host-side
before launch.

Batching: `vmap` over independent per-key histories;
`check_batch_sharded` shards the key axis over a `jax.sharding.Mesh` and
reduces verdicts with a psum-OR over ICI.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import heapq
import time as _time
from typing import Any, Callable

import numpy as np

from ..history import (F_CAS, F_READ, F_WRITE, KIND_OK, NIL, OpArray,
                       PENDING_RET, History, default_register_codec,
                       encode_ops, history as as_history)

# Entry kinds
E_INVOKE = 0
E_RETURN = 1
E_PAD = 2


class SlotOverflow(Exception):
    """More concurrent+crashed pending ops than the kernel's P slots."""


# ---------------------------------------------------------------------------
# Device models: vectorized step semantics (mirrors models.device_step_*)
# ---------------------------------------------------------------------------

def _register_step(cas_enabled: bool):
    def step(state, f, a, b):
        import jax.numpy as jnp
        legal = (f == F_READ) & ((a == NIL) | (state == a))
        legal = legal | (f == F_WRITE)
        if cas_enabled:
            cas_ok = (f == F_CAS) & (state == a)
            legal = legal | cas_ok
            new = jnp.where(f == F_WRITE, a, jnp.where(cas_ok, b, state))
        else:
            new = jnp.where(f == F_WRITE, a, state)
        return legal, new
    return step


def _mutex_step(state, f, a, b):
    # f: 0 = acquire, 1 = release. Outputs broadcast over state x f.
    import jax.numpy as jnp
    state, f = jnp.broadcast_arrays(state, f)
    legal = ((f == 0) & (state == 0)) | ((f == 1) & (state == 1))
    new = jnp.where(f == 0, jnp.ones_like(state), jnp.zeros_like(state))
    return legal, new


def mutex_codec(o: dict) -> tuple[int, int, int]:
    f = o["f"]
    if f == "acquire":
        return 0, NIL, NIL
    if f == "release":
        return 1, NIL, NIL
    raise ValueError(f"unknown mutex op f={f!r}")


# name -> (step fn, value codec, f-codes droppable when pending)
DEVICE_MODELS: dict[str, tuple[Callable, Callable, frozenset]] = {
    "cas-register": (_register_step(True), default_register_codec,
                     frozenset({F_READ})),
    "register": (_register_step(False), default_register_codec,
                 frozenset({F_READ})),
    "mutex": (_mutex_step, mutex_codec, frozenset()),
}


# ---------------------------------------------------------------------------
# Host preprocessing: ops -> entry stream with slot assignment
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Entries:
    """The kernel's input: the history as a stream of events.

    kind   int32[E] — E_INVOKE | E_RETURN | E_PAD
    slot   int32[E] — the op's slot
    f,a,b  int32[E] — op arguments (invoke entries)
    op_row int32[E] — row in the source OpArray (diagnostics)
    n      int      — live entries (<= E)
    """
    kind: np.ndarray
    slot: np.ndarray
    f: np.ndarray
    a: np.ndarray
    b: np.ndarray
    op_row: np.ndarray
    n: int

    def pad_to(self, e: int) -> "Entries":
        if len(self.kind) == e:
            return self
        assert len(self.kind) <= e, "cannot shrink entries"
        m = e - len(self.kind)

        def pad(x, fill):
            return np.concatenate(
                [x, np.full(m, fill, x.dtype)])
        return Entries(pad(self.kind, E_PAD), pad(self.slot, 0),
                       pad(self.f, 0), pad(self.a, NIL), pad(self.b, NIL),
                       pad(self.op_row, -1), self.n)

    @classmethod
    def empty(cls, e: int = 0) -> "Entries":
        z = np.zeros(0, np.int32)
        return cls(z, z, z, z, z, z, 0).pad_to(e)


def required_slots(ops: OpArray) -> int:
    """The peak number of simultaneously-pending ops (crashed ops pend
    forever) — the minimum slot count the kernel needs. Computing it up
    front avoids SlotOverflow escalation recompiles."""
    # same (position, order) tie-break as build_entries: invokes sort
    # before returns at equal positions
    events = []
    for r in range(len(ops)):
        events.append((int(ops.inv[r]), 0, 1))
        if ops.kind[r] == KIND_OK:
            events.append((int(ops.ret[r]), 1, -1))
    events.sort()
    cur = peak = 0
    for _, _, d in events:
        cur += d
        peak = max(peak, cur)
    return max(peak, 1)


def build_entries(ops: OpArray, p: int) -> Entries:
    """Lower an OpArray to an event stream, assigning each op a slot in
    [0, p). Raises SlotOverflow if concurrency + crashed ops exceed p."""
    events = []  # (position, order, kind, row)
    for r in range(len(ops)):
        events.append((int(ops.inv[r]), 0, E_INVOKE, r))
        if ops.kind[r] == KIND_OK:
            events.append((int(ops.ret[r]), 1, E_RETURN, r))
    events.sort()
    free = list(range(p))
    heapq.heapify(free)
    slot_of_row: dict[int, int] = {}
    kind, slot, f, a, b, op_row = [], [], [], [], [], []
    for _, _, k, r in events:
        if k == E_INVOKE:
            if not free:
                raise SlotOverflow(
                    f"more than {p} pending ops at op row {r} "
                    f"(crashed ops hold slots forever); raise p or check "
                    f"on the host")
            s = heapq.heappop(free)
            slot_of_row[r] = s
        else:
            s = slot_of_row.pop(r)
            heapq.heappush(free, s)
        kind.append(k)
        slot.append(s)
        f.append(int(ops.f[r]))
        a.append(int(ops.a[r]))
        b.append(int(ops.b[r]))
        op_row.append(r)
    i32 = np.int32
    return Entries(np.asarray(kind, i32), np.asarray(slot, i32),
                   np.asarray(f, i32), np.asarray(a, i32),
                   np.asarray(b, i32), np.asarray(op_row, i32),
                   len(kind))


def _stack(xs):
    import jax.numpy as jnp
    return jnp.asarray(np.stack(xs))


def _bucket(n: int, lo: int = 64) -> int:
    """Round up to a power of two to bound jit recompiles."""
    e = lo
    while e < n:
        e *= 2
    return e


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------

Kernel = collections.namedtuple(
    "Kernel", ["check", "check_batch", "check_chunk", "init_carry",
               "summarize"])


@functools.lru_cache(maxsize=32)
def _kernel(model_name: str, F: int, P: int, E: int):
    """Build the jitted checker for a (model, frontier-size, slots,
    entry-capacity) shape. Returns fn(entry arrays..., n_entries) ->
    (ok, death_entry, overflow, max_frontier)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    step = DEVICE_MODELS[model_name][0]
    W = max(1, (P + 31) // 32)
    u32 = jnp.uint32
    i32 = jnp.int32

    def bit_vec(slot):
        word = slot // 32
        bit = (slot % 32).astype(u32)
        return jnp.where(jnp.arange(W) == word,
                         jnp.left_shift(u32(1), bit), u32(0))

    def has_bit(masks, bv):
        return (masks & bv[None, :]).astype(jnp.bool_).any(axis=1)

    def dedup(masks, states, valid, origin):
        """Sort (N,)-rows lexicographically by (invalid, mask words, state);
        mark duplicate keys invalid (stable sort + old-configs-first makes
        the original config win); truncate to F.

        Returns (masks[F,W], states[F], valid[F], new[F], count, overflow).
        """
        invalid_key = (~valid).astype(u32)
        operands = [invalid_key] + [masks[:, w] for w in range(W)] \
            + [states, origin.astype(i32)]
        out = lax.sort(operands, num_keys=W + 2, is_stable=True)
        inv_s, ms, st_s, org_s = out[0], out[1:1 + W], out[1 + W], out[2 + W]

        def neq_prev(x):
            return jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), x[1:] != x[:-1]])
        first = neq_prev(inv_s) | neq_prev(st_s)
        for mw in ms:
            first = first | neq_prev(mw)
        valid_s = (inv_s == 0) & first
        overflow = valid_s[F:].any() if len(inv_s) > F else jnp.bool_(False)
        masks_f = jnp.stack([mw[:F] for mw in ms], axis=1)
        states_f = st_s[:F]
        valid_f = valid_s[:F]
        new_f = valid_f & (org_s[:F] == 1)
        return masks_f, states_f, valid_f, new_f, valid_f.sum(), overflow

    def expand_full(masks, states, valid, new, slot_f, slot_a, slot_b,
                    slot_occ, overflow):
        """Stage B: close the frontier under linearization, expanding only
        from freshly-added configs each round."""

        def cond(c):
            return c[3].any() & ~c[5]  # any new configs & not converged

        def body(c):
            masks, states, valid, new, overflow, _ = c
            # candidates: new configs x all pending slots
            legal, cstate = step(states[:, None], slot_f[None, :],
                                 slot_a[None, :], slot_b[None, :])
            bit = jnp.left_shift(
                u32(1), (jnp.arange(P, dtype=u32) % 32))          # (P,)
            word = jnp.arange(P) // 32                             # (P,)
            bitmat = jnp.where(word[:, None] == jnp.arange(W)[None, :],
                               bit[:, None], u32(0))               # (P,W)
            already = (masks[:, None, :] & bitmat[None, :, :]) \
                .astype(jnp.bool_).any(-1)                         # (F,P)
            legal = legal & valid[:, None] & new[:, None] \
                & slot_occ[None, :] & ~already
            any_legal = legal.any()

            def do_sort(_):
                cmasks = (masks[:, None, :] | bitmat[None, :, :]) \
                    .reshape(F * P, W)
                cstates = cstate.reshape(F * P)
                cvalid = legal.reshape(F * P)
                all_masks = jnp.concatenate([masks, cmasks])
                all_states = jnp.concatenate([states, cstates])
                all_valid = jnp.concatenate([valid, cvalid])
                origin = jnp.concatenate(
                    [jnp.zeros(F, jnp.bool_), jnp.ones(F * P, jnp.bool_)])
                m2, s2, v2, n2, cnt2, ovf2 = dedup(
                    all_masks, all_states, all_valid, origin)
                grew = n2.any()
                return m2, s2, v2, n2, overflow | ovf2, ~grew

            def no_sort(_):
                # Derive constants from varying operands so both cond
                # branches carry the same manual-axes tags under shard_map.
                return masks, states, valid, \
                    valid & False, overflow, any_legal | True

            return lax.cond(any_legal, do_sort, no_sort, None)

        masks, states, valid, new, overflow, _ = lax.while_loop(
            cond, body, (masks, states, valid, new, overflow,
                         jnp.bool_(False)))
        return masks, states, valid, overflow

    def init_carry(init_state):
        masks0 = jnp.zeros((F, W), u32)
        states0 = jnp.full((F,), init_state, i32)
        valid0 = jnp.zeros((F,), jnp.bool_).at[0].set(True)
        return (i32(0), masks0, states0, valid0,
                jnp.zeros((P,), i32), jnp.full((P,), NIL, i32),
                jnp.full((P,), NIL, i32), jnp.zeros((P,), jnp.bool_),
                jnp.bool_(False), i32(1), i32(1))

    def summarize(carry):
        (e, _m, _s, _valid, *_slots, overflow, count, max_count) = carry
        ok = count > 0
        death = jnp.where(ok, i32(-1), e - 1)
        return ok, death, overflow, max_count

    def run_range(ek, es, ef, ea, eb, stop, carry):
        """Advance the search from carry's position up to entry `stop`
        (or until the frontier dies). Bounded-duration device work: long
        histories run as a sequence of these calls with the frontier
        carried between them — which is also the checkpoint for
        long searches (the carry round-trips through host memory)."""
        def invoke_entry(e, masks, states, valid, slot_f, slot_a, slot_b,
                         slot_occ, overflow):
            s, f, a, b = es[e], ef[e], ea[e], eb[e]
            slot_f = slot_f.at[s].set(f)
            slot_a = slot_a.at[s].set(a)
            slot_b = slot_b.at[s].set(b)
            slot_occ = slot_occ.at[s].set(True)
            # stage A: linearize just the new op
            legal, nstate = step(states, f, a, b)
            bv = bit_vec(s)
            cvalid = valid & legal & ~has_bit(masks, bv)
            all_masks = jnp.concatenate([masks, masks | bv[None, :]])
            all_states = jnp.concatenate([states, nstate])
            all_valid = jnp.concatenate([valid, cvalid])
            origin = jnp.concatenate(
                [jnp.zeros(F, jnp.bool_), jnp.ones(F, jnp.bool_)])
            masks, states, valid, new, _, ovf = dedup(
                all_masks, all_states, all_valid, origin)
            overflow = overflow | ovf
            # stage B: chase enabled chains
            masks, states, valid, overflow = expand_full(
                masks, states, valid, new, slot_f, slot_a, slot_b,
                slot_occ, overflow)
            return masks, states, valid, slot_f, slot_a, slot_b, slot_occ, \
                overflow

        def return_entry(e, masks, states, valid, slot_f, slot_a, slot_b,
                         slot_occ, overflow):
            s = es[e]
            bv = bit_vec(s)
            valid = valid & has_bit(masks, bv)
            masks = masks & ~bv[None, :]
            slot_occ = slot_occ.at[s].set(False)
            masks, states, valid, _, _, ovf = dedup(
                masks, states, valid, jnp.zeros(F, jnp.bool_))
            return masks, states, valid, slot_f, slot_a, slot_b, slot_occ, \
                overflow | ovf

        def noop_entry(e, *c):
            return c

        def cond(c):
            return (c[0] < stop) & (c[9] > 0)

        def body(c):
            (e, masks, states, valid, slot_f, slot_a, slot_b, slot_occ,
             overflow, count, max_count) = c
            out = lax.switch(
                ek[e],
                [lambda args: invoke_entry(e, *args),
                 lambda args: return_entry(e, *args),
                 lambda args: noop_entry(e, *args)],
                (masks, states, valid, slot_f, slot_a, slot_b, slot_occ,
                 overflow))
            (masks, states, valid, slot_f, slot_a, slot_b, slot_occ,
             overflow) = out
            count = valid.sum().astype(i32)
            return (e + 1, masks, states, valid, slot_f, slot_a, slot_b,
                    slot_occ, overflow, count,
                    jnp.maximum(max_count, count))

        return lax.while_loop(cond, body, carry)

    def make_check(ek, es, ef, ea, eb, n_entries, init_state):
        return summarize(run_range(ek, es, ef, ea, eb, n_entries,
                                   init_carry(init_state)))

    @jax.jit
    def check(ek, es, ef, ea, eb, n_entries, init_state):
        return make_check(ek, es, ef, ea, eb, n_entries, init_state)

    @jax.jit
    def check_batch(ek, es, ef, ea, eb, n_entries, init_state):
        return jax.vmap(make_check)(ek, es, ef, ea, eb, n_entries,
                                    init_state)

    @jax.jit
    def check_chunk(ek, es, ef, ea, eb, stop, carry):
        return run_range(ek, es, ef, ea, eb, stop, carry)

    return Kernel(check, check_batch, check_chunk, init_carry, summarize)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def encode_ops_for_model(model, hist) -> OpArray:
    """Encode a history with the model's value codec, honoring the model's
    rules about which pending ops are droppable."""
    name = model.device_model
    if name is None or name not in DEVICE_MODELS:
        raise ValueError(f"model {model!r} has no device form")
    _, codec, droppable = DEVICE_MODELS[name]
    return encode_ops(as_history(hist), codec, droppable)


def analysis_tpu(model, hist, frontier: int = 256, slots: int | None = None,
                 max_frontier: int = 65536,
                 chunk_entries: int = 4096,
                 budget_s: float | None = None,
                 cancel=None,
                 explain: bool = True,
                 slot_overflow_fallback: bool = True) -> dict:
    """Check one history on the device. The slot count is sized to the
    history's actual peak concurrency; long histories run as a sequence
    of bounded-duration chunked kernel calls with the frontier carried
    (and checkpointable) between them, so a 100k-op search never holds
    the device in one multi-minute call. Escalates the frontier on
    overflow-with-invalid (a dropped config could have been the
    witness); falls back to the host search past 256 slots.

    budget_s caps total wall time: past it, an undecided search returns
    'unknown' instead of escalating further (histories with many
    crashed mutating ops are genuinely exponential — the reference's
    checker hits the same wall as an OOM or its 1 h timeout).

    cancel: zero-arg callable polled between chunks — truthy stops the
    search with 'unknown' (competition racing). explain: on a definite
    invalid verdict, re-run the host oracle on the prefix ending at the
    culprit op to reconstruct configs and final-paths (the reference
    renders these via knossos.linear.report, `checker.clj:205-216`)."""
    import jax.numpy as jnp

    t0 = _time.monotonic()
    name = model.device_model
    ops = encode_ops_for_model(model, hist)
    if slots is None:
        slots = _bucket(required_slots(ops), lo=8)
    try:
        entries = build_entries(ops, slots)
    except SlotOverflow:
        # caller-supplied slots too small: size from the history
        slots = _bucket(required_slots(ops), lo=8)
        if slots <= 256:
            entries = build_entries(ops, slots)
    if slots > 256:
        if not slot_overflow_fallback:
            # competition racing: a parallel host thread is already
            # running this search — don't duplicate it
            return {"valid?": "unknown", "analyzer": "tpu-wgl",
                    "error": f"slot overflow ({slots} slots needed)"}
        from .linear import analysis_host
        a = analysis_host(model, hist, budget_s=budget_s, cancel=cancel)
        a["analyzer"] = "host-jit-linear (slot overflow)"
        return a
    E = _bucket(max(entries.n, 1))
    entries = entries.pad_to(E)
    args = (jnp.asarray(entries.kind), jnp.asarray(entries.slot),
            jnp.asarray(entries.f), jnp.asarray(entries.a),
            jnp.asarray(entries.b))
    F = frontier
    timed_out = cancelled = False
    while True:
        k = _kernel(name, F, slots, E)
        carry = k.init_carry(jnp.int32(model.device_state()))
        e = 0
        while e < entries.n:
            stop = min(e + chunk_entries, entries.n)
            carry = k.check_chunk(*args, jnp.int32(stop), carry)
            e = stop
            if int(carry[-2]) == 0:   # frontier died: definite verdict
                break
            # only give up when chunks remain — a search that just
            # finished is definitive regardless of elapsed time
            if e < entries.n:
                if budget_s is not None and \
                        _time.monotonic() - t0 > budget_s:
                    timed_out = True
                    break
                if cancel is not None and cancel():
                    timed_out = cancelled = True
                    break
        ok, death, overflow, max_count = k.summarize(carry)
        ok = bool(ok) and not timed_out
        overflow = bool(overflow) or timed_out
        if ok or not overflow or F >= max_frontier or timed_out:
            break
        F *= 4  # invalid + overflow: the witness may have been dropped
    out = {
        "valid?": (True if ok else
                   "unknown" if overflow else False),
        "analyzer": "tpu-wgl",
        "op-count": len(ops),
        "max-frontier": int(max_count),
        "frontier-size": F,
        "duration-ms": (_time.monotonic() - t0) * 1e3,
        "configs": [],
        "final-paths": [],
    }
    if not ok:
        if cancelled:
            out["error"] = "search cancelled (competition loser)"
        elif timed_out:
            out["error"] = (
                f"search exceeded the {budget_s} s budget at frontier "
                f"{F}; verdict unknown")
        elif overflow:
            # The death point is an artifact of dropped configs — do not
            # name a culprit op for an 'unknown' verdict.
            out["error"] = (
                f"frontier overflowed at {F} configs; verdict unknown "
                f"(re-run with a larger frontier or the host checker)")
        else:
            row = int(entries.op_row[int(death)]) if int(death) >= 0 else -1
            if row >= 0:
                src_index = int(ops.index[row])
                out["op"] = _find_op(hist, src_index)
                out["op-index"] = src_index
                if explain:
                    from .linear import explain_failure
                    ex = explain_failure(model, hist, src_index)
                    if ex is not None:
                        out["configs"] = ex["configs"]
                        out["final-paths"] = ex["final-paths"]
                        if ex.get("previous-ok") is not None:
                            out["previous-ok"] = ex["previous-ok"]
    return out


def _find_op(hist, index: int):
    """The completion op for the invocation with the given :index (the
    completion carries the observed value; knossos reports it too)."""
    hist = as_history(hist)
    if hist.ops and "index" not in hist.ops[0]:
        hist = hist.index()
    for pos, o in enumerate(hist.ops):
        if o.get("index") == index:
            comp = hist.completion(pos)
            return comp if comp is not None else o
    return None


def analysis_tpu_batch(model, hists: list, frontier: int = 1024,
                       slots: int = 32) -> list[dict]:
    """Check a batch of independent histories (e.g. per-key subhistories
    from the independent workload) in one vmapped device call."""
    import jax.numpy as jnp

    t0 = _time.monotonic()
    name = model.device_model
    all_entries = []
    host_fallback: dict[int, dict] = {}
    for i, h in enumerate(hists):
        ops = encode_ops_for_model(model, h)
        try:
            all_entries.append((i, ops, build_entries(ops, slots)))
        except SlotOverflow:
            a = analysis_tpu(model, h, frontier, slots * 2)
            host_fallback[i] = a
    results: list[dict | None] = [None] * len(hists)
    for i, a in host_fallback.items():
        results[i] = a
    if all_entries:
        E = _bucket(max(e.n for _, _, e in all_entries))
        padded = [e.pad_to(E) for _, _, e in all_entries]
        check_batch = _kernel(name, frontier, slots, E).check_batch
        ok, death, overflow, max_count = check_batch(
            _stack([e.kind for e in padded]),
            _stack([e.slot for e in padded]),
            _stack([e.f for e in padded]), _stack([e.a for e in padded]),
            _stack([e.b for e in padded]),
            jnp.asarray(np.asarray([e.n for e in padded], np.int32)),
            jnp.asarray(np.full(len(padded), model.device_state(),
                                np.int32)))
        ok = np.asarray(ok)
        death = np.asarray(death)
        overflow = np.asarray(overflow)
        for j, (i, ops, entries) in enumerate(all_entries):
            if bool(ok[j]):
                v: Any = True
            elif bool(overflow[j]):
                # escalate this key alone
                results[i] = analysis_tpu(model, hists[i], frontier * 4,
                                          slots)
                continue
            else:
                v = False
            r = {"valid?": v, "analyzer": "tpu-wgl-batch",
                 "op-count": len(ops),
                 "max-frontier": int(max_count[j]),
                 "configs": [], "final-paths": []}
            if v is False:
                row = int(entries.op_row[int(death[j])])
                if row >= 0:
                    src = int(ops.index[row])
                    r["op"] = _find_op(hists[i], src)
                    r["op-index"] = src
            results[i] = r
    dur = (_time.monotonic() - t0) * 1e3
    for r in results:
        if r is not None:
            r.setdefault("duration-ms", dur)
    return results  # type: ignore[return-value]


def check_batch_sharded(model, hists: list, mesh=None, axis: str = "keys",
                        frontier: int = 1024, slots: int = 32):
    """Shard a batch of independent histories across a device mesh and
    reduce the aggregate verdict with a psum-OR over ICI.

    Returns (all_valid: bool, per_key_ok: np.ndarray[bool]). The per-key
    verdicts stay sharded until fetched; the scalar verdict is computed
    with an explicit collective so multi-chip runs never gather full
    frontiers to one chip.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    name = model.device_model
    if mesh is None:
        devs = np.array(jax.devices())
        mesh = Mesh(devs, (axis,))
    n_dev = mesh.shape[axis]
    k = len(hists)
    if k == 0:
        return True, np.zeros(0, bool)
    pad_k = -(-k // n_dev) * n_dev

    entries_list = []
    for h in hists:
        ops = encode_ops_for_model(model, h)
        entries_list.append(build_entries(ops, slots))
    E = _bucket(max(max(e.n for e in entries_list), 1))
    padded = [e.pad_to(E) for e in entries_list]
    padded += [Entries.empty(E)] * (pad_k - k)

    from functools import partial

    check_batch = _kernel(name, frontier, slots, E).check_batch

    # check_vma=False: the kernel's inner lax loops create fresh constants
    # whose varying-manual-axes tags can't match the sharded carries; the
    # math is still replication-safe (the only cross-shard op is the psum).
    try:
        shard_map = partial(jax.shard_map, check_vma=False)
    except AttributeError:
        from jax.experimental.shard_map import shard_map as _sm
        shard_map = partial(_sm, check_rep=False)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                       P(axis)),
             out_specs=(P(), P(axis), P(axis)))
    def run(ek, es, ef, ea, eb, n, s0):
        ok, death, overflow, max_count = check_batch(ek, es, ef, ea, eb,
                                                     n, s0)
        # every shard's verdict, reduced over ICI: 1 iff all keys valid
        bad = (~ok).sum()
        total_bad = jax.lax.psum(bad, axis)
        return (total_bad == 0)[None], ok, overflow

    all_ok, per_key, overflow = run(
        _stack([e.kind for e in padded]), _stack([e.slot for e in padded]),
        _stack([e.f for e in padded]), _stack([e.a for e in padded]),
        _stack([e.b for e in padded]),
        jnp.asarray(np.asarray([e.n for e in padded], np.int32)),
        jnp.asarray(np.full(pad_k, model.device_state(), np.int32)))
    all_ok = bool(np.asarray(all_ok)[0])
    per_key = np.asarray(per_key)[:k]
    overflow = np.asarray(overflow)[:k]
    # An 'invalid' under frontier overflow is unsound (the witness config
    # may have been dropped): escalate those keys individually, which
    # retries with growing frontiers and reports 'unknown' if still capped.
    suspect = ~per_key & overflow
    if suspect.any():
        per_key = per_key.copy()
        for i in np.flatnonzero(suspect):
            a = analysis_tpu(model, hists[int(i)], frontier * 4, slots)
            per_key[i] = a["valid?"] is True
        all_ok = bool(per_key.all())
    return all_ok, per_key
