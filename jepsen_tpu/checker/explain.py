"""Render the neighborhood of a nonlinearizable verdict as SVG.

The reference renders failing knossos analyses with
`knossos.linear.report/render-analysis!` into ``linear.svg``
(`jepsen/src/jepsen/checker.clj:205-212`). Here the renderer is
self-contained: a window of operations around the culprit, one row per
process, invoke->completion bars colored by completion type, the
culprit op highlighted, and the reconstructed final paths listed
beneath it.
"""

from __future__ import annotations

from html import escape

from ..history import history as as_history

_BAR_H = 18
_ROW_H = 26
_CHAR_W = 7
_COLORS = {"ok": "#6DB6FE", "info": "#FFAA26", "fail": "#FEB5DA"}


def _fmt(op: dict) -> str:
    v = op.get("value")
    return f"{op.get('f')} {v if v is not None else 'nil'}"


def render_failure_svg(hist, op_index: int, final_paths=(),
                       window: int = 20) -> str:
    """SVG for the ops surrounding the op with history :index
    `op_index` (the culprit). window = ops kept either side."""
    hist = as_history(hist)
    if hist.ops and "index" not in hist.ops[0]:
        hist = hist.index()
    pairs = []  # (invoke, completion|None)
    culprit_row = None
    open_by_process: dict = {}
    for pos, o in enumerate(hist.ops):
        t = o.get("type")
        p = o.get("process")
        if not isinstance(p, int):
            continue
        if t == "invoke":
            open_by_process[p] = (len(pairs), o)
            pairs.append([o, None])
        elif p in open_by_process:
            row, _inv = open_by_process.pop(p)
            pairs[row][1] = o
            if o.get("index") == op_index or \
                    pairs[row][0].get("index") == op_index:
                culprit_row = row
    if culprit_row is None:
        for row, (inv, _c) in enumerate(pairs):
            if inv.get("index") == op_index:
                culprit_row = row
    lo = max(0, (culprit_row or 0) - window)
    hi = min(len(pairs), (culprit_row or 0) + window + 1)
    shown = pairs[lo:hi]
    procs = sorted({p[0]["process"] for p in shown})
    prow = {p: i for i, p in enumerate(procs)}

    # layout: x by pair order inside the window (time is too bursty for
    # a linear scale to stay readable), y by process
    x_step = 84
    width = 120 + x_step * max(1, len(shown))
    height = 60 + _ROW_H * len(procs) + 18 * (len(final_paths) and
                                              (2 + sum(len(p) + 1 for p in
                                                       final_paths)))
    svg = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="monospace" font-size="11">',
           '<text x="8" y="16" font-size="13">nonlinearizable — ops '
           f'around culprit index {op_index}</text>']
    for p, r in prow.items():
        svg.append(f'<text x="8" y="{46 + r * _ROW_H + 12}">'
                   f'p{escape(str(p))}</text>')
    for i, (inv, comp) in enumerate(shown):
        r = prow[inv["process"]]
        x = 60 + i * x_step
        y = 42 + r * _ROW_H
        typ = (comp or {}).get("type", "info")
        color = _COLORS.get(typ, "#dddddd")
        is_culprit = (lo + i) == culprit_row
        stroke = ' stroke="#d32f2f" stroke-width="3"' if is_culprit else \
            ' stroke="#999" stroke-width="1"'
        svg.append(f'<rect x="{x}" y="{y}" width="{x_step - 6}" '
                   f'height="{_BAR_H}" rx="3" fill="{color}"{stroke}>'
                   f'<title>{escape(str(inv))} -> {escape(str(comp))}'
                   f'</title></rect>')
        label = _fmt(comp or inv)[:11]
        svg.append(f'<text x="{x + 3}" y="{y + 13}">'
                   f'{escape(label)}</text>')
    y = 42 + _ROW_H * len(procs) + 24
    if final_paths:
        svg.append(f'<text x="8" y="{y}" font-size="12">final paths '
                   '(legal linearizations ending at the failure):</text>')
        y += 18
        for path in final_paths:
            for step in path:
                op = step.get("op") or {}
                svg.append(
                    f'<text x="24" y="{y}">{escape(_fmt(op))} '
                    f'&#8594; {escape(str(step.get("model")))}</text>')
                y += 18
            y += 18
    svg.append("</svg>")
    return "\n".join(svg)


def write_failure_svg(test, opts, analysis: dict, hist) -> str | None:
    """Write linear.svg (linear-<key>.svg under the independent checker,
    so concurrent per-key failures don't clobber each other) into the
    test's store directory for a definite invalid analysis carrying an
    op-index. Only writes for real runs — a test map with both a name
    and a start-time (`core.run!` sets it); ad-hoc checker calls stay
    side-effect-free. Returns the path or None."""
    if analysis.get("valid?") is not False or \
            "op-index" not in analysis or not test.get("name") or \
            not test.get("start-time"):
        return None
    from .perf import out_path
    svg = render_failure_svg(hist, analysis["op-index"],
                             analysis.get("final-paths") or ())
    key = (opts or {}).get("history-key")
    fname = f"linear-{key}.svg" if key is not None else "linear.svg"
    p = out_path(test, opts, fname)
    with open(p, "w") as f:
        f.write(svg)
    return p
