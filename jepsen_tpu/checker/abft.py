"""ABFT attestation: host-verifiable digests over staged buffers and
device carries.

The recovery ladder (doc/robustness.md) handles *loud* backend faults;
this module closes the silent half: a bit-flip in a staged step
buffer, in HBM under a live carry, or on the fetch path would
otherwise yield a confidently wrong verdict. Following GCN-ABFT
(arXiv 2412.18534), every guarded value is covered by a cheap
checksum computed twice through independent paths:

  * **Staged-buffer digests.** The host computes a position-weighted
    wrap-around int32 digest over the canonical numpy buffer; a tiny
    jitted reduction computes the same digest over the device copy.
    Disagreement means the data was corrupted between staging and the
    kernel's first read — the exact window a DMA/HBM flip occupies.
    Both sides run the identical modular arithmetic (sums and
    products mod 2^32 are independent of intermediate wrap points),
    so a mismatch is never a rounding artifact and any single flipped
    bit changes the digest.
  * **Carry digests.** The kernels expose ``Kernel.digest(carry)`` —
    an on-device mix over the carry arrays (including the in-kernel
    ``att`` invariant accumulator). At chunk boundaries where the
    carry is fetched anyway (stream checkpoints, offline summarize)
    the host recomputes the mix from the fetched arrays: a mismatch
    means the carry changed between the device's reduction and the
    fetch. ``verify_carry`` additionally checks the structural
    invariants the host can see (att == 0, count == live-config
    population).

A mismatch raises ``_platform.CorruptDeviceResult`` (fault kind
``corrupt``), which climbs the existing recovery ladder: offline /
batch / sharded entries re-stage from canonical host data, streams
restore the last carry checkpoint and replay the steps log — silent
corruption becomes a *resumed* verdict instead of a wrong one.

Float buffers (the Elle adjacency stacks) are digested over their BIT
PATTERNS (bitcast to int32), so detection is exact there too — no
float-tolerance window for a low-mantissa flip to hide in.
"""

from __future__ import annotations

import functools

import numpy as np

from .. import telemetry as _telemetry
from .._platform import CorruptDeviceResult

_M_VERIFY = _telemetry.counter(
    "jepsen_tpu_abft_verifications_total",
    "ABFT digest verifications by kind (steps = staged buffers, "
    "carry = fetched carries)", ("kind",))
_M_FAIL = _telemetry.counter(
    "jepsen_tpu_abft_failures_total",
    "ABFT attestation mismatches (silent corruption detected)",
    ("kind",))

_MASK = 0xFFFFFFFF
# position weight period: coprime-ish to power-of-two shapes so equal
# elements at different offsets contribute distinct terms
_W_PERIOD = 8191


def _to_i32(x: int) -> int:
    """Wrap a python int to signed 32-bit (the device digest dtype)."""
    x &= _MASK
    return x - (1 << 32) if x >= (1 << 31) else x


@functools.lru_cache(maxsize=None)
def _weights64(n: int) -> np.ndarray:
    return (np.arange(n, dtype=np.int64) % _W_PERIOD) + 1


def digest_host(arr: np.ndarray) -> int:
    """Position-weighted digest of a host buffer, as signed int32.

    Computed in int64 and masked: sums/products mod 2^32 match the
    device's wrapping int32 arithmetic exactly, regardless of where
    the intermediate wraps land."""
    a = np.asarray(arr)
    if a.dtype.kind == "f":
        a = a.view(np.int32)       # bit pattern, not value
    elif a.dtype == np.uint32:
        a = a.view(np.int32)
    flat = a.astype(np.int64, copy=False).reshape(-1)
    if flat.size == 0:
        return 0
    return _to_i32(int((flat * _weights64(flat.size)).sum()))


@functools.cache
def _digest_dev_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def digest(x):
        if x.dtype in (jnp.float32, jnp.uint32):
            x = jax.lax.bitcast_convert_type(x, jnp.int32)
        flat = x.astype(jnp.int32).reshape(-1)
        w = (jnp.arange(flat.shape[0], dtype=jnp.int32) % _W_PERIOD) + 1
        return jnp.sum(flat * w, dtype=jnp.int32)

    return digest


def digest_device(x):
    """Async device-side twin of digest_host over an already-staged
    device array. Returns an UNFETCHED scalar so callers can batch the
    sync with the fetch they were already doing."""
    return _digest_dev_fn()(x)


def note_failure(kind: str) -> None:
    """Count an attestation failure detected outside verify_* — the
    kernels' in-carry ``att`` accumulator read at summarize
    (wgl._check_att), which never fetches a whole carry."""
    _M_FAIL.labels(kind=kind).inc()


def verify_steps(site: str, fetched_digest, expected: int) -> None:
    """Compare a fetched device digest with the host's canonical one;
    raise CorruptDeviceResult on disagreement."""
    got = int(fetched_digest)
    _M_VERIFY.labels(kind="steps").inc()
    if got != expected:
        _M_FAIL.labels(kind="steps").inc()
        raise CorruptDeviceResult(
            site, f"staged-buffer digest {got} != host {expected} — "
                  f"the shipped buffer was corrupted in transit")


# ---------------------------------------------------------------------------
# Carry digests (host mirrors of Kernel.digest — see wgl._kernel*)
# ---------------------------------------------------------------------------

# per-component mixing primes, shared by the device digest builders in
# wgl.py and the host mirrors below: position i's component multiplies
# _PRIMES[i % len] before xor-folding
_PRIMES = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F,
           0x165667B1, 0x68B5A6D9, 0x7FEB352D, 0x846CA68B)


def prime_i32(i: int) -> int:
    return _to_i32(_PRIMES[i % len(_PRIMES)])


def _sum_i32(arr: np.ndarray) -> int:
    a = np.asarray(arr)
    if a.dtype == np.bool_:
        a = a.astype(np.int64)
    elif a.dtype.kind in "fu":
        a = a.view(np.int32) if a.dtype.itemsize == 4 \
            else a.astype(np.int64)
    return int(a.astype(np.int64, copy=False).sum())


def carry_digest_host(carry) -> int:
    """Recompute Kernel.digest's mix from a FETCHED host carry — the
    formula is xor-fold of (component wrap-sum * prime_i) over every
    carry element, scalars included, in carry order. Must stay in
    lockstep with the device builders in wgl.py."""
    h = 0
    for i, c in enumerate(carry):
        s = _sum_i32(c) * _PRIMES[i % len(_PRIMES)]
        h ^= s & _MASK
    return _to_i32(h)


def verify_carry(site: str, fetched_digest, carry_host,
                 att_index: int = -3) -> None:
    """Check a fetched carry against its device-computed digest plus
    the structural invariants the host can see:

      * digest parity — the carry arrays the device mixed are the
        arrays the host received (transfer/fetch integrity);
      * att == 0 — the kernel's in-loop invariant accumulator (dedup
        digest mismatches, frontier/table occupancy violations) never
        fired;
      * count == live population — carry[-2] must equal the popcount
        of the liveness structure the digest already covers (a flip
        in either is caught even when the digest round-trips clean,
        because count is re-derived, not copied).
    """
    got = int(fetched_digest)
    want = carry_digest_host(carry_host)
    _M_VERIFY.labels(kind="carry").inc()
    if got != want:
        _M_FAIL.labels(kind="carry").inc()
        raise CorruptDeviceResult(
            site, f"carry digest {got} != host recompute {want} — the "
                  f"fetched carry differs from the device's")
    att = int(np.asarray(carry_host[att_index]))
    if att != 0:
        _M_FAIL.labels(kind="carry").inc()
        raise CorruptDeviceResult(
            site, f"in-kernel attestation accumulator = {att} — a "
                  f"frontier/table invariant or dedup digest failed "
                  f"on device")
    count = int(np.asarray(carry_host[-2]))
    live = carry_host[1]            # masks (sort) / table (dense)
    if live.dtype == np.bool_:      # dense table: count == popcount
        pop = int(np.asarray(live).sum())
    else:                           # sort frontier: count == sum(valid)
        pop = int(np.asarray(carry_host[3]).sum())
    if count != pop:
        _M_FAIL.labels(kind="carry").inc()
        raise CorruptDeviceResult(
            site, f"carry count {count} != live population {pop}")
