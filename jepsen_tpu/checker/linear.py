"""Linearizability checking — host reference implementation.

This is the CPU oracle for the TPU kernels (checker/wgl.py): a just-in-time
linearization search in the style of knossos.linear (the reference consumes
knossos via `jepsen/src/jepsen/checker.clj:185-216`). The algorithm walks the
history entry by entry, maintaining the set of *configurations* — pairs of
(model state, subset of currently-pending operations already linearized).

  * at an invocation, the op joins the pending set (not yet linearized);
  * at an :ok completion of op i, configurations expand by linearizing any
    sequence of pending ops; configurations in which i has not linearized by
    its completion are killed (its linearization point must lie between
    invocation and completion);
  * :fail pairs never took effect and are excluded up front;
  * :info ops stay pending forever — they may linearize at any later point,
    or never (crashed reads constrain nothing and are dropped);
  * the history is linearizable iff a configuration survives every entry.

Works with arbitrary hashable models (models.Model). The TPU path handles
the enumerable-state models at scale; `linearizable()` dispatches.
"""

from __future__ import annotations

import time as _time

from .. import models as m
from ..history import DeviceEncodingError, History, \
    history as as_history, is_fail, is_info, is_invoke
from . import Checker, UNKNOWN


def _prepare(hist: History):
    """Lower an indexed client history to a list of entries:
    ('invoke', op_id, op) / ('ok', op_id, op). op_id is the invocation's
    history index; the op dict carries the authoritative value (completion
    value for :ok ops). Fail pairs and pending reads are dropped."""
    hist = as_history(hist).client_ops()
    pairs = hist.pair_index()
    entries = []
    for i, o in enumerate(hist.ops):
        if not is_invoke(o):
            continue
        j = pairs.get(i)
        comp = hist.ops[j] if j is not None else None
        if comp is not None and is_fail(comp):
            continue
        if comp is None or is_info(comp):
            if o["f"] in ("read", "r"):
                continue  # a pending read constrains nothing
            entries.append((i, None, dict(o)))
        else:
            op = dict(o)
            op["type"] = comp["type"]
            op["value"] = comp["value"]
            entries.append((i, j, op))
    # Emit in history order: invoke events at position i, ok events at j.
    events = []
    for i, j, op in entries:
        events.append((i, "invoke", i, op))
        if j is not None:
            events.append((j, "ok", i, op))
    events.sort(key=lambda e: e[0])
    return [(kind, op_id, op) for _, kind, op_id, op in events]


def _closure(configs: set, pending: dict) -> set:
    """All configurations reachable by linearizing pending ops in any
    order. A configuration is (model, frozenset-of-linearized-op-ids)."""
    stack = list(configs)
    seen = set(configs)
    while stack:
        model, lin = stack.pop()
        for op_id, op in pending.items():
            if op_id in lin:
                continue
            m2 = model.step(op)
            if m.is_inconsistent(m2):
                continue
            c = (m2, lin | {op_id})
            if c not in seen:
                seen.add(c)
                stack.append(c)
    return seen


def analysis_host(model: m.Model, hist, budget_s: float | None = None,
                  cancel=None) -> dict:
    """Run the JIT-linearization search on the host. Returns an analysis map
    with 'valid?' plus failure diagnostics.

    budget_s: wall-clock budget; past it the search stops with
    {'valid?': 'unknown'} (the reference bounds knossos the same way, via
    memory/`concurrency-limit`, `checker.clj:101-116`). cancel: optional
    zero-arg callable polled between events — truthy stops the search
    (used by competition racing, `checker.clj:199-203`)."""
    t0 = _time.monotonic()
    events = _prepare(as_history(hist).index())
    empty: frozenset = frozenset()
    configs: set = {(model, empty)}
    pending: dict[int, dict] = {}
    op_count = sum(1 for e in events if e[0] == "invoke")
    previous_ok = None
    processed = 0
    for kind, op_id, op in events:
        if budget_s is not None and _time.monotonic() - t0 > budget_s:
            # ops-processed lets callers extrapolate total runtime (a
            # lower bound: per-op cost is nondecreasing as the pending
            # set and config space grow)
            return {"valid?": UNKNOWN, "analyzer": "host-jit-linear",
                    "op-count": op_count, "cause": "budget exhausted",
                    "ops-processed": processed,
                    "duration-ms": (_time.monotonic() - t0) * 1e3}
        if cancel is not None and cancel():
            return {"valid?": UNKNOWN, "analyzer": "host-jit-linear",
                    "op-count": op_count, "cause": "cancelled",
                    "ops-processed": processed,
                    "duration-ms": (_time.monotonic() - t0) * 1e3}
        processed += kind == "invoke"
        if kind == "invoke":
            pending[op_id] = op
            continue
        # :ok completion — op_id must linearize by now.
        expanded = _closure(configs, pending)
        survivors = {(mod, lin) for (mod, lin) in expanded if op_id in lin}
        if not survivors:
            return {
                "valid?": False,
                "op": op,
                "op-index": op.get("index"),
                "previous-ok": previous_ok,
                "op-count": op_count,
                "analyzer": "host-jit-linear",
                "configs": [_config_info(c, pending)
                            for c in sorted(expanded,
                                            key=lambda c: -len(c[1]))[:10]],
                "final-paths": _final_paths(configs, pending, op, op_id),
                "duration-ms": (_time.monotonic() - t0) * 1e3,
            }
        del pending[op_id]
        configs = {(mod, lin - {op_id}) for (mod, lin) in survivors}
        previous_ok = op
    return {"valid?": True,
            "op-count": op_count,
            "analyzer": "host-jit-linear",
            "configs": [_config_info(c, pending)
                        for c in list(configs)[:10]],
            "final-paths": [],
            "duration-ms": (_time.monotonic() - t0) * 1e3}


def _brief(op: dict) -> dict:
    return {k: op.get(k) for k in ("index", "process", "f", "value")}


def _final_paths(configs: set, pending: dict, death_op: dict,
                 death_id: int, cap: int = 10,
                 max_steps: int = 6) -> list:
    """Reconstruct failure paths for a nonlinearizable verdict — the
    analog of knossos's final-paths (rendered by the reference at
    `checker.clj:205-216`). Each path is a sequence of
    {'op', 'model'} steps: a legal linearization of pending ops from a
    surviving configuration, ending with the failing attempt to
    linearize the culprit op and the resulting model inconsistency."""
    paths: list = []
    for mod, lin in sorted(configs, key=lambda c: -len(c[1]))[:cap]:
        if len(paths) >= cap:
            break
        avail = {i: op for i, op in pending.items()
                 if i not in lin and i != death_id}
        stack: list = [(mod, (), frozenset())]
        seen = set()
        while stack and len(paths) < cap:
            m0, steps, used = stack.pop()
            dm = m0.step(death_op)
            if m.is_inconsistent(dm):
                paths.append(
                    [*steps, {"op": _brief(death_op), "model": repr(dm)}])
            if len(steps) >= max_steps:
                continue
            for i, op in avail.items():
                if i in used:
                    continue
                m2 = m0.step(op)
                if m.is_inconsistent(m2):
                    continue
                key = (m2, used | {i})
                if key in seen:
                    continue
                seen.add(key)
                stack.append(
                    (m2, (*steps, {"op": _brief(op), "model": repr(m2)}),
                     used | {i}))
    return paths[:cap]


def explain_failure(model: m.Model, hist, op_index: int,
                    budget_s: float | None = 60.0) -> dict | None:
    """Host re-search of the history prefix ending at the culprit op's
    completion — reconstructs configs and final-paths for a device
    'invalid' verdict (the device kernel reports only the death op).
    Returns the host analysis, or None if the prefix can't be found or
    the budget expires."""
    hist = as_history(hist)
    if hist.ops and "index" not in hist.ops[0]:
        hist = hist.index()
    pos = None
    for i, o in enumerate(hist.ops):
        if o.get("index") == op_index:
            j = hist.pair_index().get(i)
            pos = j if j is not None else i
            break
    if pos is None:
        return None
    prefix = History(hist.ops[:pos + 1])
    a = analysis_host(model, prefix, budget_s=budget_s)
    if a["valid?"] is not False:
        return None
    return a


def _config_info(config, pending) -> dict:
    model, lin = config
    return {"model": repr(model),
            "pending": [pending[i] for i in sorted(lin) if i in pending],
            "linearized-pending": sorted(lin)}


class Linearizable(Checker):
    """Linearizability checker (reference checker.clj:185-216). Algorithms:

      'host'  — pure-Python JIT-linearization (any model)
      'tpu'   — JAX frontier-BFS kernel (enumerable-state models)
      'auto'  — tpu when the model has a device form, else host
      'competition' — race host against tpu in parallel; the first
                 definitive verdict wins and the loser is cancelled
                 (reference dispatch at checker.clj:199-203). Also the
                 natural home for histories that overflow device slots:
                 the host thread keeps going where the kernel gives up.
      'linear'/'wgl' — accepted aliases (reference names) for 'auto'.

    On a definite invalid verdict with an op-index, writes the failure
    neighborhood to linear.svg in the test's store directory (the
    reference renders knossos analyses the same way,
    checker.clj:205-212).

    Extra keyword options flow straight to the device engine
    (`wgl.analysis_tpu`), so the search heuristics are user-tunable the
    way knossos's memoization threshold should have been (its plan.md
    asks for this):

      engine='auto'|'dense'|'sort' — kernel family; 'auto' runs the
                     cost model (`wgl.select_engine`: state-range
                     width, slot count, history length, frontier)
      dense_slot_cap int — 'auto' never asks the dense table to absorb
                     more than this many slots (each slot doubles the
                     table; cap it when tail concurrency is known)
      pallas=True|False|None — force the Pallas kernel variants (dense
                     closure round, sort-family hash dedup) on/off;
                     None defers to the JEPSEN_TPU_PALLAS_* env gates
                     (default ON on real TPU backends)
      frontier / max_frontier / chunk_entries / budget_s — the sort
                     family's frontier sizing, escalation cap, device
                     call granularity, and wall-clock budget
      max_recovery_retries int — device-fault recovery budget: how
                     many classified backend faults (OOM / device
                     lost / compile / wedged / corrupt) the entry
                     absorbs and retries before taking its final rung
                     (host mirror under the size cap). Defaults to
                     wgl.MAX_RECOVERY_RETRIES; the test map's
                     'max-recovery-retries' (CLI
                     --max-recovery-retries) applies when the option
                     is unset here.
      tier='full'|'screen'|1 — tiered verification (checker/screen.py).
                     'screen' runs the O(n) invariant screen first and
                     the full search only on suspicion or a sampled
                     fraction; a screen pass returns a screened
                     verdict, an escalated result carries 'escalated'
                     with the screen's suspicion and the cost-model
                     pricing. The test map's 'tier' (CLI --tier)
                     applies when unset here.
      screen_sample float — sampled-escalation fraction for clean
                     histories at tier 1 (default
                     screen.DEFAULT_SAMPLE; test map 'screen-sample' /
                     CLI --screen-sample).

    e.g. ``linearizable({'model': m, 'engine': 'dense',
    'budget_s': 120})`` or ``linearizable(m, dense_slot_cap=12,
    pallas=True)``. Of these, only `pallas` reaches the online
    pipeline (checker/streaming.py picks its own engine from the
    test's declared `online-state-range`); the rest apply when the
    history is checked offline.
    """

    def __init__(self, model: m.Model, algorithm: str = "auto", **opts):
        assert model is not None, \
            "the linearizable checker requires a model"
        self.model = model
        self.algorithm = algorithm
        self.opts = opts

    def check(self, test, hist, opts):
        from . import screen as _screen
        tier = self.opts.get("tier", (test or {}).get("tier"))
        if _screen.tier_is_screen(tier):
            return self._tier1(test, hist, opts)
        return self._full_check(test, hist, opts)

    def _tier1(self, test, hist, opts):
        """The tiered pipeline: O(n) screen every history; run the
        full device search only on suspicion or a deterministic
        sampled fraction, priced through wgl.select_engine's cost
        model. See checker/screen.py for the screen's invariants and
        soundness posture."""
        from . import screen as _screen
        sc = self._streamed_screen(test, hist) \
            or _screen.screen_history(self.model, hist)
        price = _screen.price_escalation(self.model, hist)
        sample = self.opts.get("screen_sample")
        if sample is None:
            sample = (test or {}).get("screen-sample")
        if sample is None:
            sample = _screen.DEFAULT_SAMPLE
        esc, why = _screen.should_escalate(
            sc, sample=float(sample),
            cost=price["cost"] if price else None)
        if not esc:
            out = dict(sc)
            out["tier"] = 1
            return out
        full = self._full_check(test, hist, opts)
        full["escalated"] = _screen.escalation_record(sc, why, price)
        full["tier"] = 1
        return full

    def _streamed_screen(self, test, hist) -> dict | None:
        """A screen verdict the online pipeline already produced
        (maybe_online's 'screen-linear' target) — reused under the
        same coverage guards as _streamed_result."""
        r = ((test or {}).get("streamed-results") or {}) \
            .get("screen-linear")
        if not r or not r.get("screened"):
            return None
        if r.get("model") != repr(self.model):
            return None
        if r.get("history-len") != \
                len(as_history(hist).client_ops()):
            return None
        return dict(r)

    def _full_check(self, test, hist, opts):
        streamed = self._streamed_result(test, hist)
        if streamed is not None:
            # same post-processing as an offline verdict: a definite
            # invalid still renders its linear.svg failure neighborhood
            try:
                from .explain import write_failure_svg
                write_failure_svg(test or {}, opts, streamed, hist)
            except OSError:
                pass
            return streamed
        algo = self.algorithm
        if algo in ("linear", "wgl"):
            algo = "auto"
        elif algo == "tpu-wgl":
            algo = "tpu"
        if algo not in ("auto", "tpu", "host", "competition"):
            raise ValueError(f"unknown linearizability algorithm {algo!r}")
        kw = dict(self.opts)
        kw.pop("tier", None)           # tier knobs are this checker's,
        kw.pop("screen_sample", None)  # not the device engine's
        mrr = (test or {}).get("max-recovery-retries")
        if mrr is not None:
            kw.setdefault("max_recovery_retries", mrr)
        a = None
        if algo == "competition" and self.model.device_model is not None:
            a = self._compete(hist, kw)
        elif algo in ("auto", "tpu", "competition"):
            if self.model.device_model is not None:
                try:
                    from .wgl import analysis_tpu
                    a = analysis_tpu(self.model, hist, **kw)
                except ImportError:
                    if algo == "tpu":
                        raise
                except DeviceEncodingError:
                    # history exceeds the device encoding (e.g. g-set
                    # elements beyond the bitmask, crashed queue
                    # dequeues, values outside int32): the host model
                    # handles it
                    if algo == "tpu":
                        raise
            elif algo == "tpu":
                return {"valid?": UNKNOWN,
                        "error": f"model {self.model!r} has no device form"}
        if a is None:
            a = analysis_host(self.model, hist,
                              budget_s=self.opts.get("budget_s"))
        a = _truncate(a)
        try:
            from .explain import write_failure_svg
            write_failure_svg(test or {}, opts, a, hist)
        except OSError:  # unwritable store is not a checking failure
            pass
        return a

    def _streamed_result(self, test, hist) -> dict | None:
        """A verdict already produced by the online pipeline
        (core.run stashes it under test['streamed-results']) — reuse
        it instead of re-searching the same history, but only when it
        is definite and demonstrably covers this history (same client
        op count; post-hoc `analyze` may be handed a different one)
        AND this checker's model (a Compose can hold several
        Linearizable checkers — only the one whose model was streamed
        may reuse the verdict). An 'unknown' streamed verdict
        (frontier cap) re-checks offline, where the dense engine or
        host fallback may still decide it."""
        r = ((test or {}).get("streamed-results") or {}).get("linear")
        if not r or r.get("valid?") not in (True, False):
            return None
        if r.get("model") != repr(self.model):
            return None
        if r.get("history-len") != \
                len(as_history(hist).client_ops()):
            return None
        return _truncate(dict(r))

    def _compete(self, hist, base_opts: dict | None = None) -> dict:
        """Race the host search against the device kernel; first
        definitive (non-'unknown') verdict wins, loser is cancelled."""
        import queue as _queue
        import threading

        cancel = threading.Event()
        results: _queue.Queue = _queue.Queue()

        def run(name, fn):
            try:
                results.put((name, fn()))
            except Exception as e:  # noqa: BLE001 — loser may die racing
                results.put((name, {"valid?": UNKNOWN, "error": repr(e)}))

        from .wgl import analysis_tpu
        opts = dict(base_opts if base_opts is not None else self.opts)
        opts["explain"] = False  # explain after the race, not during it
        # on slot overflow the device path would duplicate the racing
        # host thread's search — make it concede instead
        opts["slot_overflow_fallback"] = False
        threads = [
            threading.Thread(
                target=run, daemon=True,
                args=("host", lambda: analysis_host(
                    self.model, hist, cancel=cancel.is_set))),
            threading.Thread(
                target=run, daemon=True,
                args=("tpu", lambda: analysis_tpu(
                    self.model, hist, cancel=cancel.is_set, **opts))),
        ]
        for t in threads:
            t.start()
        a = None
        for _ in threads:
            name, r = results.get()
            if r.get("valid?") != UNKNOWN:
                cancel.set()
                r["competition-winner"] = name
                if r["valid?"] is False and not r.get("final-paths") \
                        and "op-index" in r:
                    ex = explain_failure(self.model, hist, r["op-index"])
                    if ex is not None:
                        r["configs"] = ex["configs"]
                        r["final-paths"] = ex["final-paths"]
                return r
            a = r
        return a  # both indefinite


def _truncate(a: dict) -> dict:
    """Writing full configs/final-paths 'can take hours' — truncate to 10
    (reference checker.clj:213-216)."""
    a["final-paths"] = list(a.get("final-paths", []))[:10]
    a["configs"] = list(a.get("configs", []))[:10]
    return a


def linearizable(model_or_opts, algorithm: str = "auto", **opts) -> Checker:
    """Build a linearizability checker. Accepts linearizable(model) or the
    reference's map shape linearizable({'model': m, 'algorithm': 'wgl'})."""
    if isinstance(model_or_opts, dict):
        o = dict(model_or_opts)
        model = o.pop("model")
        algorithm = o.pop("algorithm", algorithm)
        opts = {**o, **opts}
    else:
        model = model_or_opts
    return Linearizable(model, algorithm, **opts)
