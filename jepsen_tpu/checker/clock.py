"""Clock-skew analysis: plots per-node clock offsets over time.

Reference: `jepsen/src/jepsen/checker/clock.clj` — any op carrying a
`clock-offsets` map (node -> offset seconds, emitted by the clock
nemesis's :check-offsets) contributes points; series render as step
functions, extended to the end of the history (:13-34).
"""

from __future__ import annotations

from .. import plot as gp
from .. import util
from ..history import history
from . import Checker
from .perf import out_path, polysort, with_nemeses


def history_to_datasets(hist) -> dict:
    """node -> [[t, offset], ...], each series extended to the final
    history time (`clock.clj:13-34`)."""
    hist = list(hist)
    if not hist:
        return {}
    final_time = util.nanos_to_secs(hist[-1].get("time", 0))
    series: dict = {}
    for op in hist:
        offsets = op.get("clock-offsets")
        if not offsets:
            continue
        t = util.nanos_to_secs(op.get("time", 0))
        for node, offset in offsets.items():
            series.setdefault(node, []).append([t, offset])
    return {node: pts + [[final_time, pts[-1][1]]]
            for node, pts in series.items()}


def short_node_names(nodes) -> list[str]:
    """Strip common trailing domains: n1.foo.com, n2.foo.com -> n1, n2
    (`clock.clj:36-45`)."""
    split = [list(reversed(str(n).split("."))) for n in nodes]
    prefix = util.longest_common_prefix(split)
    n = min(len(prefix), min((len(s) for s in split), default=1) - 1) \
        if split else 0
    return [".".join(reversed(s[n:])) for s in split]


def plot(test, hist, opts=None) -> dict:
    """Render clock-skew.svg from clock-offset ops
    (`clock.clj:47-75`)."""
    hist = history(hist)
    if len(hist):
        datasets = history_to_datasets(hist)
        nodes = polysort(datasets.keys())
        names = short_node_names(nodes)
        palette = ["#cc3333", "#3366cc", "#33aa33", "#aa33aa",
                   "#cc9933", "#33aaaa"]
        p = gp.Plot(title=f"{test.get('name', '')} clock skew",
                    ylabel="Skew (s)")
        for i, (node, name) in enumerate(zip(nodes, names)):
            if datasets[node]:
                p.series.append(gp.Series(
                    title=name, data=datasets[node],
                    color=palette[i % len(palette)], mode="steps",
                    line_width=1.5))
        if gp.has_data(p):
            with_nemeses(p, hist,
                         (test.get("plot") or {}).get("nemeses"))
            gp.write(p, out_path(test, opts, "clock-skew.svg"))
    return {"valid?": True}


class ClockPlot(Checker):
    """Checker wrapper (`checker.clj:831-837`)."""

    def check(self, test, hist, opts):
        return plot(test, hist, opts)


def clock_plot() -> Checker:
    return ClockPlot()
