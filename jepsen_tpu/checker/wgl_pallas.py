"""Pallas TPU kernel for the dense WGL closure round.

The dense engine's hot op is one closure round over the configuration
table: for every pending slot p,

    moved[q, c] = OR_s  M[p, s, q] AND table[s, c]        (transition)
    table      |= butterfly_p(moved)                      (set bit p)

XLA already fuses the einsum + butterfly well (`wgl.py:_dense_kernel`),
but the (P, S, C) `moved` intermediate can spill to HBM between the
product and the butterfly.  This kernel keeps the whole round in VMEM —
the table is at most DENSE_TABLE_CAP (= 2^22) bools, well under the
~16 MB VMEM budget — computing the P transition products and the
OR-accumulate in one pass with zero HBM round-trips.

Status: DEFAULT ON REAL TPU (opt-out JEPSEN_TPU_PALLAS_CLOSURE=0;
opt-in elsewhere with =1, which runs interpret mode off-TPU).
Hardware-measured on TPU v5 lite: 2x on the easy 10k-op headline
search (0.56 s -> 0.29 s) and 6.4x on the adversarial 8-crashed-writes
P=14 shape (4.8 s -> 0.75 s) versus the XLA formulation.  Correctness
is pinned against the XLA formulation by tests/test_wgl_pallas.py in
interpret mode and by an on-hardware (S, P) shape-matrix sweep.
Eligibility: the mask axis must fill the 128-lane tile (P >= 7), the
padded state axis must be a multiple of 8, and the working set must
fit VMEM (see MAX_VMEM_BYTES).

Attestation contract: this kernel needs no digest of its own (unlike
the hash-dedup kernel's table/output cross-check) because its output
IS the dense carry table, which the enclosing dense kernel guards
every step — the table-occupancy invariant in `wgl._dense_kernel`
(no true cell in a column holding an unoccupied slot's bit) sums
residues into the carry's `att` element, and `abft.verify_carry`
checks att == 0 and count == popcount(table) at every chunk boundary.
A closure round that silently corrupts the table is therefore caught
at the same host boundaries as an XLA-formulation fault.
"""

from __future__ import annotations

import functools

MIN_P_FOR_LANES = 7       # C = 2^P must be a multiple of 128
SUBLANE = 8               # f32 tile: (8, 128) — S must align
# everything lives in VMEM (~16 MB): four (S, C) f32/i32 tensors (tb,
# moved, acc, iota mask) plus the (P, S, S) transition stack, with
# headroom for Mosaic temporaries. Hardware-validated boundary: S=8
# P=16 and S=256 P=10 compile; S=8 P=17 and S=512 P=10 blow VMEM.
MAX_VMEM_BYTES = 12 << 20


def eligible(S: int, P: int) -> bool:
    vmem = (4 * S * (1 << P) + P * S * S) * 4
    return (P >= MIN_P_FOR_LANES
            and S % SUBLANE == 0
            and vmem <= MAX_VMEM_BYTES)


@functools.lru_cache(maxsize=16)
def closure_round_fn(S: int, P: int, interpret: bool = False):
    """Build `round(table_f32 (S,C), mft_f32 (P,S,S)) -> table_f32` —
    one fused closure round.  mft holds the TRANSPOSED transition
    matrices (mft[p] = M[p].T) so the in-kernel product is a plain
    matmul feeding the MXU."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C = 1 << P

    def kernel(tb_ref, mft_ref, out_ref):
        tb = tb_ref[:]                                    # (S, C)
        acc = tb
        # butterfly as a static lane-roll + iota bitmask: the target
        # config of slot p's completion is c | (1<<p), i.e. cand[c] =
        # moved[c - b] exactly when bit p of c is set. A lane-axis
        # reshape (the textbook butterfly) is an unsupported shape cast
        # in Mosaic; tpu.roll with a static shift + a broadcasted-iota
        # mask lowers cleanly. Cyclic wrap lands only on bit-p=0 lanes,
        # which the mask zeroes.
        idx = jax.lax.broadcasted_iota(jnp.int32, (S, C), 1)
        for p in range(P):                                # static unroll
            moved = jax.lax.dot(
                mft_ref[p], tb,
                preferred_element_type=jnp.float32)       # (S, C)
            moved = (moved > 0.0).astype(jnp.float32)
            b = 1 << p
            shifted = pltpu.roll(moved, b, axis=1)        # moved[c - b]
            mask = ((idx >> p) & 1).astype(jnp.float32)   # bit p of c
            acc = jnp.maximum(acc, shifted * mask)
        out_ref[:] = acc

    @jax.jit
    def closure_round(table, mft):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((S, C), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(table, mft)

    return closure_round
