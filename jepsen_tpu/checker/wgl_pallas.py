"""Pallas TPU kernel for the dense WGL closure round.

The dense engine's hot op is one closure round over the configuration
table: for every pending slot p,

    moved[q, c] = OR_s  M[p, s, q] AND table[s, c]        (transition)
    table      |= butterfly_p(moved)                      (set bit p)

XLA already fuses the einsum + butterfly well (`wgl.py:_dense_kernel`),
but the (P, S, C) `moved` intermediate can spill to HBM between the
product and the butterfly.  This kernel keeps the whole round in VMEM —
the table is at most DENSE_TABLE_CAP (= 2^22) bools, well under the
~16 MB VMEM budget — computing the P transition products and the
OR-accumulate in one pass with zero HBM round-trips.

Status: OPT-IN (set JEPSEN_TPU_PALLAS_CLOSURE=1).  The XLA path remains
the default until the compiled kernel has been timed on real hardware;
correctness is pinned against the XLA formulation by
tests/test_wgl_pallas.py in pallas interpret mode.  Eligibility: the
mask axis must fill the 128-lane tile (P >= 7) and the padded state
axis must be a multiple of 8.
"""

from __future__ import annotations

import functools

MIN_P_FOR_LANES = 7       # C = 2^P must be a multiple of 128
SUBLANE = 8               # f32 tile: (8, 128) — S must align
# three (S, C) f32 live tensors (tb, moved, acc) + mft + headroom must
# fit VMEM (~16 MB); cap the table itself well below that
MAX_TABLE_BYTES = 4 << 20


def eligible(S: int, P: int) -> bool:
    return (P >= MIN_P_FOR_LANES
            and S % SUBLANE == 0
            and S * (1 << P) * 4 <= MAX_TABLE_BYTES)


@functools.lru_cache(maxsize=16)
def closure_round_fn(S: int, P: int, interpret: bool = False):
    """Build `round(table_f32 (S,C), mft_f32 (P,S,S)) -> table_f32` —
    one fused closure round.  mft holds the TRANSPOSED transition
    matrices (mft[p] = M[p].T) so the in-kernel product is a plain
    matmul feeding the MXU."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C = 1 << P

    def kernel(tb_ref, mft_ref, out_ref):
        tb = tb_ref[:]                                    # (S, C)
        acc = tb
        for p in range(P):                                # static unroll
            moved = jax.lax.dot(
                mft_ref[p], tb,
                preferred_element_type=jnp.float32)       # (S, C)
            moved = (moved > 0.0).astype(jnp.float32)
            b = 1 << p
            m4 = moved.reshape(S, C // (2 * b), 2, b)
            cand = jnp.concatenate(
                [jnp.zeros_like(m4[:, :, :1, :]), m4[:, :, :1, :]],
                axis=2).reshape(S, C)
            acc = jnp.maximum(acc, cand)
        out_ref[:] = acc

    @jax.jit
    def closure_round(table, mft):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((S, C), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(table, mft)

    return closure_round
