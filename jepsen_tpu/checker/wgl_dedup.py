"""Pallas TPU kernel: sort-free frontier dedup + compaction.

The sort-family WGL kernel's hot op is the frontier dedup: every
expand round lexicographically `lax.sort`s N candidate configurations
(N = F·(1+P) in stage B, 2·F at each invoke) just to drop duplicates
and compact the survivors to the front — O(N log N) work per event on
(W+2) sort lanes, the dominant cost named by `doc/plan.md`.  Dedup is
a *set* operation, not an order operation: this kernel replaces the
sort with a VMEM-resident open-addressing hash table and does dedup +
compaction in one pass, O(N) expected (cf. P-compositionality — the
win compounds exactly when per-key sub-histories keep N small — and
TrieJax's hash/trie set ops beating sort formulations on-matrix-unit).

Contract (pinned by tests/test_wgl_dedup.py against the sort path):

  * input: N packed config keys, **old frontier first** (both wgl.py
    call sites concatenate `[old configs, candidates]`), invalid
    entries = EMPTY (-1).  A key packs `(state - s_lo) << P | mask`
    into 31 bits (the sort path's `dedup_packed` single-lane key minus
    the invalid bit), so eligibility requires the packed
    representation: `_pack_params(...) is not None and W == 1`.
  * output: the distinct valid keys in **first-seen order**, compacted
    to the front of an F-slot frontier; a per-slot `new` flag (the
    key's first occurrence had input index >= F, i.e. it was a
    candidate, not an old config — the same "stable sort,
    old-configs-first wins" rule the sort path uses); and the total
    distinct count (count > F == the sort path's overflow flag).
  * the emitted frontier is **set-equal** to the sort path's (the sort
    path emits key order, this kernel first-seen order) whenever the
    sort path does not overflow.  Every downstream consumer is
    order-invariant — the completion phase is elementwise, `summarize`
    reads only the count, and blame re-runs the unmerged stream — so
    summaries, verdicts, and blame certificates are identical.
  * under frontier pressure the hash table is strictly *tighter* than
    the sort: sorted duplicate runs can push a key's first occurrence
    past row F, so the sort path drops configs and flags overflow even
    when the distinct count fits the frontier, while the hash path
    drops nothing and flags overflow exactly when distinct > F.  Same
    soundness argument either way (dropping only loses candidate
    linearizations, so 'valid' stays sound and invalid-under-overflow
    escalates) — the hash path just escalates less often.

Kernel layout: one grid step; three VMEM buffers — the key vector
(N, 1), the hash table (H, 1) with H = 2·next_pow2(N) (load factor
<= 1/2, so linear probing terminates fast), and the compacted output
(F, 1) — all int32 (keys are 31-bit, so EMPTY = -1 is unambiguous).
A `fori_loop` walks the keys in order; each key multiplicative-hashes
(murmur3 finalizer) to a bucket and linear-probes: EMPTY -> claim the
bucket, append to the output cursor; equal key -> duplicate, skip.
The scalar probe loop is the price of exactness — but it runs against
VMEM with zero HBM traffic, does one u32 compare per probe instead of
a (W+2)-lane sort network stage, and skips dead candidates (stage B's
legality mask is usually almost empty) in one compare each.

Status: opt-in everywhere via JEPSEN_TPU_PALLAS_DEDUP=1 (interpret
mode off-TPU), DEFAULT ON for real TPU backends per the closure
kernel's precedent, opt-out with =0.  Correctness is pinned in
interpret mode by tests/test_wgl_dedup.py; hardware numbers land in
doc/perf/dedup.md once measured on the chip.
"""

from __future__ import annotations

import functools

EMPTY = -1                # table/key sentinel; valid keys are 31-bit
# the key vector, the hash table (2x the padded key count), and the
# output frontier must all sit in VMEM together, with headroom for
# Mosaic temporaries (same budget discipline as wgl_pallas).
MAX_VMEM_BYTES = 12 << 20


def table_size(n: int) -> int:
    """Hash slots for n keys: next power of two at load factor 1/2."""
    from .wgl import _bucket

    return 2 * _bucket(n)


_PROBE: bool | None = None   # one-time Mosaic compile probe result


def compiles() -> bool:
    """Does the hash kernel actually lower through Mosaic on this
    backend?  The kernel's scalar probe loop (dynamic VMEM indexing
    inside while_loop inside fori_loop) is exactly the kind of shape
    a Mosaic release can reject, and the hardware numbers are still
    pending (doc/perf/dedup.md) — so the first real-TPU use pays one
    tiny compile here, and a rejection downgrades to the proven sort
    path instead of raising out of the checker mid-run.  Resolved
    once per process; interpret mode never needs it (pure JAX)."""
    global _PROBE
    if _PROBE is None:
        try:
            import numpy as np

            from .._platform import guarded_device_get

            fn = dedup_fn(8, 4, interpret=False)
            # guarded: a wedged relay at probe time must downgrade to
            # the sort path (via the except below), not hang the first
            # checker call of the process forever
            out, _new, cnt, _dig = guarded_device_get(
                fn(np.arange(8, dtype=np.int32)), site="dedup probe")
            _PROBE = int(cnt) == 8 and list(map(int, out)) == [0, 1, 2, 3]
        except Exception:   # Mosaic lowering/compile failure
            _PROBE = False
    return _PROBE


def eligible(F: int, P: int) -> bool:
    """Can the sort family's dedup run through the hash kernel at
    frontier F with P slots?  Sized for the LARGER call site (stage
    B's F·(1+P) candidates); the invoke-stage 2·F call then fits a
    fortiori.  The packed-key requirement (W == 1 and
    `_pack_params(...) is not None`) is checked by the caller — this
    gate is pure capacity."""
    n = F * (1 + P)
    vmem = (n + table_size(n) + 2 * F) * 4
    return vmem <= MAX_VMEM_BYTES


# digest mixing constant (golden-ratio prime): the occupancy count is
# folded into the XOR digest so a dropped-and-double-counted key pair
# (XOR-cancelling) still perturbs the digest
DIGEST_COUNT_MIX = -1640531527   # 0x9E3779B9 as int32


@functools.lru_cache(maxsize=32)
def dedup_fn(N: int, F: int, interpret: bool = False):
    """Build `dedup(keys (N,) int32) -> (out_keys (F,), new (F,),
    count (), digest ())` — distinct valid keys in first-seen order,
    compacted; `new[i]` set when out_keys[i] was first seen at input
    index >= F; `count` is the TOTAL distinct-valid count (count > F
    <=> the sort path's overflow).  Slots past min(count, F) hold
    EMPTY.

    `digest` is the kernel's ABFT self-attestation: the XOR of every
    key CLAIMED IN THE HASH TABLE, mixed with the occupancy count
    (digest = xor(inserted keys) ^ (count * DIGEST_COUNT_MIX)).  When
    the frontier did not overflow (count <= F) the caller can
    recompute the same value from the compacted output alone
    (wgl.dedup_hash does, folding any mismatch into the carry's att
    accumulator): table and output are written by different store
    paths, so a silent flip in either VMEM buffer — or a probe loop
    miscompare that drops/double-claims a key — makes the two digests
    disagree."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    H = table_size(N)
    i32 = jnp.int32

    def _hash(k):
        # murmur3 finalizer over the 31-bit key; logical shifts keep
        # the mixing well-defined after the wrapping multiplies
        h = k ^ lax.shift_right_logical(k, i32(16))
        h = h * i32(-2048144789)          # 0x85ebca6b
        h = h ^ lax.shift_right_logical(h, i32(13))
        h = h * i32(-1028477387)          # 0xc2b2ae35
        h = h ^ lax.shift_right_logical(h, i32(16))
        return h & i32(H - 1)

    def kernel(keys_ref, out_keys_ref, out_new_ref, count_ref,
               digest_ref, table_ref):
        table_ref[:] = jnp.full((H, 1), EMPTY, i32)
        out_keys_ref[:] = jnp.full((F, 1), EMPTY, i32)
        out_new_ref[:] = jnp.zeros((F, 1), i32)

        def insert(i, carry):
            count, dig = carry
            k = keys_ref[i, 0]

            def probe(state):
                pos, _res = state
                t = table_ref[pos, 0]
                hit_empty = t == EMPTY

                @pl.when(hit_empty)
                def _():
                    table_ref[pos, 0] = k

                # 0 = keep probing, 1 = inserted (new distinct key),
                # 2 = duplicate of a table entry
                res = jnp.where(hit_empty, i32(1),
                                jnp.where(t == k, i32(2), i32(0)))
                return jnp.where(res == 0, (pos + 1) & (H - 1),
                                 pos), res

            # an EMPTY input slot starts resolved (res=2): dead
            # candidates cost one compare, no probes
            _pos, res = lax.while_loop(
                lambda s: s[1] == 0, probe,
                (_hash(k), jnp.where(k == EMPTY, i32(2), i32(0))))
            fresh = res == 1

            @pl.when(fresh & (count < F))
            def _():
                out_keys_ref[count, 0] = k
                out_new_ref[count, 0] = jnp.where(i >= F, i32(1),
                                                  i32(0))

            return (count + fresh.astype(i32),
                    jnp.where(fresh, dig ^ k, dig))

        count, dig = lax.fori_loop(0, N, insert, (i32(0), i32(0)))
        count_ref[0, 0] = count
        digest_ref[0, 0] = dig ^ (count * i32(DIGEST_COUNT_MIX))

    @jax.jit
    def dedup(keys):
        out_keys, out_new, count, digest = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((F, 1), jnp.int32),
                       jax.ShapeDtypeStruct((F, 1), jnp.int32),
                       jax.ShapeDtypeStruct((1, 1), jnp.int32),
                       jax.ShapeDtypeStruct((1, 1), jnp.int32)),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                       pl.BlockSpec(memory_space=pltpu.VMEM),
                       pl.BlockSpec(memory_space=pltpu.VMEM),
                       pl.BlockSpec(memory_space=pltpu.VMEM)),
            scratch_shapes=[pltpu.VMEM((H, 1), jnp.int32)],
            interpret=interpret,
        )(keys.reshape(N, 1).astype(jnp.int32))
        return (out_keys[:, 0], out_new[:, 0] != 0, count[0, 0],
                digest[0, 0])

    return dedup
