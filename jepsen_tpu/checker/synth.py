"""Synthetic histories with known verdicts, for kernel golden tests and
benchmarks (the reference's perf_test.clj generates synthetic histories the
same way: `jepsen/test/jepsen/perf_test.clj`, tag :perf).

`register_history` builds a *valid-by-construction* concurrent register
history: a simulated linearizable register applies each op's effect at a
random point inside its invocation window (we use the invoke point, which
is always a legal linearization), with real overlap between processes and
optional crashed ops. `corrupt` then breaks a valid history in a way the
checker must catch (stale read).
"""

from __future__ import annotations

import random
from typing import Any

from ..history import History


def register_history(n_ops: int, concurrency: int = 5, values: int = 5,
                     crash_rate: float = 0.02, cas: bool = True,
                     seed: int = 45100) -> History:
    """A valid concurrent read/write/cas register history.

    One logical process per concurrency slot; crashed processes are retired
    and replaced (process id += concurrency, mirroring the interpreter's
    process-retirement rule)."""
    rng = random.Random(seed)
    ops: list[dict] = []
    t = 0
    value = None  # the register's true value (linearize at invoke)
    process = {i: i for i in range(concurrency)}
    pending: dict[int, dict] = {}  # slot -> completion op to emit later
    emitted = 0

    def tick() -> int:
        nonlocal t
        t += rng.randint(1, 10)
        return t

    while emitted < n_ops or pending:
        slot = rng.randrange(concurrency)
        if slot in pending:
            # complete the in-flight op on this slot
            comp = pending.pop(slot)
            comp["time"] = tick()
            ops.append(comp)
            continue
        if emitted >= n_ops:
            # drain remaining slots
            for s in sorted(pending):
                comp = pending.pop(s)
                comp["time"] = tick()
                ops.append(comp)
            break
        p = process[slot]
        f = rng.choice(["read", "write", "cas"] if cas
                       else ["read", "write"])
        if f == "read":
            inv = {"type": "invoke", "f": "read", "value": None,
                   "process": p, "time": tick()}
            comp = {**inv, "type": "ok", "value": value}
        elif f == "write":
            v = rng.randrange(values)
            inv = {"type": "invoke", "f": "write", "value": v,
                   "process": p, "time": tick()}
            value = v  # linearization point at invoke
            comp = {**inv, "type": "ok"}
        else:
            old, new = rng.randrange(values), rng.randrange(values)
            inv = {"type": "invoke", "f": "cas", "value": (old, new),
                   "process": p, "time": tick()}
            if value == old:
                value = new
                comp = {**inv, "type": "ok"}
            else:
                comp = {**inv, "type": "fail"}
        ops.append(inv)
        emitted += 1
        if rng.random() < crash_rate and f != "read":
            # crash: op stays pending forever; its effect may or may not
            # have applied (we applied writes, which is legal), and the
            # process retires
            comp["type"] = "info"
            comp["time"] = tick()
            ops.append(comp)
            process[slot] = p + concurrency
        else:
            pending[slot] = comp
    return History(ops)


def adversarial_register_history(n_ops: int, concurrency: int = 6,
                                 crashed_writes: int = 9, values: int = 5,
                                 front_load: bool = False,
                                 seed: int = 45100) -> History:
    """A valid-by-construction register history engineered to explode
    sequential JIT-linearization search, the exact shape the reference
    calls out as the hours/32 GB case (`checker.clj:213-216`:
    crashed ops "hold slots forever").

    `crashed_writes` writes crash (:info) at evenly spaced points and
    their values are *never applied*: each such write may legally
    linearize at any later point or never, so every one permanently
    doubles the set of reachable configurations a checker must carry
    — after k crashes a sequential search juggles ~2^k × |states|
    configurations per completion, while the device frontier holds
    them as rows of one array. `concurrency` live slots keep real
    overlap on top.

    front_load=True crashes all writes in the first ~5% of the
    history, so the search runs at full configuration width for the
    remaining 95% — maximum sequential pain per unit of width."""
    rng = random.Random(seed)
    ops: list[dict] = []
    t = 0
    value = None
    process = {i: i for i in range(concurrency)}
    pending: dict[int, dict] = {}
    emitted = 0
    if front_load:
        gap = max(1, (n_ops // 20) // (crashed_writes + 1))
        crash_at = {(i + 1) * gap for i in range(crashed_writes)}
    else:
        crash_at = {round((i + 1) * n_ops / (crashed_writes + 1))
                    for i in range(crashed_writes)}

    def tick() -> int:
        nonlocal t
        t += rng.randint(1, 10)
        return t

    while emitted < n_ops or pending:
        slot = rng.randrange(concurrency)
        if slot in pending:
            comp = pending.pop(slot)
            comp["time"] = tick()
            ops.append(comp)
            continue
        if emitted >= n_ops:
            for s in sorted(pending):
                comp = pending.pop(s)
                comp["time"] = tick()
                ops.append(comp)
            break
        p = process[slot]
        if emitted in crash_at:
            # a crashed write whose value never takes effect: the op
            # stays pending forever and may linearize at any point
            v = rng.randrange(values)
            inv = {"type": "invoke", "f": "write", "value": v,
                   "process": p, "time": tick()}
            ops.append(inv)
            ops.append({**inv, "type": "info", "time": tick()})
            emitted += 1
            process[slot] = p + concurrency  # crashed process retires
            continue
        f = rng.choice(["read", "write", "cas"])
        if f == "read":
            inv = {"type": "invoke", "f": "read", "value": None,
                   "process": p, "time": tick()}
            comp = {**inv, "type": "ok", "value": value}
        elif f == "write":
            v = rng.randrange(values)
            inv = {"type": "invoke", "f": "write", "value": v,
                   "process": p, "time": tick()}
            value = v
            comp = {**inv, "type": "ok"}
        else:
            old, new = rng.randrange(values), rng.randrange(values)
            inv = {"type": "invoke", "f": "cas", "value": (old, new),
                   "process": p, "time": tick()}
            if value == old:
                value = new
                comp = {**inv, "type": "ok"}
            else:
                comp = {**inv, "type": "fail"}
        ops.append(inv)
        emitted += 1
        pending[slot] = comp
    return History(ops)


def corrupt(hist: History, seed: int = 7) -> History:
    """Break a valid register history: rewrite one :ok read to a value that
    was never current at any point in its window (forced stale/phantom)."""
    rng = random.Random(seed)
    ops = [dict(o) for o in hist.ops]
    reads = [i for i, o in enumerate(ops)
             if o["type"] == "ok" and o["f"] == "read"]
    if not reads:
        raise ValueError("history has no ok reads to corrupt")
    i = rng.choice(reads)
    # a value outside the generator's domain can never be read legally
    # (NIL aside), so this must be caught
    ops[i]["value"] = 10 ** 6
    return History(ops)


def _txn_history(n_txns: int, concurrency: int, seed: int,
                 make_txn) -> History:
    """Shared scheduler for synthetic transaction histories: one slot per
    process, txns applied serially at their invoke point (a legal
    serialization) with real inter-process overlap. make_txn(rng) returns
    the applied micro-op list (reads filled in)."""
    rng = random.Random(seed)
    ops: list[dict] = []
    t = 0
    pending: dict[int, dict] = {}
    emitted = 0

    def tick() -> int:
        nonlocal t
        t += rng.randint(1, 10)
        return t

    while emitted < n_txns or pending:
        slot = rng.randrange(concurrency)
        if slot in pending:
            comp = pending.pop(slot)
            comp["time"] = tick()
            ops.append(comp)
            continue
        if emitted >= n_txns:
            for s in sorted(pending):
                comp = pending.pop(s)
                comp["time"] = tick()
                ops.append(comp)
            break
        txn = make_txn(rng)
        inv = {"type": "invoke", "f": "txn",
               "value": [[f, k, None] if f == "r" else [f, k, v]
                         for f, k, v in txn],
               "process": slot, "time": tick()}
        ops.append(inv)
        pending[slot] = {**inv, "type": "ok", "value": txn}
        emitted += 1
    return History(ops)


def append_history(n_txns: int, concurrency: int = 10,
                   active_keys: int = 5, max_txn_len: int = 4,
                   appends_per_key: int = 32,
                   seed: int = 45100) -> History:
    """A valid-by-construction list-append transaction history at
    north-star scale (BASELINE config 5: 100k txns). Keys rotate out
    after `appends_per_key` appends so read prefixes — and hence graph
    build cost — stay bounded (the reference's elle generator rotates
    keys the same way)."""
    store: dict[int, list] = {}
    counters: dict[int, int] = {}
    state = {"next_key": active_keys}

    def make_txn(rng):
        txn = []
        for _ in range(rng.randint(1, max_txn_len)):
            k = rng.randrange(max(0, state["next_key"] - active_keys),
                              state["next_key"])
            if rng.random() < 0.5:
                v = counters.get(k, 0) + 1
                counters[k] = v
                store.setdefault(k, []).append(v)
                txn.append(["append", k, v])
                if v >= appends_per_key:
                    state["next_key"] += 1
            else:
                txn.append(["r", k, list(store.get(k, []))])
        return txn

    return _txn_history(n_txns, concurrency, seed, make_txn)


def inject_append_cycles(hist: History, n_cycles: int = 1,
                         anomaly: str = "G1c",
                         seed: int = 7,
                         key_base: int = 10 ** 9) -> History:
    """Append `n_cycles` disjoint two-transaction anomaly cycles on fresh
    keys to a (valid) list-append history — each becomes one nontrivial
    SCC, exercising the batched device classification. anomaly: 'G1c'
    (write-read cycle) or 'G-single' (write skew with one rw)."""
    rng = random.Random(seed)
    ops = [dict(o) for o in hist.ops]
    t = 1 + max((o.get("time", 0) for o in ops), default=0)
    base = key_base  # key space far above the generator's
    p1, p2 = 10 ** 6, 10 ** 6 + 1
    for c in range(n_cycles):
        kx, ky = base + 2 * c, base + 2 * c + 1
        if anomaly == "G1c":
            # T1 appends x and reads y=[1]; T2 appends y and reads x=[1]
            t1 = [["append", kx, 1], ["r", ky, [1]]]
            t2 = [["append", ky, 1], ["r", kx, [1]]]
        else:
            # T1 appends x,y; T2 reads x=[1], y=[] (one anti-dependency)
            t1 = [["append", kx, 1], ["append", ky, 1]]
            t2 = [["r", kx, [1]], ["r", ky, []]]
        for p, txn in ((p1, t1), (p2, t2)):
            ops.append({"type": "invoke", "f": "txn", "value": txn,
                        "process": p, "time": t})
            t += rng.randint(1, 3)
            ops.append({"type": "ok", "f": "txn", "value": txn,
                        "process": p, "time": t})
            t += rng.randint(1, 3)
    return History(ops)


def wr_history(n_txns: int, concurrency: int = 10, active_keys: int = 5,
               max_txn_len: int = 4, writes_per_key: int = 32,
               seed: int = 45100) -> History:
    """A valid-by-construction rw-register transaction history
    (BASELINE config 3 shape: 10k txns). Writes unique per key via
    per-key counters; keys rotate like `append_history`."""
    store: dict[int, Any] = {}
    counters: dict[int, int] = {}
    state = {"next_key": active_keys}

    def make_txn(rng):
        txn = []
        for _ in range(rng.randint(1, max_txn_len)):
            k = rng.randrange(max(0, state["next_key"] - active_keys),
                              state["next_key"])
            if rng.random() < 0.5:
                v = counters.get(k, 0) + 1
                counters[k] = v
                store[k] = v
                txn.append(["w", k, v])
                if v >= writes_per_key:
                    state["next_key"] += 1
            else:
                txn.append(["r", k, store.get(k)])
        return txn

    return _txn_history(n_txns, concurrency, seed, make_txn)


def _slotted_history(n_ops: int, concurrency: int, seed: int,
                     make_op, crash_rate: float = 0.0,
                     crashable=lambda f: True) -> History:
    """Shared scheduler for single-object model histories: ops apply
    at their invoke point (a legal linearization) with real overlap.
    make_op(rng) -> (invoke-value-fn applied immediately, returning
    (f, invoke_value, ok_value))."""
    rng = random.Random(seed)
    ops: list[dict] = []
    t = 0
    pending: dict[int, dict] = {}
    process = {i: i for i in range(concurrency)}
    emitted = 0

    def tick() -> int:
        nonlocal t
        t += rng.randint(1, 10)
        return t

    while emitted < n_ops or pending:
        slot = rng.randrange(concurrency)
        if slot in pending:
            comp = pending.pop(slot)
            comp["time"] = tick()
            ops.append(comp)
            continue
        if emitted >= n_ops:
            for s in sorted(pending):
                comp = pending.pop(s)
                comp["time"] = tick()
                ops.append(comp)
            break
        p = process[slot]
        f, inv_v, ok_v, ok = make_op(rng)
        inv = {"type": "invoke", "f": f, "value": inv_v,
               "process": p, "time": tick()}
        comp = {**inv, "type": "ok" if ok else "fail", "value": ok_v}
        ops.append(inv)
        emitted += 1
        if ok and crash_rate and crashable(f) \
                and rng.random() < crash_rate:
            comp["type"] = "info"
            comp["time"] = tick()
            ops.append(comp)
            process[slot] = p + concurrency
        else:
            pending[slot] = comp
    return History(ops)


def counter_history(n_ops: int, concurrency: int = 4,
                    max_delta: int = 3, crash_rate: float = 0.0,
                    seed: int = 45100) -> History:
    """A valid counter history: adds (possibly negative) applied at
    invoke; reads observe the true value. Crashed adds (crash_rate)
    are applied — a legal linearization."""
    state = {"v": 0}

    def make_op(rng):
        if rng.random() < 0.5:
            d = rng.randint(1, max_delta) * rng.choice((1, -1))
            state["v"] += d
            return "add", d, d, True
        return "read", None, state["v"], True

    return _slotted_history(n_ops, concurrency, seed, make_op,
                            crash_rate, crashable=lambda f: f == "add")


def gset_history(n_ops: int, concurrency: int = 4, elements: int = 8,
                 seed: int = 45100) -> History:
    """A valid grow-only-set history over int elements [0, elements)."""
    members: set = set()

    def make_op(rng):
        if rng.random() < 0.5:
            v = rng.randrange(elements)
            members.add(v)
            return "add", v, v, True
        return "read", None, sorted(members), True

    return _slotted_history(n_ops, concurrency, seed, make_op)


def uqueue_history(n_ops: int, concurrency: int = 4, values: int = 5,
                   seed: int = 45100) -> History:
    """A valid unordered-queue history: enqueues/dequeues over a small
    value domain; dequeues of absent values fail."""
    counts = [0] * values

    def make_op(rng):
        if rng.random() < 0.5:
            v = rng.randrange(values)
            if counts[v] >= 15:
                counts[v] -= 1
                return "dequeue", v, v, True
            counts[v] += 1
            return "enqueue", v, v, True
        v = rng.randrange(values)
        if counts[v] > 0:
            counts[v] -= 1
            return "dequeue", v, v, True
        return "dequeue", v, v, False

    return _slotted_history(n_ops, concurrency, seed, make_op)


def mutex_history(n_ops: int, concurrency: int = 3,
                  seed: int = 45100) -> History:
    """A valid mutex acquire/release history: only the lock holder releases;
    acquires that would deadlock the simulation fail instead."""
    rng = random.Random(seed)
    ops: list[dict] = []
    t = 0
    holder: int | None = None
    pending: dict[int, dict] = {}
    emitted = 0

    def tick() -> int:
        nonlocal t
        t += rng.randint(1, 10)
        return t

    while emitted < n_ops or pending:
        slot = rng.randrange(concurrency)
        if slot in pending:
            comp = pending.pop(slot)
            comp["time"] = tick()
            ops.append(comp)
            continue
        if emitted >= n_ops:
            for s in sorted(pending):
                comp = pending.pop(s)
                comp["time"] = tick()
                ops.append(comp)
            break
        if holder is None:
            inv = {"type": "invoke", "f": "acquire", "value": None,
                   "process": slot, "time": tick()}
            holder = slot
            pending[slot] = {**inv, "type": "ok"}
        elif holder == slot:
            inv = {"type": "invoke", "f": "release", "value": None,
                   "process": slot, "time": tick()}
            holder = None
            pending[slot] = {**inv, "type": "ok"}
        else:
            inv = {"type": "invoke", "f": "acquire", "value": None,
                   "process": slot, "time": tick()}
            pending[slot] = {**inv, "type": "fail"}
        ops.append(inv)
        emitted += 1
    return History(ops)
