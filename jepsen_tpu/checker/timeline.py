"""Renders an HTML timeline of a history.

Reference: `jepsen/src/jepsen/checker/timeline.clj` — one column per
process, one absolutely-positioned div per invoke/completion pair,
color-coded by completion type, capped at `OP_LIMIT` ops (:12-14), with
hover titles carrying the full op (:69-106).
"""

from __future__ import annotations

from html import escape

from .. import util
from ..history import NEMESIS, history
from . import Checker

OP_LIMIT = 10_000  # render cap for massive histories (`timeline.clj:12-14`)

COL_WIDTH = 100     # px
GUTTER_WIDTH = 106  # px
HEIGHT = 16         # px

STYLESHEET = """\
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.12),
                          0 1px 2px rgba(0,0,0,0.24);
              transition: all 0.3s cubic-bezier(.25,.8,.25,1);
              overflow: hidden; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
.op:target  { box-shadow: 0 14px 28px rgba(0,0,0,0.25),
                          0 10px 10px rgba(0,0,0,0.22); }
"""


def pairs(hist) -> list:
    """Pair ops per process: yields [info] singletons or
    [invoke, completion] pairs (`timeline.clj:37-57`)."""
    invocations: dict = {}
    out = []
    for op in hist:
        t = op.get("type")
        p = op.get("process")
        if t == "info":
            if p in invocations:
                out.append([invocations.pop(p), op])
            else:
                out.append([op])
        elif t == "invoke":
            assert p not in invocations
            invocations[p] = op
        elif t in ("ok", "fail"):
            assert p in invocations
            out.append([invocations.pop(p), op])
    return out


def is_nemesis(op: dict) -> bool:
    return op.get("process") == NEMESIS


def render_op(op: dict) -> str:
    shown = ("process", "type", "f", "index")
    extra = "".join(f"\n {k} {v!r}" for k, v in op.items()
                    if k not in shown + ("sub-index", "value", "time"))
    return (f"Op:\n{{process {op.get('process')}"
            f"\n type {op.get('type')}"
            f"\n f {op.get('f')}"
            f"\n index {op.get('index')}"
            f"{extra}"
            f"\n value {op.get('value')!r}}}")


def title(test, op, start, stop) -> str:
    parts = []
    if is_nemesis(op):
        parts.append(f"Msg: {start.get('value')!r}")
    if stop:
        dur_ms = int((stop["time"] - start["time"]) / 1e6)
        parts.append(f"Dur: {dur_ms} ms")
    parts.append(f"Err: {op.get('error')!r}")
    parts.append(f"Rel-time: {util.nanos_to_secs(op.get('time', 0)):.3f} s")
    parts.append("")
    parts.append(render_op(op))
    return "\n".join(parts)


def body(op, start, stop) -> str:
    same = stop is not None and start.get("value") == stop.get("value")
    s = escape(f"{op.get('process')} {op.get('f')}") + " "
    if not is_nemesis(op):
        s += escape(repr(start.get("value")))
    if stop is not None and not same:
        s += "<br />" + escape(repr(stop.get("value")))
    return s


def process_index(hist) -> dict:
    """Process -> column number: clients in order, nemesis last
    (`timeline.clj:163-170`)."""
    procs = []
    for op in hist:
        p = op.get("process")
        if p not in procs:
            procs.append(p)
    ints = sorted(p for p in procs if isinstance(p, int))
    rest = [p for p in procs if not isinstance(p, int)]
    return {p: i for i, p in enumerate(ints + rest)}


def pair_to_div(hist_len, test, pindex, pair) -> str:
    start = pair[0]
    stop = pair[1] if len(pair) > 1 else None
    op = stop or start
    left = GUTTER_WIDTH * pindex.get(start.get("process"), 0)
    top = HEIGHT * start["sub-index"]
    if stop is not None and stop.get("type") == "info":
        h = HEIGHT * (hist_len + 1 - start["sub-index"])
    elif stop is not None:
        h = HEIGHT * max(stop["sub-index"] - start["sub-index"], 1)
    else:
        h = HEIGHT
    style = (f"width:{COL_WIDTH}px;left:{left}px;top:{top}px;"
             f"height:{h}px")
    idx = op.get("index")
    return (f'<a href="#i{idx}">'
            f'<div class="op {escape(str(op.get("type")))}" id="i{idx}" '
            f'style="{style}" title="{escape(title(test, op, start, stop))}"'
            f'>{body(op, start, stop)}</div></a>')


class Html(Checker):
    """Writes timeline.html into the test's store directory
    (`timeline.clj:180-209`)."""

    def check(self, test, hist, opts):
        hist = history(hist)
        sub = [dict(o, **{"sub-index": i}) for i, o in enumerate(hist)]
        ps = pairs(sub)
        total = len(ps)
        ps = ps[:OP_LIMIT]
        pindex = process_index(sub)
        parts = ["<html><head><style>", STYLESHEET, "</style></head><body>",
                 f"<h1>{escape(str(test.get('name', '')))} key "
                 f"{escape(str((opts or {}).get('history-key', '')))}</h1>"]
        if total > OP_LIMIT:
            parts.append(
                f'<div class="truncation-warning">Showing only {OP_LIMIT} '
                f'of {total} operations in this history.</div>')
        parts.append('<div class="ops">')
        for pair in ps:
            parts.append(pair_to_div(len(sub), test, pindex, pair))
        parts.append("</div></body></html>")
        from .perf import out_path
        with open(out_path(test, opts, "timeline.html"), "w") as f:
            f.write("\n".join(parts))
        return {"valid?": True}


def html() -> Checker:
    return Html()
