"""Checker core: protocol, validity lattice, composition.

Behavioral parity with `jepsen/src/jepsen/checker.clj:29-116`: the validity
lattice (true < :unknown < false), exception-absorbing `check_safe`, parallel
`compose`, and `concurrency_limit` for memory-heavy checkers.

A checker is any object with ``check(test, history, opts) -> result-dict``;
results carry a ``'valid?'`` key which is True, False, or the string
``'unknown'``. Plain functions ``f(test, history, opts)`` are adapted
automatically.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Mapping

from .._platform import classify_backend_error
from ..history import History, history
from ..util import bounded_pmap

UNKNOWN = "unknown"

# :valid? priorities — larger dominates in composition
# (reference checker.clj:29-34).
_VALID_PRIORITIES = {True: 0, UNKNOWN: 0.5, False: 1}


def merge_valid(valids) -> Any:
    """Merge :valid? values; the highest-priority (worst) wins."""
    out = True
    for v in valids:
        if v not in _VALID_PRIORITIES:
            raise ValueError(f"{v!r} is not a known valid? value")
        if _VALID_PRIORITIES[v] > _VALID_PRIORITIES[out]:
            out = v
    return out


class Checker:
    """Protocol base. Subclasses implement check()."""

    def check(self, test: Mapping, hist: History, opts: Mapping) -> dict:
        raise NotImplementedError

    def __call__(self, test, hist, opts=None):
        return self.check(test, hist, opts or {})


class FnChecker(Checker):
    """Adapts a plain function into a Checker."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def check(self, test, hist, opts):
        return self.fn(test, hist, opts)


def coerce(c) -> Checker:
    if isinstance(c, Checker):
        return c
    if callable(c):
        return FnChecker(c)
    raise TypeError(f"not a checker: {c!r}")


class _Noop(Checker):
    def check(self, test, hist, opts):
        return None


def noop() -> Checker:
    """A checker that returns nothing (reference checker.clj:68-72)."""
    return _Noop()


class _UnbridledOptimism(Checker):
    def check(self, test, hist, opts):
        return {"valid?": True}


def unbridled_optimism() -> Checker:
    """Everything is awesome (reference checker.clj:118-122)."""
    return _UnbridledOptimism()


def checker_name(checker) -> str:
    """A human-readable name for a checker, for error attribution."""
    c = checker
    if isinstance(c, FnChecker):
        c = c.fn
    if isinstance(c, Checker):
        return type(c).__name__
    return getattr(c, "__name__", None) or type(c).__name__


def check_safe(checker, test, hist, opts=None, name=None) -> dict:
    """check(), but exceptions come back as {'valid?': 'unknown', ...}
    (reference checker.clj:74-85). The payload names the checker that
    failed ('checker') so a traceback inside compose stays
    attributable.

    Backend failures are routed through
    `_platform.classify_backend_error`: only an exception the
    classifier recognizes (jax's XlaRuntimeError family — device init,
    device OOM, preemption, a wedged sync — plus the platform module's
    own classified fault types) reports 'degraded': True with its
    'fault' bucket. An ordinary checker bug raised as a plain
    RuntimeError is NOT degradation — the device path didn't fall
    over, the checker is wrong — and reports like any other crash.
    (Reaching here at all means the entry's own recovery ladder
    already spent its budget: the ladders in checker/wgl.py and
    checker/streaming.py absorb classified faults and re-run before
    anything escapes to this level.)"""
    cname = name if name is not None else checker_name(checker)
    try:
        return coerce(checker).check(test, history(hist), opts or {})
    except (NotImplementedError, RecursionError):
        # RuntimeError subclasses, but ordinary checker bugs — not a
        # backend falling over
        return {"valid?": UNKNOWN, "checker": cname,
                "error": traceback.format_exc()}
    except Exception as e:  # noqa: BLE001 — crashes must not kill the run
        kind = classify_backend_error(e)
        if kind is not None:
            return {"valid?": UNKNOWN, "checker": cname,
                    "degraded": True, "fault": kind,
                    "error": traceback.format_exc()}
        return {"valid?": UNKNOWN, "checker": cname,
                "error": traceback.format_exc()}


class Compose(Checker):
    """Runs a map of named checkers (in parallel) and merges validity
    (reference checker.clj:87-99).

    Device-fault outcomes are summarized across the composition:
    'recovered-checkers' names sub-checkers whose results carry a
    recovery trail (the device faulted but the verdict was resumed —
    full recovery), 'degraded-checkers' names those that lost their
    verdict to faults past the recovery budget (partial degradation).
    The two are distinct outcomes: a recovered composition is
    complete, a degraded one is missing answers.

    Tiered-verification outcomes are summarized the same way:
    'screened-checkers' names sub-checkers whose verdict came from the
    tier-1 O(n) screen alone, 'escalated-checkers' those the screen
    escalated to a full search, and 'attested-checkers' those whose
    device results carried (and passed) ABFT attestation. Older
    stored results without these fields summarize to nothing."""

    def __init__(self, checker_map: Mapping[str, Any]):
        self.checkers = {k: coerce(c) for k, c in checker_map.items()}

    def check(self, test, hist, opts):
        hist = history(hist)
        items = list(self.checkers.items())
        results = bounded_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, hist, opts,
                                          name=kv[0])),
            items, max_workers=8)
        out: dict = dict(results)
        out["valid?"] = merge_valid(
            r.get("valid?", True) for _, r in results if r is not None)
        # a recovery trail is a dict ({'faults': ..., 'retries': ...});
        # workload checkers reuse the 'recovered' key for their own
        # payloads (e.g. the set checker's recovered-element string)
        recovered = sorted(k for k, r in results
                           if isinstance(r, dict)
                           and isinstance(r.get("recovered"), dict))
        degraded = sorted(k for k, r in results
                          if isinstance(r, dict) and r.get("degraded"))
        if recovered:
            out["recovered-checkers"] = recovered
        if degraded:
            out["degraded-checkers"] = degraded
        screened = sorted(k for k, r in results
                          if isinstance(r, dict) and r.get("screened")
                          and not r.get("escalated"))
        escalated = sorted(k for k, r in results
                           if isinstance(r, dict)
                           and isinstance(r.get("escalated"), dict))
        attested = sorted(k for k, r in results
                          if isinstance(r, dict)
                          and isinstance(r.get("attested"), dict))
        if screened:
            out["screened-checkers"] = screened
        if escalated:
            out["escalated-checkers"] = escalated
        if attested:
            out["attested-checkers"] = attested
        return out


def compose(checker_map: Mapping[str, Any]) -> Checker:
    return Compose(checker_map)


class ConcurrencyLimit(Checker):
    """Bounds concurrent executions of a checker with a fair semaphore
    (reference checker.clj:101-116)."""

    def __init__(self, limit: int, checker):
        self.sem = threading.Semaphore(limit)
        self.checker = coerce(checker)

    def check(self, test, hist, opts):
        with self.sem:
            return self.checker.check(test, hist, opts)


def concurrency_limit(limit: int, checker) -> Checker:
    return ConcurrencyLimit(limit, checker)


# Re-exports of the standard checkers (defined in submodules).
from .basic import (  # noqa: E402
    counter, counter_plot, log_file_pattern, queue, set_checker, set_full, stats,
    total_queue, unhandled_exceptions, unique_ids,
)
from .clock import clock_plot  # noqa: E402
from .linear import linearizable  # noqa: E402
# `perf_checker` (not `perf`) so the factory doesn't shadow the
# jepsen_tpu.checker.perf submodule attribute.
from .perf import latency_graph, perf_checker  # noqa: E402
from .perf import rate_graph_checker as rate_graph  # noqa: E402

__all__ = [
    "Checker", "UNKNOWN", "merge_valid", "check_safe", "checker_name",
    "compose",
    "concurrency_limit", "noop", "unbridled_optimism", "coerce",
    "stats", "unhandled_exceptions", "set_checker", "set_full", "queue",
    "total_queue", "unique_ids", "counter", "counter_plot",
    "log_file_pattern",
    "linearizable", "latency_graph", "rate_graph", "perf_checker",
    "clock_plot",
]
