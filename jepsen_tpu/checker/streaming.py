"""Online verification: overlap device checking with the live run.

Offline, a test pays run wall-clock *plus* analyze wall-clock — the
reference's structural pain point (`checker.clj:213-216`: post-hoc
Knossos "can take hours") survives even with fast kernels. This module
closes the gap: a driver consumes the run's history ops *as they are
journaled* (store.Journal.subscribe in-process, store.JournalTail
across processes), encodes them incrementally into the same packed
step stream the offline checker builds, batches steps into
power-of-two chunks, and advances a device-resident WGL carry with the
kernels' `check_stream_chunk` entry:

  * **Async dispatch.** Chunks are enqueued without blocking; the one
    host<->device sync per chunk reads the *previous* chunk's liveness
    flag — a value the device has already produced — so host encoding
    of chunk k+1 overlaps device compute of chunk k (the offline
    chunk loop's pipelining trick, applied across the whole run).
  * **Double-buffered staging.** Two host staging buffers alternate;
    a buffer is refilled only after the chunk that shipped from it is
    known complete, so the H2D copy of chunk k overlaps the encode of
    chunk k+1 without aliasing hazards.
  * **Prefix semantics.** An op's encoding is final only once its
    completion lands (an :ok read's authoritative value arrives with
    the completion; a :fail pair is dropped entirely), so the encoder
    emits events exactly up to the earliest still-open invocation.
    With PR 2's op-timeouts every invocation resolves within a bounded
    window, so the checked frontier trails the live run closely and
    only the last chunk (plus crash leftovers) remains at test end —
    `analyze` latency collapses from O(history) to O(last chunk).
  * **Early abort.** A dead frontier with no overflow is a *definite*
    nonlinearizable prefix (the same soundness argument as offline);
    the driver raises a violation flag mid-run and, behind the test's
    'abort-on-violation' flag, the interpreter stops issuing ops —
    the remaining cluster time is saved, cf. online/P-compositional
    linearizability checking.

Verdict parity: the encoder's emitted stream is byte-identical to
`build_steps(encode_ops(h), p)` over the completed history (same slot
heap, same merge rule, same droppable elision), and escalation/blame
replay reuse the offline machinery, so the online verdict always
equals the offline verdict on the same history (pinned by
tests/test_streaming.py for both kernel families).

The Elle side streams too: `WrStream` accumulates the rw-register
ww/wr/rw dependency edges (and the single-pass G1a/G1b/internal/
duplicate cases) incrementally as completions arrive, resolving
late-arriving references (a read observed before its writer completes)
through pending indexes; only the final SCC condensation + device
classification runs at test end.
"""

from __future__ import annotations

import heapq
import logging
import queue as _queue
import threading
import time as _time
import traceback
from typing import Any, Callable

import numpy as np

from .. import telemetry as _telemetry
from .. import trace as _trace
from .._platform import (FAULT_COMPILE, FAULT_DEVICE_LOST, FAULT_OOM,
                         attest_enabled, guarded_device_get,
                         maybe_corrupt, maybe_inject_fault,
                         probe as _probe)
from ..history import (KIND_INFO, KIND_OK, NIL, PENDING_RET,
                       DeviceEncodingError, History, OpArray,
                       history as as_history)
from . import UNKNOWN
from . import wgl as _wgl

log = logging.getLogger(__name__)

DEFAULT_CHUNK_ENTRIES = 1024
# Carry-checkpoint cadence: every K chunks the device carry round-trips
# to host memory (one extra blocking sync per K chunks), so recovery
# from a backend fault replays at most K chunks instead of the whole
# stream. 0 disables checkpointing (recovery then replays from chunk
# 0 — still correct, just cold). See doc/robustness.md for cadence
# guidance.
DEFAULT_CHECKPOINT_EVERY = 8

# row resolution states (kind uses history.KIND_* once resolved)
_UNRESOLVED = -1
_DROPPED = -2

# -- telemetry (doc/observability.md catalogs these) -------------------------
_M_CHUNKS = _telemetry.counter(
    "jepsen_tpu_streaming_chunks_total",
    "Stream chunks dispatched to the device", ("family",))
_M_LAG = _telemetry.histogram(
    "jepsen_tpu_streaming_lag_rows",
    "Encoded step rows still awaiting dispatch (the stream's lag "
    "behind the journal tail), observed per chunk",
    buckets=(16, 64, 256, 1024, 4096, 16384, 65536, 262144))
_M_CKPT_S = _telemetry.histogram(
    "jepsen_tpu_streaming_checkpoint_seconds",
    "Carry-checkpoint fetch + verify latency")
_M_CKPTS = _telemetry.counter(
    "jepsen_tpu_streaming_checkpoints_total",
    "Carry checkpoints stored")
_M_REBUILDS = _telemetry.counter(
    "jepsen_tpu_streaming_rebuilds_total",
    "Stream kernel rebuilds by cause", ("reason",))
_M_VIOLATIONS = _telemetry.counter(
    "jepsen_tpu_streaming_violations_total",
    "Definite violations confirmed mid-stream")


class _Row:
    """One logical operation (invoke paired with its completion)."""

    __slots__ = ("f", "a", "b", "kind", "inv_pos", "ret_pos", "slot",
                 "inv_op")

    def __init__(self, inv_pos: int, inv_op: dict):
        self.kind = _UNRESOLVED
        self.f = self.a = self.b = 0
        self.inv_pos = inv_pos
        self.ret_pos = int(PENDING_RET)
        self.slot = -1
        self.inv_op = inv_op


class StreamEncoder:
    """Incremental `encode_ops` + `build_steps(merge=True)`.

    Feed journal ops in arrival order; the encoder emits packed merged
    step rows for the prefix whose encoding is final. Once the history
    is complete and finish() has run, the emitted stream is
    byte-identical to ``build_steps(encode_ops(h, codec, droppable),
    p).x`` — same slot min-heap, same ok-run merging, same droppable
    pending elision — which is what makes online and offline verdicts
    interchangeable.

    Events can only be emitted in history-position order, and an
    invocation's event is unknown until its completion arrives (the
    completion carries the authoritative value; a :fail drops the
    pair), so the emit cursor trails the earliest open invocation —
    the structural lag of any online linearizability checker.
    """

    def __init__(self, codec: Callable, droppable: frozenset, p: int):
        self.p = p
        self.w = max(1, (p + 31) // 32)
        self.codec = codec
        self.droppable = droppable
        self.rows: list[_Row] = []
        self.n_client_ops = 0
        self.finished = False
        self._free = list(range(p))
        heapq.heapify(self._free)
        self._open: dict[Any, int] = {}      # process -> row id
        self._events: list = []              # per client-op position
        self._cursor = 0
        self._pend = [0] * self.w
        self._out: list[list[int]] = []      # emitted, unconsumed steps
        self.steps_emitted = 0

    # -- feeding ----------------------------------------------------------

    def feed(self, op: dict) -> None:
        """Accept the next journal op (client ops only; the caller
        filters). Raises DeviceEncodingError if the op exceeds the
        device encoding and SlotOverflow when concurrency + crashed
        ops exceed p (the caller rebuilds with a larger p)."""
        assert not self.finished, "feed() after finish()"
        pos = self.n_client_ops
        self.n_client_ops += 1
        t = op.get("type")
        if t == "invoke":
            r = len(self.rows)
            self.rows.append(_Row(pos, op))
            self._open[op["process"]] = r
            self._events.append(("inv", r))
        else:
            r = self._open.pop(op["process"], None)
            if r is None:
                # completion with no journaled invocation: encode_ops
                # iterates invokes, so it contributes nothing
                self._events.append(None)
            elif t == "fail":
                self.rows[r].kind = _DROPPED
                self._events.append(None)
            elif t == "ok":
                row = self.rows[r]
                row.f, row.a, row.b = self.codec(op)
                row.kind = KIND_OK
                row.ret_pos = pos
                self._events.append(("ret", r))
            else:  # info: pending forever (encoding is final now)
                self._resolve_info(self.rows[r])
                self._events.append(None)
        self._advance()

    def _resolve_info(self, row: _Row) -> None:
        f, a, b = self.codec(row.inv_op)
        if f in self.droppable:
            row.kind = _DROPPED
        else:
            row.f, row.a, row.b = f, a, b
            row.kind = KIND_INFO

    def finish(self) -> None:
        """Resolve every still-open invocation as pending-forever (the
        crash-salvage tail encode_ops would produce) and flush the
        trailing completion run."""
        if self.finished:
            return
        for r in self._open.values():
            if self.rows[r].kind == _UNRESOLVED:
                self._resolve_info(self.rows[r])
        self._open.clear()
        self._advance()
        assert self._cursor == len(self._events)
        if any(self._pend):
            self._flush(-1, 0, NIL, NIL)
        self.finished = True

    # -- emission ---------------------------------------------------------

    def _flush(self, inv_slot: int, f: int, a: int, b: int) -> None:
        # mask words carry bit 31 when slot 31/63/... is pending —
        # reinterpret as int32 (build_steps does this with a uint32
        # view) so the packed row fits the kernels' int32 matrix
        words = [w - (1 << 32) if w >= (1 << 31) else w
                 for w in self._pend]
        self._out.append(words + [inv_slot, f, a, b])
        self.steps_emitted += 1
        self._pend = [0] * self.w

    def _advance(self) -> None:
        events = self._events
        while self._cursor < len(events):
            ev = events[self._cursor]
            if ev is None:
                self._cursor += 1
                continue
            kind, r = ev
            row = self.rows[r]
            if kind == "inv":
                if row.kind == _UNRESOLVED:
                    return        # the stable prefix ends here
                if row.kind == _DROPPED:
                    self._cursor += 1
                    continue
                if not self._free:
                    raise _wgl.SlotOverflow(
                        f"more than {self.p} pending ops in the live "
                        f"stream (crashed ops hold slots forever)")
                s = heapq.heappop(self._free)
                row.slot = s
                self._flush(s, row.f, row.a, row.b)
            else:  # ret — only emitted for OK rows
                s = row.slot
                heapq.heappush(self._free, s)
                self._pend[s // 32] |= 1 << (s % 32)
            self._cursor += 1

    def take(self, n: int) -> list[list[int]]:
        """Pop up to n emitted step rows."""
        rows, self._out = self._out[:n], self._out[n:]
        return rows

    def available(self) -> int:
        return len(self._out)

    def op_array(self) -> OpArray:
        """The resolved rows as an OpArray — the bridge back to the
        offline machinery (escalation replay, unmerged blame runs,
        model validators)."""
        rows = [r for r in self.rows if r.kind in (KIND_OK, KIND_INFO)]
        cols: list[list[int]] = [[] for _ in range(8)]
        for r in rows:
            cols[0].append(r.f)
            cols[1].append(r.a)
            cols[2].append(r.b)
            cols[3].append(r.kind)
            cols[4].append(r.inv_pos)
            cols[5].append(r.ret_pos if r.kind == KIND_OK
                           else int(PENDING_RET))
            cols[6].append(int(r.inv_op.get("process", -1)))
            cols[7].append(int(r.inv_op.get("index", r.inv_pos)))
        return OpArray(*(np.asarray(c, np.int32) for c in cols))


class WglStream:
    """The online WGL pipeline for one linearizability target.

    feed(op) with every history op (any thread discipline where feeds
    are serialized — the OnlineChecker driver thread in practice);
    finish() returns an analysis dict shaped like `wgl.analysis_tpu`'s
    (plus 'tail-latency-ms', 'chunks', 'streamed').

    engine: 'sort' (default — works with no a-priori knowledge) or
    'dense' (exact, no frontier, but needs `state_range` declared up
    front so the reachable-set table can be allocated before the
    first op arrives). A declared state_range also lets the SORT
    family pack configs into single-u32 keys up front, which is what
    makes the Pallas hash dedup (JEPSEN_TPU_PALLAS_DEDUP /
    pallas=True) available online — without it the sort stream keeps
    the multi-word lexicographic dedup. Values escaping a declared
    range trigger a transparent rebuild: dense -> sort, packed sort
    -> unpacked sort.

    Fault tolerance: the carry round-trips to host memory every
    `checkpoint_every` chunks (reusing wgl.run_range's carry
    checkpointability), so a classified backend fault mid-stream —
    OOM, device loss/preemption, compile failure, a wedged sync —
    recovers by reinitializing the kernel, restoring the last
    checkpoint, and replaying at most `checkpoint_every` chunks from
    the dispatched-steps log instead of surfacing as a lost verdict.
    The OOM rung additionally applies backpressure: the dense engine
    re-selects onto the sort family (the table is the memory hog) and
    the sort engine halves `chunk_entries`. The encoder is host-side
    and untouched by device faults, so a resumed stream's emitted
    step rows are byte-identical to an uninterrupted run's and the
    verdict/certificate are identical too (pinned by
    tests/test_recovery.py). A recovered stream reports its trail
    under 'recovered' in finish()'s analysis.
    """

    def __init__(self, model, *, slots: int | None = None,
                 frontier: int = 256, max_frontier: int = 65536,
                 chunk_entries: int = DEFAULT_CHUNK_ENTRIES,
                 engine: str = "sort",
                 state_range: tuple[int, int] | None = None,
                 concurrency_hint: int | None = None,
                 pallas=None,
                 checkpoint_every: int = DEFAULT_CHECKPOINT_EVERY,
                 max_recovery_retries: int | None = None,
                 auto_pump: bool = True,
                 fault_site: str = "stream-chunk"):
        name = model.device_model
        if name is None or name not in _wgl.DEVICE_MODELS:
            raise ValueError(f"model {model!r} has no device form")
        self.model = model
        self.name = name
        self.dm = _wgl.DEVICE_MODELS[name]
        # service scheduling: auto_pump=False turns feed() into
        # encode-only — a scheduler calls pump() to dispatch chunks
        # under its own budget. fault_site names this stream's fault-
        # injection/attestation site so a multi-stream service can
        # target (and account) faults per stream.
        self.auto_pump = bool(auto_pump)
        self.fault_site = fault_site
        self.chunk = _wgl._bucket(max(int(chunk_entries), 1), lo=64)
        self.frontier = frontier
        self.max_frontier = max_frontier
        if engine not in ("sort", "dense", "auto"):
            raise ValueError(f"unknown streaming engine {engine!r}")
        self.state_range = state_range
        self.pallas = pallas
        self.engine = self._pick_engine(engine, state_range)
        p0 = slots or _wgl._bucket(
            max(int(concurrency_hint or 0) + 4, 8), lo=8)
        self.p = p0
        # a declared state range lets the sort family pack configs up
        # front (the offline path derives this from the whole history;
        # online it must be promised) — range escapes drop it below
        self._pack = (_wgl._pack_params(state_range, p0)
                      if state_range is not None else None)
        if self.engine == "dense":
            # validate at construction, not at first dispatch deep
            # inside feed(): a forced 'dense' raises (the caller asked
            # for the impossible); 'auto' downgrades to the sort
            # engine, which needs no a-priori table
            try:
                self._dense_shape()
            except ValueError:
                if engine == "dense":
                    raise
                log.info("online WGL stream: dense table exceeds caps "
                         "at %d slots; using the sort engine", p0)
                self.engine = "sort"
        self.encoder = StreamEncoder(self.dm.codec, self.dm.droppable, p0)
        self._client_ops: list[dict] = []   # raw feed, for rebuild/blame
        self._t_first: float | None = None
        self._failed: Exception | None = None
        self.violation = False              # definite dead frontier
        self.violation_at_op: int | None = None  # ops fed at detection
        self._dead = False                  # frontier known dead
        self._dead_overflow = False         # ... but under overflow
        self._k = None
        self._carry = None
        self._chunks = 0
        self._chunk_syncs = 0
        self._bufs: list[np.ndarray] | None = None
        self._pad_row: np.ndarray | None = None
        self._steps_log: list[np.ndarray] = []   # dispatched step slices
        # fault tolerance: carry checkpoints + the recovery trail
        # (classification / budget / backoff policy lives in ONE place,
        # wgl._RecoveryTrail — the stream only adds checkpoint restore)
        self.checkpoint_every = max(0, int(checkpoint_every))
        self._trail = _wgl._RecoveryTrail(max_recovery_retries)
        # (rows consumed, chunks dispatched, host-resident carry)
        self._ckpt: tuple[int, int, tuple] | None = None
        # bumped whenever the recovery target changes (a cadence/
        # forced checkpoint lands, or a rebuild invalidates it) — a
        # service watches this to know when to persist the export
        # durably without re-fetching or comparing carries
        self.checkpoint_seq = 0
        # an imported (cross-process) checkpoint waiting to seed the
        # carry at the next kernel build — see import_checkpoint()
        self._restore_ckpt_pending = False
        self._rows_fed = 0        # step rows appended to the log
        self._rows_done = 0       # step rows the device has consumed
        self._resumed_from_chunk: int | None = None
        self._last_fault: BaseException | None = None
        # ABFT attestation (JEPSEN_TPU_ATTEST, default on): each
        # staged chunk's device digest is held here and verified
        # against the host digest at the NEXT chunk boundary (the
        # lagged liveness sync / a checkpoint / finish), so detection
        # adds no extra sync; carry digests verify at checkpoints.
        self._attest = attest_enabled()
        self._att_pending: list[tuple] = []   # (device digest, expected)
        self._att_steps = 0
        self._att_carry = 0
        # attestation tallies as of the last checkpoint — exported
        # with it so a cross-process resume reports the same totals
        # as an uninterrupted run
        self._ckpt_att = (0, 0)
        # chunk-level tracing: ONE trace id threads run -> stream ->
        # chunk -> recovery-retry. The stream span parents to the
        # caller's current span when one is open (a traced run),
        # else anchors a fresh trace; finish() stamps the trace id on
        # the verdict, so a violation resolves to the exact device
        # chunks that produced it.
        tr = _trace.tracer()
        self._span_stream = None
        self._trace_ctx = None
        if tr.enabled:
            parent = tr.context()
            if not parent.get("trace-id"):
                parent = tr.new_context()
            self._span_stream = tr.start_span("wgl.stream",
                                              parent=parent)
            self._span_stream.tags["model"] = str(self.name)
            self._span_stream.tags["engine"] = str(self.engine)
            self._trace_ctx = self._span_stream.context()

    def end_trace(self, valid=None) -> None:
        """Record the stream's root span (idempotent). Every terminal
        path must land here — verdict, disablement, a service shedding
        or draining the worker — or the already-exported chunk spans
        point at a parent the collector never receives."""
        sp, self._span_stream = self._span_stream, None
        if sp is not None:
            if valid is not None:
                sp.tags["valid"] = str(valid)
            _trace.tracer().finish_span(sp)

    @property
    def faults(self) -> list:
        return self._trail.faults

    @property
    def max_recovery_retries(self) -> int:
        return self._trail.max

    # -- engine / kernel management ---------------------------------------

    def _pick_engine(self, engine: str, srange) -> str:
        if engine == "dense" or (engine == "auto" and srange is not None):
            if srange is None:
                raise ValueError(
                    "streaming dense engine needs an up-front "
                    "state_range (the table is allocated before the "
                    "first op arrives)")
            return "dense"
        return "sort"

    def _dense_shape(self):
        lo, hi = self.state_range
        S = _wgl._bucket(hi - lo + 1, lo=4)
        if S > _wgl.DENSE_STATE_CAP or \
                S * (1 << self.p) > _wgl.DENSE_TABLE_CAP:
            raise ValueError(
                f"dense streaming table ({S} states x 2^{self.p} "
                f"slots) exceeds the dense caps")
        return lo, S, self.p

    def _setup(self) -> None:
        """Build the kernel + staging buffers; warm the compile with a
        zero-length chunk so the first real dispatch never pays it."""
        import jax.numpy as jnp

        if self.engine == "dense":
            lo, S, P = self._dense_shape()
            self._k = _wgl._dense_kernel(self.name, lo, S, P,
                                         self.chunk, pallas=self.pallas)
        else:
            self._k = _wgl._kernel(self.name, self.frontier, self.p,
                                   self.chunk, self._pack,
                                   pallas=self.pallas)
        w = self.encoder.w
        pad = np.zeros((self.chunk, w + 4), np.int32)
        pad[:, w] = -1
        pad[:, w + 2:] = NIL
        self._pad_row = pad[0].copy()
        self._bufs = [pad.copy(), pad.copy()]
        self._carry = self._k.init_carry(
            jnp.int32(self.model.device_state()))
        # compile warm-up: consumes nothing, leaves the carry
        # untouched — and IS the stream's XLA compile, so its wall
        # time is the execute-vs-compile split's other half
        t0 = _time.monotonic()
        self._carry = self._k.check_stream_chunk(
            self._bufs[0], jnp.int32(0), self._carry)
        _wgl._M_COMPILE.labels(
            family=self.engine, stage="warmup").observe(
            _time.monotonic() - t0)
        if self._restore_ckpt_pending and self._ckpt is not None:
            # a checkpoint imported from a drained service: seed the
            # carry from it so the refed prefix (skipped row-for-row by
            # _dispatch_once) resumes instead of recomputing
            self._carry = tuple(jnp.asarray(a) for a in self._ckpt[2])
            self._restore_ckpt_pending = False

    # -- fault tolerance ---------------------------------------------------

    def _absorb_fault(self, exc: BaseException, site: str) -> bool:
        """Classify + record a backend fault; True when another retry
        is allowed (after the backoff sleep), False when the budget is
        spent. Exceptions the classifier rejects — ordinary bugs — are
        re-raised by the trail: they must never trigger recovery."""
        self._last_fault = exc
        more = self._trail.absorb(exc, f"online WGL stream {site}")
        _probe("fault", site=self.fault_site,
               kind=(self.faults[-1] if self.faults else None),
               retry=len(self.faults), at=site)
        return more

    def _apply_stream_rung(self, kind: str) -> None:
        """Mutate the stream's knobs per the fault bucket before the
        retry. Every rung drops the kernel so the retry rebuilds it."""
        if kind == FAULT_OOM:
            if self.engine == "dense":
                # the dense table is the memory hog: re-select onto the
                # sort family. A dense checkpoint cannot seed a sort
                # carry, so recovery replays the whole log (cold but
                # correct); range escapes were already impossible here,
                # so the packed sort stays available.
                log.warning("online WGL stream: OOM on the dense "
                            "engine; re-selecting onto the sort family")
                self.engine = "sort"
                self._ckpt = None
            else:
                self.chunk = _wgl._bucket(max(self.chunk // 2, 64),
                                          lo=64)
                log.warning("online WGL stream: OOM backpressure; "
                            "chunk_entries now %d", self.chunk)
        elif kind == FAULT_DEVICE_LOST:
            _wgl._device_reinit()
        elif kind == FAULT_COMPILE:
            self.pallas = False
        self._k = None

    def _restore_and_replay(self) -> None:
        """Rebuild the kernel, restore the last carry checkpoint, and
        replay the dispatched-steps log from its row index — the
        recovery resume. The encoder and steps log are host-side and
        untouched, so the replayed stream is byte-identical to the
        uninterrupted one."""
        import jax.numpy as jnp

        self._k = None
        self._setup()
        # digests enqueued by the failed attempt reference dead
        # dispatches; the replay below re-stages (and re-attests)
        # every slice past the checkpoint
        self._att_pending = []
        if self._ckpt is not None:
            rows0, chunks0, host = self._ckpt
            self._carry = tuple(jnp.asarray(a) for a in host)
        else:
            rows0, chunks0 = 0, 0
        self._resumed_from_chunk = chunks0
        self._rows_done = rows0
        # chaos probe: a fault probe landing between replay-begin and
        # replay-end is the fault-DURING-replay conjunction the chaos
        # coverage rewards (no replay-end fires when the replay itself
        # faults — the harness treats the window as still open)
        _probe("replay-begin", site=self.fault_site,
               from_chunk=chunks0)
        # rewind the chunk counter too: the replay loop re-increments
        # it per slice, so it lands back at the live chunk count —
        # otherwise later checkpoints and the violation log would
        # count replayed dispatches on top of live ones
        self._chunks = chunks0
        # collect only the rows past the checkpoint, walking the log
        # from the end — concatenating the whole stream to slice its
        # tail would make recovery cost O(stream), not O(replay)
        need = sum(len(a) for a in self._steps_log) - rows0
        parts: list[np.ndarray] = []
        got = 0
        for a in reversed(self._steps_log):
            if got >= need:
                break
            parts.append(a)
            got += len(a)
        parts.reverse()
        tail = (np.concatenate(parts)[-need:] if need > 0
                else np.zeros((0, self.encoder.w + 4), np.int32))
        for e in range(0, len(tail), self.chunk):
            sl = tail[e:e + self.chunk]
            maybe_inject_fault(self.fault_site)
            # fresh staging per slice: unlike the live path, this loop
            # enqueues without a per-chunk liveness sync, so reusing
            # the double buffers could rewrite one still feeding an
            # in-flight async chunk
            buf = np.repeat(self._pad_row[None], self.chunk, axis=0)
            buf[:len(sl)] = sl
            xj = jnp.asarray(maybe_corrupt(self.fault_site, buf))
            if self._attest:
                from . import abft
                self._att_pending.append(
                    (abft.digest_device(xj), abft.digest_host(buf)))
            self._carry = self._k.check_stream_chunk(
                xj, jnp.int32(len(sl)), self._carry)
            self._chunks += 1
            self._rows_done += len(sl)
            self._maybe_checkpoint()
        if self._attest:
            self._drain_attest()
        if not self._dead:
            self._check_death(self._carry)
        _probe("replay-end", site=self.fault_site,
               replayed=len(tail))
        log.info("online WGL stream resumed from chunk %d "
                 "(replayed %d step rows)", chunks0, len(tail))

    def _maybe_checkpoint(self) -> None:
        """Round-trip the carry to host memory every checkpoint_every
        chunks. The blocking fetch also FORCES completion of every
        async chunk enqueued so far, so a stored checkpoint is known
        good — a fault in flight surfaces here and recovery falls back
        to the previous one."""
        if not self.checkpoint_every \
                or self._chunks % self.checkpoint_every:
            return
        self._checkpoint()

    def _checkpoint(self) -> None:
        """Fetch the carry to host memory NOW and store it as the
        recovery target (the cadence-independent body of
        _maybe_checkpoint — also the drain path of a verification
        service, which checkpoints every stream before exiting)."""
        # success-only metrics: a failed attempt (attest mismatch,
        # backend fault) records NEITHER series, so sum/count stays a
        # true per-checkpoint latency and count matches the counter
        t0 = _time.monotonic()
        self._checkpoint_inner()
        _M_CKPT_S.observe(_time.monotonic() - t0)
        _M_CKPTS.inc()

    def _checkpoint_inner(self) -> None:
        if self._attest:
            # a checkpoint must be KNOWN GOOD before it becomes the
            # recovery target: verify every staged chunk that fed it,
            # then fetch the carry together with its device digest and
            # cross-check on host — corruption detected here falls
            # back to the PREVIOUS checkpoint
            from . import abft
            self._drain_attest()
            host, hd = guarded_device_get(
                (self._carry, self._k.digest(self._carry)),
                site="stream checkpoint")
            abft.verify_carry(self.fault_site, hd, host)
            self._att_carry += 1
        else:
            host = guarded_device_get(self._carry,
                                      site="stream checkpoint")
        self._ckpt = (self._rows_done, self._chunks, host)
        self._ckpt_att = (self._att_steps, self._att_carry)
        self.checkpoint_seq += 1

    def _recovering(self, fn: Callable[[], Any], site: str,
                    restore: bool = True):
        """Run a device-side closure under the recovery ladder: a
        classified backend fault applies its rung, restores the last
        checkpoint, replays the steps log, and retries fn. Returns
        fn()'s value, or None when the retry budget is spent (the
        caller decides its final rung — _dispatch disables the stream,
        finish degrades to offline, blame keeps the verdict).

        restore=False skips the checkpoint-restore/replay between
        retries — for closures that build their own kernel and carry
        (escalation, blame) and would never read the restored
        self._carry; replaying the whole steps log for them would
        double the device work of every transient fault."""
        replay = False
        while True:
            try:
                if replay:
                    with _trace.tracer().span(
                            "wgl.stream.recovery-retry",
                            parent=self._trace_ctx) as sp:
                        if sp is not None and self.faults:
                            sp.tags["fault"] = str(self.faults[-1])
                        self._restore_and_replay()
                    replay = False
                return fn()
            except RuntimeError as e:
                if not self._absorb_fault(e, site):
                    return None
                self._apply_stream_rung(self.faults[-1])
                replay = restore

    # -- feeding ----------------------------------------------------------

    def feed(self, op: dict) -> None:
        if self._failed is not None:
            return
        if not isinstance(op.get("process"), int):
            return
        self._client_ops.append(op)
        if self._t_first is None:
            self._t_first = _time.monotonic()
        try:
            self.encoder.feed(op)
        except _wgl.SlotOverflow:
            self._rebuild(p=self.p * 2)
            return
        except DeviceEncodingError as e:
            # the history exceeds the device encoding altogether: no
            # kernel family can stream it — the offline checker's host
            # fallback covers it
            self._failed = e
            log.warning("online WGL stream disabled (%s); the offline "
                        "checker will run instead", e)
            return
        if self.auto_pump:
            self._pump()

    def _rebuild(self, p: int, reason: str = "slot-overflow") -> None:
        """Re-encode the full feed with new parameters and replay the
        device search from scratch — the rare recovery path (slot
        overflow beyond the initial estimate, dense range escape).
        Replay is still chunked/async, so it costs one pass of device
        time, not a behavioral change."""
        _M_REBUILDS.labels(reason=reason).inc()
        p = _wgl._bucket(p, lo=8)
        if p > 256:
            self._failed = _wgl.SlotOverflow(
                "online stream needs more than 256 slots")
            log.warning("online WGL stream disabled (%s)", self._failed)
            return
        if self.engine == "dense":
            # a grown slot count can push the dense table past its
            # caps — downgrade to the sort kernel rather than raise
            # from deep inside feed()
            try:
                old_p, self.p = self.p, p
                self._dense_shape()
                self.p = old_p
            except ValueError as e:
                self.p = old_p
                log.warning("online WGL stream: %s; rebuilding onto "
                            "the sort kernel", e)
                self.engine = "sort"
        log.info("online WGL stream rebuilding: slots %d -> %d "
                 "(engine %s)", self.p, p, self.engine)
        self.p = p
        if self._pack is not None:
            # the packed key budget shrinks as slots grow (P + state
            # bits + 1 must fit 32) — recompute, dropping to the
            # multi-word dedup when it no longer fits
            self._pack = _wgl._pack_params(self.state_range, p)
        self.encoder = StreamEncoder(self.dm.codec, self.dm.droppable, p)
        self._k = None
        self._steps_log = []
        self._att_pending = []
        self._chunks = 0
        # a rebuild replaces the kernel family/shape: the old carry
        # checkpoint no longer matches and the steps log restarts
        # (checkpoint_seq still bumps — a durably persisted export of
        # the dead checkpoint must be superseded, not left current)
        self._ckpt = None
        self.checkpoint_seq += 1
        self._restore_ckpt_pending = False
        self._rows_fed = self._rows_done = 0
        self._dead = self._dead_overflow = False
        self.violation = False
        self.violation_at_op = None
        ops, self._client_ops = self._client_ops, []
        for op in ops:
            self.feed(op)

    def _pump(self, partial: bool = False,
              limit: int | None = None) -> int:
        """Dispatch full chunks (and, when partial=True, the tail).
        limit caps the number of chunks dispatched this call — the
        service scheduler's unit of budget. Returns chunks
        dispatched."""
        done = 0
        while limit is None or done < limit:
            if self._failed is not None:
                # the recovery budget died mid-drain: every further
                # chunk would re-attempt a kernel build + dispatch on
                # the broken backend (each up to a watchdog deadline)
                return done
            avail = self.encoder.available()
            if avail == 0 or (avail < self.chunk and not partial):
                return done
            rows = self.encoder.take(self.chunk)
            arr = np.asarray(rows, np.int32)
            if (self.engine == "dense" or self._pack is not None) \
                    and self._range_escape(arr):
                # a value escaped the declared state range: the dense
                # table would silently drop legal linearizations (an
                # unsound 'invalid'), and a packed sort key would wrap
                # into a neighboring config — downgrade to the
                # unpacked sort kernel and replay
                log.warning("online WGL stream: value outside the "
                            "declared state range; rebuilding onto "
                            "the unpacked sort kernel")
                self.engine = "sort"
                self._pack = None
                self._rebuild(p=self.p, reason="range-escape")
                return done
            self._dispatch(arr)
            done += 1
        return done

    def _range_escape(self, arr: np.ndarray) -> bool:
        w = self.encoder.w
        lo, hi = self.state_range
        vals = arr[:, w + 2:]
        return bool(((vals != NIL) & ((vals < lo) | (vals > hi))).any())

    # -- service scheduling (externally pumped chunks) ---------------------

    def pending_chunks(self) -> int:
        """Full chunks encoded and waiting for dispatch — what a
        service scheduler weighs against its budget."""
        if self._failed is not None:
            return 0
        return self.encoder.available() // self.chunk

    def kernel_key(self):
        """Identity of the (process-LRU-cached, shape-shared) jitted
        kernel, or None before setup. The service's calibration feed
        uses it to tell which ONE stream per kernel shape paid the
        compile on its first chunk — only that stream's lagged sample
        is compile-tainted."""
        return id(self._k) if self._k is not None else None

    def pump(self, max_chunks: int | None = None) -> int:
        """Dispatch up to max_chunks full chunks (None = all). The
        external-pump entry for a verification service; with
        auto_pump=True, feed() already pumps and this is a no-op
        unless chunks piled up."""
        return self._pump(limit=max_chunks)

    def checkpoint_now(self) -> bool:
        """Force a carry checkpoint regardless of cadence — the drain
        path. True when a checkpoint was stored (False when nothing
        was ever dispatched, the stream already failed, or the
        recovery budget died trying)."""
        if self._failed is not None or self._k is None:
            return False
        ok = self._recovering(
            lambda: self._checkpoint() or True, "checkpoint") is not None
        if ok and self._attest:
            # the forced checkpoint's own carry verification is drain
            # overhead, not part of the stream's verdict: exclude it
            # from the exported tallies so a resumed stream reports
            # totals identical to an uninterrupted run's (cadence
            # checkpoints always fired inside dispatch already)
            self._ckpt_att = (self._ckpt_att[0], self._ckpt_att[1] - 1)
        return ok

    def export_checkpoint(self) -> dict | None:
        """The last carry checkpoint plus the kernel-shape parameters
        needed to rebuild an equivalent stream in another process —
        what a draining service persists. None when no checkpoint
        exists (resume then re-feeds from scratch: cold, correct)."""
        if self._ckpt is None:
            return None
        rows, chunks, host = self._ckpt
        return {
            "rows": int(rows),
            "chunks": int(chunks),
            "carry": [np.asarray(a) for a in host],
            "engine": self.engine,
            "p": int(self.p),
            "chunk": int(self.chunk),
            "frontier": int(self.frontier),
            "pallas": self.pallas,
            "packed": self._pack is not None,
            "att-steps": int(self._ckpt_att[0]),
            "att-carry": int(self._ckpt_att[1]),
            "state-range": (list(self.state_range)
                            if self.state_range is not None else None),
        }

    def import_checkpoint(self, ck: dict) -> bool:
        """Seed a FRESH stream from an exported checkpoint: the caller
        re-feeds the journal from the beginning, the encoder re-emits
        the byte-identical step stream, and dispatch skips row-for-row
        up to the checkpoint (restoring its carry at the first kernel
        build) — so the resumed verdict is identical to an
        uninterrupted run's. Returns False (stream stays cold) when
        the checkpoint's kernel shape doesn't match this stream's."""
        if self.encoder.n_client_ops or self._chunks or self._steps_log:
            raise ValueError("import_checkpoint on a stream that "
                             "already consumed ops")
        if (ck.get("engine") != self.engine or int(ck["p"]) != self.p
                or int(ck["chunk"]) != self.chunk
                or int(ck["frontier"]) != self.frontier
                or bool(ck.get("packed")) != (self._pack is not None)):
            log.warning("stream checkpoint shape mismatch (%s/%s/%s/%s "
                        "vs %s/%s/%s/%s); resuming cold",
                        ck.get("engine"), ck.get("p"), ck.get("chunk"),
                        ck.get("frontier"), self.engine, self.p,
                        self.chunk, self.frontier)
            return False
        carry = tuple(np.asarray(a) for a in ck["carry"])
        self._ckpt = (int(ck["rows"]), int(ck["chunks"]), carry)
        self._rows_done = int(ck["rows"])
        self._chunks = int(ck["chunks"])
        self._resumed_from_chunk = int(ck["chunks"])
        self._att_steps = int(ck.get("att-steps", 0))
        self._att_carry = int(ck.get("att-carry", 0))
        self._ckpt_att = (self._att_steps, self._att_carry)
        self._restore_ckpt_pending = True
        return True

    def _dispatch(self, arr: np.ndarray) -> None:
        self._steps_log.append(arr)
        self._rows_fed += len(arr)
        if self._dead and not self._dead_overflow:
            return   # verdict already definite; no device work left
        if self._recovering(lambda: self._dispatch_once(arr) or True,
                            "dispatch") is None:
            # recovery budget spent: disable the stream — the offline
            # checker (whose own ladder ends at the host mirror) covers
            self._failed = self._last_fault or RuntimeError(
                "stream recovery budget exhausted")
            log.warning("online WGL stream disabled after %d backend "
                        "faults (%s); the offline checker will run "
                        "instead", len(self.faults), self._failed)

    def _dispatch_once(self, arr: np.ndarray) -> None:
        import jax.numpy as jnp

        if self._rows_done >= self._rows_fed:
            return   # a recovery replay already consumed this slice
        if self._k is None:
            self._setup()
        t_chunk = _time.monotonic()
        sp = _trace.tracer().start_span("wgl.stream.chunk",
                                        parent=self._trace_ctx)
        if sp is not None:
            sp.tags["chunk"] = str(self._chunks)
            sp.tags["rows"] = str(len(arr))
        try:
            with _telemetry.profile_section("wgl.stream.chunk"):
                maybe_inject_fault(self.fault_site)
                buf = self._bufs[self._chunks % 2]
                n = len(arr)
                buf[:n] = arr
                if n < self.chunk:
                    buf[n:] = self._pad_row
                prev = self._carry
                xj = jnp.asarray(maybe_corrupt(self.fault_site, buf))
                if self._attest:
                    # enqueue the shipped buffer's device digest; the
                    # host digest comes from the canonical staging
                    # buffer BEFORE it is reused. Verified lagged (at
                    # _drain_attest callers) so the chunk pipeline
                    # keeps its one sync per chunk.
                    from . import abft
                    self._att_pending.append(
                        (abft.digest_device(xj), abft.digest_host(buf)))
                self._carry = self._k.check_stream_chunk(
                    xj, jnp.int32(n), self._carry)
                self._chunks += 1
                self._rows_done += n
                if not self._dead:
                    # one host<->device sync per chunk, one chunk
                    # behind: the flag we block on is the PREVIOUS
                    # chunk's output, already produced while we were
                    # encoding this one — the poll overlaps compute
                    # instead of serializing after it
                    self._check_death(prev)
        finally:
            _trace.tracer().finish_span(sp)
        _wgl._M_CHUNK.labels(site="stream",
                             family=self.engine).observe(
            _time.monotonic() - t_chunk)
        _M_CHUNKS.labels(family=self.engine).inc()
        _M_LAG.observe(self.encoder.available())
        self._maybe_checkpoint()

    def _drain_attest(self) -> None:
        """Verify every pending staged-buffer digest (raises
        CorruptDeviceResult on a mismatch — callers run under the
        recovery ladder, which restores the last checkpoint and
        replays the canonical steps log)."""
        while self._att_pending:
            d, exp = self._att_pending[0]
            from . import abft
            abft.verify_steps(
                self.fault_site,
                guarded_device_get(d, site="stream attest"), exp)
            self._att_pending.pop(0)
            self._att_steps += 1

    def _check_death(self, carry) -> None:
        # ONE fetch per chunk, as designed: the pending staged-buffer
        # digests ride the liveness sync instead of paying their own
        # round-trips, and summarize's att output covers the in-kernel
        # invariants at the same boundary
        from . import abft
        pend, self._att_pending = self._att_pending, []
        summary, digs = guarded_device_get(
            (self._k.summarize(carry), [d for d, _ in pend]),
            site="stream liveness")
        ok, _death, overflow, _maxc, att = summary
        for dv, (_, exp) in zip(digs, pend):
            abft.verify_steps(self.fault_site, dv, exp)
            self._att_steps += 1
        _wgl._check_att(att, self.fault_site)
        self._chunk_syncs += 1
        if not bool(ok):
            self._dead = True
            self._dead_overflow = bool(overflow)
            if not self._dead_overflow:
                self.violation = True
                self.violation_at_op = len(self._client_ops)
                _M_VIOLATIONS.inc()
                if self._span_stream is not None:
                    self._span_stream.tags["violation"] = "true"
                log.warning(
                    "online checker: nonlinearizable prefix detected "
                    "after %d ops (%d steps dispatched)",
                    len(self._client_ops), self._chunks * self.chunk)

    # -- finish -----------------------------------------------------------

    def _replay(self, steps_x: np.ndarray, kernel) -> tuple:
        """Run a full step matrix through a chunk-shaped kernel,
        synchronously; returns the final carry."""
        import jax.numpy as jnp

        carry = kernel.init_carry(jnp.int32(self.model.device_state()))
        pad = np.zeros((self.chunk, steps_x.shape[1]), np.int32)
        w = steps_x.shape[1] - 4
        pad[:, w] = -1
        pad[:, w + 2:] = NIL
        for e in range(0, len(steps_x), self.chunk):
            sl = steps_x[e:e + self.chunk]
            buf = pad.copy()
            buf[:len(sl)] = sl
            carry = kernel.check_stream_chunk(
                jnp.asarray(buf), jnp.int32(len(sl)), carry)
        return carry

    def finish(self) -> dict | None:
        """Drain the tail, settle the verdict (escalating overflowed
        invalids like the offline path), and return the analysis.
        Every exit — verdict or a declined/disabled None — records the
        stream's root span (end_trace is idempotent), so exported
        chunk spans never point at a parent the collector lacks."""
        try:
            return self._finish_inner()
        finally:
            self.end_trace()

    def _finish_inner(self) -> dict | None:
        if self._failed is not None:
            return None
        t_tail = _time.monotonic()
        # settle loop: finishing can itself trigger a rebuild (a slot
        # overflow among the crash-tail pending ops, a dense range
        # escape in the last chunk) which replaces the encoder — keep
        # finishing until the stream is stable
        while True:
            enc = self.encoder
            try:
                enc.finish()
            except _wgl.SlotOverflow:
                self._rebuild(p=self.p * 2)
            except DeviceEncodingError as e:
                log.warning("online WGL stream disabled at finish "
                            "(%s)", e)
                return None
            else:
                self._pump(partial=True)
            if self._failed is not None:
                return None
            if self.encoder is enc and enc.finished:
                break
        ops = self.encoder.op_array()
        if self.dm.validate is not None:
            try:
                self.dm.validate(ops, self.model)
            except DeviceEncodingError as e:
                log.warning("online WGL verdict discarded: %s", e)
                return None

        def _settle():
            if self._k is None:
                self._setup()   # zero-op run: still produce a verdict
            if self._attest:
                self._drain_attest()
            out = guarded_device_get(
                self._k.summarize(self._carry), site="stream summarize")
            _wgl._check_att(out[-1], self.fault_site)
            return out

        settled = self._recovering(_settle, "summarize")
        if settled is None:
            return None   # budget spent; offline checking covers
        ok, death, overflow, max_count, att = settled
        del att   # _settle already checked it (nonzero raised there)
        ok, overflow = bool(ok), bool(overflow)
        F = self.frontier
        all_steps = (np.concatenate(self._steps_log)
                     if self._steps_log
                     else np.zeros((0, self.encoder.w + 4), np.int32))
        while (not ok and overflow and self.engine == "sort"
               and F < self.max_frontier):
            # invalid under overflow: the witness may have been dropped
            # — replay everything at 4x the frontier (offline contract)
            F *= 4

            def _escalate(F=F):
                k2 = _wgl._kernel(self.name, F, self.p, self.chunk,
                                  self._pack, pallas=self.pallas)
                carry = self._replay(all_steps, k2)
                out = guarded_device_get(
                    k2.summarize(carry), site="stream escalate")
                # inside the closure so a corrupt att re-runs under
                # the same recovery ladder as any other fault here
                _wgl._check_att(out[-1], self.fault_site)
                return k2, out

            esc = self._recovering(_escalate, "escalate",
                                   restore=False)
            if esc is None:
                return None
            k2, (ok, death, overflow, max_count, _att2) = esc
            ok, overflow = bool(ok), bool(overflow)
            self._k = k2
            # keep the stream's frontier in lockstep with the kernel:
            # a fault during blame rebuilds via _setup(), which reads
            # self.frontier — rebuilding at the pre-escalation size
            # would re-overflow and drop the witness
            self.frontier = F
        now = _time.monotonic()
        out = {
            "valid?": (True if ok else UNKNOWN if overflow else False),
            "model": repr(self.model),
            "analyzer": ("tpu-wgl-dense-streaming"
                         if self.engine == "dense"
                         else "tpu-wgl-streaming"),
            "dedup": (_wgl.DEDUP_NONE if self.engine == "dense" else
                      _wgl.dedup_engine(F, self.p, self._pack,
                                        self.pallas)),
            "op-count": len(ops),
            "max-frontier": int(max_count),
            "frontier-size": F,
            "chunks": self._chunks,
            "chunk-entries": self.chunk,
            "streamed": True,
            "history-len": len(self._client_ops),
            "tail-latency-ms": (now - t_tail) * 1e3,
            "duration-ms": ((now - self._t_first) * 1e3
                            if self._t_first is not None else 0.0),
            "configs": [],
            "final-paths": [],
        }
        if self._attest:
            out["attested"] = {"steps": self._att_steps,
                               "carry": self._att_carry}
        if self.faults:
            rec = {"faults": list(self.faults),
                   "retries": len(self.faults)}
            if self._resumed_from_chunk is not None:
                rec["resumed-from-chunk"] = self._resumed_from_chunk
            out["recovered"] = rec
        if self.violation:
            out["violation-at-op"] = self.violation_at_op
        if self._trace_ctx is not None:
            # the verdict names its trace: a violation resolves to the
            # exact chunk spans (and recovery retries) that decided it
            out["trace-id"] = self._trace_ctx["trace-id"]
            self.end_trace(valid=out["valid?"])
        if not ok:
            if overflow:
                out["error"] = (
                    f"frontier overflowed at {F} configs; verdict "
                    f"unknown (re-run offline with a larger frontier)")
            else:
                self._blame(ops, out)
        return out

    def _blame(self, ops: OpArray, out: dict) -> None:
        """Name the culprit op: unmerged replay through the same
        chunk-shaped kernel (the merged stream cannot name one), then
        host explain on the prefix — the offline invalid contract."""
        try:
            steps = _wgl.build_steps(ops, self.p, merge=False)
        except _wgl.SlotOverflow:   # cannot happen: same p as merged
            return

        def _run():
            if self._k is None:   # a recovery rung dropped the kernel
                self._setup()
            carry = self._replay(steps.x, self._k)
            return guarded_device_get(
                self._k.summarize(carry), site="stream blame")

        r = self._recovering(_run, "blame", restore=False)
        if r is None:
            # blame is best-effort: the verdict is already decided,
            # only the certificate detail is lost
            log.warning("online blame replay abandoned after backend "
                        "faults; verdict kept without a culprit op")
            return
        ok, death, *_rest = r
        d = int(death)
        if bool(ok) or d < 0:
            return
        row = int(steps.inv_row[d])
        if row < 0:
            row = int(steps.ret_row[d])
        if row < 0:
            return
        hist = History(self._client_ops).index()
        src = int(ops.index[row])
        op = _wgl._find_op(hist, src)
        if op is not None:
            out["op"] = op
            out["op-index"] = src
            try:
                from .linear import explain_failure
                ex = explain_failure(self.model, hist, src)
                if ex is not None:
                    out["configs"] = ex["configs"][:10]
                    out["final-paths"] = ex["final-paths"][:10]
                    if ex.get("previous-ok") is not None:
                        out["previous-ok"] = ex["previous-ok"]
            except Exception:  # noqa: BLE001 — blame is best-effort
                log.warning("online blame explain failed", exc_info=True)


# ---------------------------------------------------------------------------
# Streaming Elle (rw-register): incremental edge accumulation
# ---------------------------------------------------------------------------

_INIT = object()   # the unwritten initial version (reads observe None)


class WrStream:
    """Incremental rw-register dependency analysis.

    Accumulates the same ww/wr/rw edges `wr.graph` derives — plus the
    single-pass G1a/G1b/internal/duplicate cases — as completions
    arrive, one txn at a time. References that resolve only later (a
    read of a value whose writer has not completed yet, a version pair
    naming a future writer, a failed write read before it failed) are
    held in pending indexes and the edges materialize when the other
    side lands, so nothing is ever re-scanned. finish() runs the one
    global pass that cannot stream — SCC condensation + device
    classification over the accumulated graph — and shapes the result
    exactly like `wr.check`.

    Node ids are completion-arrival order (the batch path orders oks
    before infos); the graphs are isomorphic, so verdicts and anomaly
    types agree — pinned by tests. Assumes the wr workload's unique-
    writes contract for exact parity (violations still *flag*
    duplicate-writes either way)."""

    def __init__(self, anomalies=None, mesh=None):
        from .elle import wr as _wr
        self._wr = _wr
        self.anomalies = tuple(anomalies) if anomalies is not None \
            else _wr.DEFAULT_ANOMALIES
        self.mesh = mesh
        self.txns: list[dict] = []
        self._acc: dict[tuple, int] = {}
        self._writer_of: dict = {}        # (k,v) -> (ti, final?, op)
        self._writers_by_key: dict = {}   # k -> [ti]
        self._ext_readers: dict = {}      # (k,v) -> [(ti, op)]
        self._nil_readers: dict = {}      # k -> [(ti, op)]
        self._raw_readers: dict = {}      # (k,v) -> [(ti, op, mop)]
        self._succ: dict = {}             # (k,u) -> [v]
        self._pairs_by_second: dict = {}  # (k,v) -> [u]
        self._pairs_seen: set = set()
        self._failed_writes: dict = {}    # (k,v) -> op
        self._internal: list = []
        self._g1a: list = []
        self._g1b: list = []
        self._duplicates: list = []
        self.client_ops_fed = 0

    def export_checkpoint(self) -> dict:
        """Host streams carry no device state worth persisting: the
        durable manifest records progress counters only, and a
        recovered stream re-derives everything by re-feeding the
        journal (one cheap host-side pass). kind='host' tells a
        resuming service there is nothing to import."""
        return {"kind": "host", "ops-fed": int(self.client_ops_fed)}

    # edge helper — masks as in kernels (_WW=1, _WR=2, _RW=4)
    def _edge(self, i: int, j: int, mask: int) -> None:
        if i != j:
            key = (i, j)
            self._acc[key] = self._acc.get(key, 0) | mask

    def feed(self, op: dict) -> None:
        if not isinstance(op.get("process"), int):
            return
        self.client_ops_fed += 1
        t = op.get("type")
        v = op.get("value")
        if t == "invoke":
            return
        if t == "fail":
            self._feed_fail(op)
            return
        if not isinstance(v, (list, tuple)):
            return   # matches _Analysis's info filter; oks are txns
        if t == "ok":
            self._feed_ok(op)
        elif t == "info":
            ti = len(self.txns)
            self.txns.append(op)
            self._feed_writes(ti, op)

    def _feed_fail(self, op: dict) -> None:
        from .. import txn as mop
        for m in (op.get("value") or ()):
            if mop.is_write(m) and m[2] is not None:
                k, v = m[1], m[2]
                self._failed_writes[(k, v)] = op
                for (rj, ro, ml) in self._raw_readers.get((k, v), ()):
                    self._g1a.append({"op": ro, "mop": ml, "writer": op})

    def _feed_writes(self, ti: int, op: dict) -> None:
        from .elle import kernels
        writes: dict = {}
        for m in (op.get("value") or ()):
            if m[0] == "w" and m[2] is not None:
                writes.setdefault(m[1], []).append(m[2])
        for k, vs in writes.items():
            for i, v in enumerate(vs):
                final = i == len(vs) - 1
                prev = self._writer_of.get((k, v))
                if prev is not None:
                    self._duplicates.append(
                        {"key": k, "value": v, "ops": [prev[2], op]})
                self._writer_of[(k, v)] = (ti, final, op)
                self._writers_by_key.setdefault(k, []).append(ti)
                # wr to readers already seen; G1b if this write is
                # internal (non-final) to its txn
                for (rj, ro) in self._ext_readers.get((k, v), ()):
                    self._edge(ti, rj, kernels._WR)
                if not final:
                    for (rj, ro, ml) in self._raw_readers.get(
                            (k, v), ()):
                        if ro is not op:
                            self._g1b.append(
                                {"op": ro, "mop": ml, "writer": op})
                # a read of nil anti-depends on every writer of the key
                for (rj, ro) in self._nil_readers.get(k, ()):
                    self._edge(rj, ti, kernels._RW)
                # version pairs naming v as the successor: u -> v
                for u in self._pairs_by_second.get((k, v), ()):
                    if u is not _INIT:
                        wu = self._writer_of.get((k, u))
                        if wu is not None:
                            self._edge(wu[0], ti, kernels._WW)
                    for (rj, ro) in self._ext_readers.get((k, u), ()):
                        self._edge(rj, ti, kernels._RW)
                # ... and as the predecessor: v -> v2
                for v2 in self._succ.get((k, v), ()):
                    w2 = self._writer_of.get((k, v2))
                    if w2 is not None:
                        self._edge(ti, w2[0], kernels._WW)

    def _feed_ok(self, op: dict) -> None:
        from .. import txn as mop
        from .elle import kernels
        ti = len(self.txns)
        self.txns.append(op)
        case = self._wr.op_internal_case(op)
        if case is not None:
            self._internal.append(case)
        self._feed_writes(ti, op)
        # raw reads: G1a/G1b (the batch path scans raw read mops, not
        # just external reads)
        for m in (op.get("value") or ()):
            if m[0] == "r" and m[2] is not None:
                k, v = m[1], m[2]
                ml = list(m)
                self._raw_readers.setdefault((k, v), []).append(
                    (ti, op, ml))
                w = self._writer_of.get((k, v))
                if w is not None and not w[1] and w[2] is not op:
                    self._g1b.append({"op": op, "mop": ml,
                                      "writer": w[2]})
                fw = self._failed_writes.get((k, v))
                if fw is not None:
                    self._g1a.append({"op": op, "mop": ml, "writer": fw})
        # external reads: wr / rw edges
        for k, v in mop.ext_reads(op.get("value") or ()).items():
            if v is None:
                self._nil_readers.setdefault(k, []).append((ti, op))
                for wj in self._writers_by_key.get(k, ()):
                    self._edge(ti, wj, kernels._RW)
                continue
            self._ext_readers.setdefault((k, v), []).append((ti, op))
            w = self._writer_of.get((k, v))
            if w is not None:
                self._edge(w[0], ti, kernels._WR)
            for v2 in self._succ.get((k, v), ()):
                w2 = self._writer_of.get((k, v2))
                if w2 is not None:
                    self._edge(ti, w2[0], kernels._RW)
        # intra-txn version order
        cur: dict = {}
        for m in (op.get("value") or ()):
            k, v = m[1], m[2]
            if m[0] == "r":
                cur[k] = _INIT if v is None else v
            elif v is not None:
                u = cur.get(k)
                if u is not None and u != v:
                    self._new_pair(k, u, v)
                cur[k] = v

    def _new_pair(self, k, u, v) -> None:
        from .elle import kernels
        if (k, u, v) in self._pairs_seen:
            return
        self._pairs_seen.add((k, u, v))
        self._succ.setdefault((k, u), []).append(v)
        self._pairs_by_second.setdefault((k, v), []).append(u)
        wv = self._writer_of.get((k, v))
        if wv is None:
            return   # the writer-arrival trigger will materialize these
        if u is not _INIT:
            wu = self._writer_of.get((k, u))
            if wu is not None:
                self._edge(wu[0], wv[0], kernels._WW)
            for (rj, ro) in self._ext_readers.get((k, u), ()):
                self._edge(rj, wv[0], kernels._RW)

    def finish(self) -> dict:
        from .elle import kernels
        t0 = _time.monotonic()
        found: dict[str, list] = {}
        if self._duplicates:
            found["duplicate-writes"] = self._duplicates
        if self._g1a:
            found["G1a"] = self._g1a
        if self._g1b:
            found["G1b"] = self._g1b
        if self._internal:
            found["internal"] = self._internal
        edges = kernels.mask_edges_to_sets(self._acc)
        cyc = kernels.analyze_edges(len(self.txns), edges,
                                    mesh=self.mesh)
        found.update(kernels.certificates(self.txns, edges, cyc))
        reported = {t: cases for t, cases in found.items()
                    if t in self.anomalies}
        return {
            "valid?": not reported,
            "anomaly-types": sorted(reported),
            "anomalies": reported,
            "txn-count": len(self.txns),
            "streamed": True,
            "history-len": self.client_ops_fed,
            # reuse guard: a checker may only adopt this result if it
            # would have asked the same question
            "checked-anomalies": sorted(self.anomalies),
            "tail-latency-ms": (_time.monotonic() - t0) * 1e3,
        }


# ---------------------------------------------------------------------------
# The driver: one background thread feeding every stream target
# ---------------------------------------------------------------------------

_SENTINEL = object()


class OnlineChecker:
    """Consumes history ops (offer(), or a Journal subscription wired
    to offer) on a dedicated thread and feeds every stream target.
    should_abort() flips once a target confirms a definite violation
    and abort_on_violation was requested — the interpreter polls it
    and stops issuing ops. finalize() drains, finishes every target,
    and returns {target-name: result} (targets that failed or
    declined return no entry; offline checking covers them)."""

    def __init__(self, targets: dict[str, Any],
                 abort_on_violation: bool = False):
        self.targets = dict(targets)
        self.abort_on_violation = abort_on_violation
        self.aborted = False
        self.driver_error: str | None = None
        self._abort = threading.Event()
        self._q: _queue.SimpleQueue = _queue.SimpleQueue()
        self._results: dict[str, dict] = {}
        self._client_ops = 0
        self._thread = threading.Thread(
            target=self._run, name="jepsen-online-checker", daemon=True)
        self._thread.start()

    def offer(self, op: dict) -> None:
        self._q.put(op)

    def should_abort(self) -> bool:
        return self._abort.is_set()

    def _run(self) -> None:
        # the driver thread must not die silently: an uncaught
        # exception here used to discard every streamed result with no
        # trace — now it stamps driver_error, finalize() marks the
        # streamed-results degraded, and core.run's offline re-check
        # covers the targets
        try:
            self._run_inner()
        except BaseException:  # noqa: BLE001 — thread boundary
            self.driver_error = traceback.format_exc()
            log.warning("online checker driver thread crashed; "
                        "streamed results are discarded and offline "
                        "checking covers them", exc_info=True)

    def _run_inner(self) -> None:
        dead: set[str] = set()
        while True:
            op = self._q.get()
            if op is _SENTINEL:
                break
            if isinstance(op.get("process"), int):
                self._client_ops += 1
            for name, t in self.targets.items():
                if name in dead:
                    continue
                try:
                    t.feed(op)
                except Exception:  # noqa: BLE001 — run must survive us
                    log.warning("online target %r failed; offline "
                                "checking will cover it", name,
                                exc_info=True)
                    dead.add(name)
            if self.abort_on_violation and not self._abort.is_set():
                if any(getattr(t, "violation", False)
                       for n, t in self.targets.items()
                       if n not in dead):
                    self.aborted = True
                    self._abort.set()
        for name, t in self.targets.items():
            if name in dead:
                continue
            try:
                r = t.finish()
            except Exception:  # noqa: BLE001
                log.warning("online target %r failed at finish; "
                            "offline checking will cover it", name,
                            exc_info=True)
                continue
            if r is not None:
                r.setdefault("history-len", self._client_ops)
                self._results[name] = r

    def finalize(self, timeout_s: float | None = 600.0) -> dict:
        """Stop the driver and return every finished target's result.
        A crashed driver thread yields {'degraded': True, 'error': tb}
        (no per-target verdicts) so the caller can log the degradation
        and fall through to offline checking."""
        self._q.put(_SENTINEL)
        self._thread.join(timeout_s)
        if self._thread.is_alive():
            log.warning("online checker still finishing after %ss; "
                        "abandoning it (offline checking still runs)",
                        timeout_s)
            return {}
        out = dict(self._results)
        if self.driver_error is not None:
            out["degraded"] = True
            out["error"] = self.driver_error
        return out

    def close(self) -> None:
        """Crash-path stop: don't wait for tail verification."""
        self._q.put(_SENTINEL)
        self._thread.join(5.0)


def _walk_checkers(checker):
    """Yield leaf checkers (descending through Compose)."""
    from . import Compose, ConcurrencyLimit, FnChecker
    if isinstance(checker, Compose):
        for c in checker.checkers.values():
            yield from _walk_checkers(c)
    elif isinstance(checker, ConcurrencyLimit):
        yield from _walk_checkers(checker.checker)
    elif isinstance(checker, FnChecker):
        yield checker.fn
    elif checker is not None:
        yield checker


def maybe_online(test: dict):
    """Build an OnlineChecker for a test that asked for one ('online'
    truthy), wiring a stream target per recognized checker: the first
    Linearizable with a device-form model (key 'linear') and the first
    RWRegisterChecker without additional graphs (key 'elle-wr').
    At tier 'screen' (test['tier'], CLI --tier) the O(n) tier-1
    screens additionally run over the live journal feed
    ('screen-linear' / 'screen-wr' — host-side, model-agnostic
    enough to cover checkers the device streams decline), and their
    verdicts are what the tiered checkers reuse at analyze time.
    Returns None when the test declined or nothing is streamable."""
    if not test.get("online"):
        return None
    from . import screen as _screen
    from .elle import RWRegisterChecker
    from .linear import Linearizable

    targets: dict[str, Any] = {}
    tiered = _screen.tier_is_screen(test.get("tier"))
    for c in _walk_checkers(test.get("checker")):
        if tiered and isinstance(c, Linearizable) \
                and "screen-linear" not in targets:
            targets["screen-linear"] = _screen.ScreenStream(c.model)
        if tiered and isinstance(c, RWRegisterChecker) \
                and not c.additional_graphs \
                and "screen-wr" not in targets:
            targets["screen-wr"] = _screen.WrScreen(
                anomalies=c.anomalies)
        if isinstance(c, Linearizable) and "linear" not in targets:
            if c.model.device_model is None or c.algorithm == "host":
                continue
            try:
                targets["linear"] = WglStream(
                    c.model,
                    frontier=c.opts.get("frontier", 256),
                    max_frontier=c.opts.get("max_frontier", 65536),
                    chunk_entries=test.get("online-chunk-entries",
                                           DEFAULT_CHUNK_ENTRIES),
                    engine=("auto"
                            if test.get("online-state-range") else
                            "sort"),
                    state_range=test.get("online-state-range"),
                    concurrency_hint=test.get("concurrency"),
                    pallas=c.opts.get("pallas"),
                    checkpoint_every=test.get(
                        "online-checkpoint-every",
                        DEFAULT_CHECKPOINT_EVERY),
                    max_recovery_retries=test.get(
                        "max-recovery-retries"))
            except (ValueError, ImportError) as e:
                log.warning("online: linearizable target declined: %s",
                            e)
        elif isinstance(c, RWRegisterChecker) and \
                "elle-wr" not in targets:
            if c.additional_graphs:
                # precedence graphs need full-history positions; the
                # offline path handles them
                log.info("online: elle-wr target declined "
                         "(additional_graphs configured)")
                continue
            targets["elle-wr"] = WrStream(anomalies=c.anomalies,
                                          mesh=c.mesh)
    if not targets:
        log.info("online verification requested but no streamable "
                 "checker found; running offline only")
        return None
    log.info("online verification enabled: %s", sorted(targets))
    return OnlineChecker(
        targets,
        abort_on_violation=bool(test.get("abort-on-violation")))


def stream_check(model, hist, **kw) -> dict | None:
    """Convenience: push a complete history through a WglStream (as the
    live run would, op by op) and finish — the one-call form for tests
    and benchmarks."""
    s = WglStream(model, **kw)
    for op in as_history(hist).ops:
        s.feed(op)
    return s.finish()
