"""Elle-class transactional anomaly detection, TPU-native.

The reference's per-suite `append`/`wr` workloads call the Elle JVM
library (`jepsen/src/jepsen/tests/cycle{,/append,/wr}.clj`). Here the
dependency graphs are built host-side as sparse edge lists, condensed to
strongly-connected components in linear time (every cycle lives inside
one SCC), and the nontrivial SCCs are classified on device
(`kernels.py`): batched dense blocks, transitive closure as repeated
boolean matrix squaring on the MXU, vmapped over SCCs and sharded over a
`Mesh` for huge histories. Valid histories (no nontrivial SCC)
short-circuit with zero device work, which is what lets 100k-txn
north-star histories (BASELINE config 5) check in seconds.

Anomaly specs accept Adya shorthand: 'G1' expands to G1a+G1b+G1c, 'G2'
to G-single+G2-item (matching `tests/cycle/wr.clj:31-45`'s taxonomy).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

from .. import Checker
from ...history import history as _history
from . import graphs, kernels, list_append, wr  # noqa: F401

_EXPANSIONS = {
    "G1": ("G1a", "G1b", "G1c"),
    "G2": ("G-single", "G2-item"),
}


def expand_anomalies(anomalies: Iterable[str]) -> tuple:
    out: list = []
    for a in anomalies:
        for x in _EXPANSIONS.get(a, (a,)):
            if x not in out:
                out.append(x)
    return tuple(out)


class ListAppendChecker(Checker):
    """Checker adapter over list_append.check (reference
    `tests/cycle/append.clj:11-55`; default anomalies [:G1 :G2] plus the
    definite single-pass errors). additional_graphs folds realtime /
    process precedence edges into the cycle search (reference
    `tests/cycle/append.clj:48-50` via `:additional-graphs`)."""

    def __init__(self, anomalies=("G0", "G1", "G2"), mesh=None,
                 additional_graphs=()):
        extra = ("internal", "duplicate-elements", "incompatible-order")
        self.anomalies = expand_anomalies(tuple(anomalies) + extra)
        self.mesh = mesh
        self.additional_graphs = tuple(additional_graphs)

    def check(self, test, hist, opts):
        return list_append.check(
            hist, self.anomalies, mesh=self.mesh,
            additional_graphs=self.additional_graphs)


class RWRegisterChecker(Checker):
    """Checker adapter over wr.check (reference
    `tests/cycle/wr.clj:14-54`; `:additional-graphs` per its lines
    17-26).

    Honors the test map's 'tier' knob (CLI --tier): at tier 'screen'
    the O(n) WrScreen (single-pass anomalies + exact SCC cycle
    existence — see checker/screen.py) decides whether the full
    classification/certificate search runs at all. Checkers with
    additional precedence graphs always run the full search: the
    screen's SCC pass covers only the dependency edges."""

    def __init__(self, anomalies=("G0", "G1", "G2"), mesh=None,
                 additional_graphs=()):
        extra = ("internal", "duplicate-writes")
        self.anomalies = expand_anomalies(tuple(anomalies) + extra)
        self.mesh = mesh
        self.additional_graphs = tuple(additional_graphs)

    def check(self, test, hist, opts):
        from .. import screen as _screen
        if _screen.tier_is_screen((test or {}).get("tier")) \
                and not self.additional_graphs:
            return self._tier1(test, hist)
        return self._full_check(test, hist)

    def _tier1(self, test, hist):
        from .. import screen as _screen
        sc = self._streamed_screen(test, hist) \
            or _screen.screen_wr(hist, anomalies=self.anomalies)
        sample = (test or {}).get("screen-sample")
        if sample is None:
            sample = _screen.DEFAULT_SAMPLE
        esc, why = _screen.should_escalate(sc, sample=float(sample))
        if not esc:
            out = dict(sc)
            out["tier"] = 1
            return out
        full = self._full_check(test, hist)
        full["escalated"] = _screen.escalation_record(sc, why)
        full["tier"] = 1
        return full

    def _streamed_screen(self, test, hist):
        r = ((test or {}).get("streamed-results") or {}) \
            .get("screen-wr")
        if not r or not r.get("screened"):
            return None
        if r.get("history-len") != len(_history(hist).client_ops()):
            return None
        return dict(r)

    def _full_check(self, test, hist):
        # a result the online pipeline already streamed during the run
        # (checker/streaming.WrStream) is reused instead of rebuilding
        # the graph — guarded on covering the same history AND asking
        # the same question: a sibling checker with additional graphs
        # or a different anomaly set must run its own (offline) search
        r = ((test or {}).get("streamed-results") or {}).get("elle-wr")
        if r and not self.additional_graphs \
                and r.get("checked-anomalies") == sorted(self.anomalies) \
                and r.get("history-len") == len(
                    _history(hist).client_ops()):
            return dict(r)
        return wr.check(hist, self.anomalies, mesh=self.mesh,
                        additional_graphs=self.additional_graphs)


def list_append_checker(anomalies=("G0", "G1", "G2"), mesh=None,
                        additional_graphs=()) -> Checker:
    return ListAppendChecker(anomalies, mesh, additional_graphs)


def rw_register_checker(anomalies=("G0", "G1", "G2"), mesh=None,
                        additional_graphs=()) -> Checker:
    return RWRegisterChecker(anomalies, mesh, additional_graphs)


# ---------------------------------------------------------------------------
# Generators (reference: elle.list-append/gen, elle.rw-register/gen, used
# by tests/cycle/append.clj:19-27 and tests/cycle/wr.clj:12,51)
# ---------------------------------------------------------------------------

from ... import generator as gen  # noqa: E402


@dataclasses.dataclass(frozen=True)
class _TxnGen(gen.Gen):
    """Random transactions over a sliding window of active keys. Appends/
    writes use per-key monotone counters so every written value is unique
    and (for appends) traceable."""
    mode: str               # 'append' | 'wr'
    key_count: int          # active window size
    min_len: int
    max_len: int
    max_writes_per_key: int
    next_key: int           # keys [next_key - key_count, next_key) active
    counters: tuple         # ((key, next value), ...)

    def op(self, test, ctx):
        length = gen.rng.randint(self.min_len, self.max_len)
        txn = []
        counters = dict(self.counters)
        next_key = self.next_key
        lo = max(0, next_key - self.key_count)
        write_f = "append" if self.mode == "append" else "w"
        for _ in range(length):
            k = gen.rng.randrange(lo, max(lo + 1, next_key))
            if gen.rng.random() < 0.5:
                v = counters.get(k, 1)
                counters[k] = v + 1
                txn.append([write_f, k, v])
                if v >= self.max_writes_per_key:
                    next_key += 1  # retire the hottest key, open a new one
            else:
                txn.append(["r", k, None])
        o = gen.fill_in_op({"f": "txn", "value": txn}, ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, dataclasses.replace(
            self, next_key=next_key,
            counters=tuple(sorted(counters.items())))

    def update(self, test, ctx, event):
        return self


def append_gen(key_count: int = 5, min_txn_length: int = 1,
               max_txn_length: int = 4,
               max_writes_per_key: int = 16) -> gen.Gen:
    """List-append transaction generator."""
    return _TxnGen("append", key_count, min_txn_length, max_txn_length,
                   max_writes_per_key, 1, ())


def wr_gen(key_count: int = 5, min_txn_length: int = 1,
           max_txn_length: int = 4,
           max_writes_per_key: int = 16) -> gen.Gen:
    """Write/read register transaction generator."""
    return _TxnGen("wr", key_count, min_txn_length, max_txn_length,
                   max_writes_per_key, 1, ())
