"""Additional precedence graphs for the Elle cycle search (reference
`jepsen/src/jepsen/tests/cycle.clj:9-16` folds extra graph analyzers —
most importantly `cycle/realtime-graph` — into the dependency-cycle
search; `tests/cycle/wr.clj:17-26` is the canonical consumer).

Two graphs are derivable from the history alone, no workload semantics
needed:

  * **realtime** — op A completed (:ok) before op B was invoked. Built
    with the completed-frontier construction: walking the journal in
    order, each invocation links from every member of the current
    antichain of maximal completed ops; a completion evicts the ops it
    was linked from. The edge set is transitively reduced (size is
    bounded by concurrency x ops, not ops^2) and its transitive closure
    is exactly the realtime order — all the cycle search needs. :info
    ops never complete, so they take incoming edges only.
  * **process** — same process, consecutive ops. A chain edge per
    adjacent pair; an :info op ends its chain (its effect time is
    unknown, and in Jepsen a crashed process number is never reused).
    Since a process invokes its next op only after the previous
    completed, process edges are a subset of the realtime relation —
    which is why the classifier's realtime level folds both
    (`kernels._LEVEL_SPECS`).

The edges union with the workload-derived ww/wr/rw edges into one
adjacency structure (`union_edges`) and ride the existing pipeline
unchanged: one sparse SCC condensation over the union, then per-level
dense classification on device (kernels.py stacks the levels along the
vmapped batch axis, so the MXU kernel itself never changes). Cycles
that *require* a precedence edge classify as G0-process, G0-realtime,
G1c-process, G1c-realtime, G-single-process, G-single-realtime,
G2-item-process, G2-item-realtime — the reference's `elle.txn`
taxonomy.
"""

from __future__ import annotations

from ...history import history as as_history, is_ok
from . import kernels

GRAPH_NAMES = ("realtime", "process")


def node_intervals(hist, ops) -> list:
    """Per-op (inv_pos, comp_pos, ok?) tuples, positions within `hist`'s
    journal order (which the interpreter guarantees is consistent with
    real time). `ops` are completion ops drawn from `hist`; an op whose
    invocation was not journaled (completion-only histories are legal
    checker input) gets inv_pos -1 — "invoked before everything" — so
    it can never *gain* a precedence edge it cannot prove, only grant
    them from its journaled completion."""
    hist = as_history(hist)
    pos_of = {id(o): p for p, o in enumerate(hist.ops)}
    pairs = hist.pair_index()
    end = len(hist.ops)
    out = []
    for o in ops:
        cp = pos_of.get(id(o))
        if cp is None:
            out.append((end, end, False))
            continue
        ip = pairs.get(cp, -1)
        out.append((min(ip, cp), cp, is_ok(o)))
    return out


def realtime_edges(hist, txns) -> dict:
    """{(i, j): mask} — txn i completed before txn j was invoked
    (transitively reduced via the completed frontier)."""
    iv = node_intervals(hist, txns)
    events = []
    for ti, (ip, cp, ok) in enumerate(iv):
        events.append((ip, 0, ti))
        if ok:
            events.append((cp, 1, ti))
    events.sort()
    acc: dict[tuple, int] = {}
    frontier: set[int] = set()
    snapshot: dict[int, frozenset] = {}
    for _pos, tag, ti in events:
        if tag == 0:    # invocation: link from the completed frontier
            s = frozenset(frontier)
            snapshot[ti] = s
            for a in s:
                acc[(a, ti)] = kernels._RT
        else:           # completion: evict everything it was linked from
            frontier -= snapshot.get(ti, frozenset())
            frontier.add(ti)
    return acc


def process_edges(hist, txns) -> dict:
    """{(i, j): mask} — consecutive ops of one process, chained in
    *completion* order; edges originate only from :ok ops. A process is
    sequential (it invokes its next op only after the previous one
    completed), so its completions journal in op order — which makes
    completion position the correct chain key even for ops whose
    invocation was never journaled (invocation order would put those
    first and fabricate reversed edges)."""
    iv = node_intervals(hist, txns)
    by_proc: dict = {}
    for ti, (_ip, cp, _ok) in enumerate(iv):
        by_proc.setdefault(txns[ti].get("process"), []).append((cp, ti))
    acc: dict[tuple, int] = {}
    for lst in by_proc.values():
        lst.sort()
        for (_, a), (_, b) in zip(lst, lst[1:]):
            if is_ok(txns[a]):
                acc[(a, b)] = kernels._PROC
    return acc


_BUILDERS = {"realtime": realtime_edges, "process": process_edges}


def additional_edges(hist, txns, graphs) -> dict:
    """Union of the requested precedence graphs over the txn node list,
    as {(i, j): frozenset of edge-type names}."""
    hist = as_history(hist)
    acc: dict[tuple, int] = {}
    for g in graphs:
        builder = _BUILDERS.get(g)
        if builder is None:
            raise ValueError(f"unknown additional graph {g!r}; "
                             f"expected one of {GRAPH_NAMES}")
        for k, m in builder(hist, txns).items():
            acc[k] = acc.get(k, 0) | m
    return kernels.mask_edges_to_sets(acc)


def union_edges(*edge_dicts) -> dict:
    """Union several {(i, j): types} edge dicts into one (types may be
    frozensets or masks); the result uses the shared frozensets."""
    acc: dict[tuple, int] = {}
    for d in edge_dicts:
        for k, t in d.items():
            acc[k] = acc.get(k, 0) | kernels.type_mask(t)
    return kernels.mask_edges_to_sets(acc)


def expand_anomalies(anomalies, graphs) -> tuple:
    """Extend an anomaly list with the -process/-realtime variants of
    whichever cycle anomalies it already names, per the requested
    graphs. A caller asking for G-single with realtime edges is asking
    for G-single-realtime too (`tests/cycle/wr.clj:17-26` wires the
    realtime analyzer in exactly this implicit way)."""
    out = list(anomalies)
    for base in kernels._VARIANT_BASES:
        if base not in out:
            continue
        if "process" in graphs:
            out.append(base + "-process")
        if "realtime" in graphs:
            out.append(base + "-realtime")
    return tuple(dict.fromkeys(out))
