"""Elle-class list-append checker (reference consumes
`elle.list-append/check` via `jepsen/src/jepsen/tests/cycle/append.clj:
11-55`; algorithm re-derived from the Elle paper's list-append analysis).

Txns are micro-op lists mixing ['append', k, v] and ['r', k, [v...]].
Because appends are traceable — every read of k returns the *full
append order so far* — the per-key version order is recoverable:

  * a read whose value is None carries no information (the client never
    filled it in); an observed-empty read is [];
  * every observed read list must be a prefix of the longest one
    (else 'incompatible-order');
  * the longest list per key is the version chain v1 < v2 < ...;
  * ww: writer(vi) -> writer(vi+1) for consecutive versions with
    distinct writers;
  * wr: writer(last element of a read) -> reader;
  * rw: reader of a prefix ending at vi -> writer(vi+1) (reads of the
    empty list anti-depend on the first writer).

Single-pass anomalies: duplicate appended elements, G1a (reading a
failed txn's append), G1b (observing an intermediate state of a
multi-append txn), internal (a txn's read inconsistent with its own
earlier ops).

Cycle anomalies (G0/G1c/G-single/G2-item) are decided by
`kernels.analyze_edges` (sparse SCC condensation + batched MXU
classification); certificates are reconstructed host-side.
"""

from __future__ import annotations

from typing import Any

from ... import txn as mop
from ...history import history as as_history, is_fail, is_info, is_ok
from . import graphs as precedence, kernels


def _is_append(m) -> bool:
    return m[0] == "append"


# edge-type bitmask for graph()'s hot accumulation path; kernels owns
# the canonical bits and the mask -> shared-frozenset table in the
# {(i, j): {'ww', ...}} shape the cycle analyzers consume
_WW, _WR, _RW = kernels._WW, kernels._WR, kernels._RW


def op_internal_case(op: dict) -> dict | None:
    """A txn's reads must be consistent with its own earlier appends: a
    read of k after this txn appended vs must end with those vs in
    order."""
    # micro-op fields accessed positionally (f, k, v = m): this loop
    # runs once per mop over 100k-txn histories
    expected_suffix: dict[Any, list] = {}
    prev_read: dict[Any, list] = {}
    for m in op.get("value") or ():
        k = m[1]
        if m[0] == "append":
            expected_suffix.setdefault(k, []).append(m[2])
            if k in prev_read:
                prev_read[k] = prev_read[k] + [m[2]]
        elif m[0] == "r":
            if m[2] is None:
                continue  # unfilled read: no information
            v = list(m[2])
            suffix = expected_suffix.get(k, [])
            if suffix and v[len(v) - len(suffix):] != suffix:
                return {"op": op, "mop": list(m),
                        "expected": ["...", *suffix]}
            if k in prev_read and v[:len(prev_read[k])] != prev_read[k]:
                return {"op": op, "mop": list(m),
                        "expected": prev_read[k]}
            prev_read[k] = v
    return None


def internal_cases(hist) -> list:
    # a txn needs at least two mops to disagree with itself; skipping
    # the (common) single-mop txns saves two dict allocations each
    # across a 100k-txn history
    out = []
    for o in hist:
        if is_ok(o):
            v = o.get("value")
            if v is not None and len(v) > 1:
                c = op_internal_case(o)
                if c is not None:
                    out.append(c)
    return out


class _Analysis:
    """Shared single-pass extraction over an indexed client history."""

    def __init__(self, hist):
        hist = as_history(hist).index().client_ops()
        self.hist = hist
        self.oks = [o for o in hist if is_ok(o)]
        self.infos = [o for o in hist if is_info(o)]
        self.fails = [o for o in hist if is_fail(o)]
        # txns is the graph's node order; writer_of[k][v] -> (txn index,
        # final?) for ok/info appends.  Indices (not op objects) keep
        # the 100k-txn hot loops free of id()-keyed lookups — an ok
        # writer is exactly an index < len(self.oks).
        self.txns = self.oks + self.infos
        self.writer_of: dict[Any, dict[Any, tuple]] = {}
        self.duplicates: list = []
        # ok_reads: every informative read mop of an ok txn, extracted
        # once as (reader txn index, op, mop) — version_orders, g1a,
        # g1b, and graph() all iterate this flat list instead of
        # re-dispatching over every op's mop list (4 extra full passes
        # at 100k-txn scale)
        self.ok_reads: list[tuple] = []
        n_oks = len(self.oks)
        for ti, o in enumerate(self.txns):
            appended: dict[Any, list] = {}
            val = o.get("value")
            if ti >= n_oks and not isinstance(val, (list, tuple)):
                continue  # info op that crashed before we knew the txn
            is_ok_t = ti < n_oks
            for m in val or ():
                if m[0] == "append":
                    appended.setdefault(m[1], []).append(m[2])
                elif is_ok_t and m[0] == "r" and m[2] is not None:
                    self.ok_reads.append((ti, o, m))
            for k, vs in appended.items():
                for i, v in enumerate(vs):
                    w = self.writer_of.setdefault(k, {})
                    if v in w:
                        self.duplicates.append(
                            {"key": k, "value": v,
                             "ops": [self.txns[w[v][0]], o]})
                    w[v] = (ti, i == len(vs) - 1)
        self.failed_writes = {
            (mop.key(m), mop.value(m)): o
            for o in self.fails
            for m in (o.get("value") or ())
            if _is_append(m)}

    def version_orders(self):
        """Longest observed prefix per key; returns (orders, incompatible)
        where orders[k] is the version chain and incompatible lists
        prefix-violations."""
        longest: dict[Any, list] = {}
        incompatible: list = []
        for _ri, o, m in self.ok_reads:
            k, v = m[1], list(m[2])
            cur = longest.get(k, [])
            shorter, lnger = (v, cur) if len(v) <= len(cur) \
                else (cur, v)
            if lnger[:len(shorter)] != shorter:
                incompatible.append(
                    {"key": k, "values": [cur, v], "op": o})
            elif len(v) > len(cur):
                longest[k] = v
        return longest, incompatible

    def g1a_cases(self) -> list:
        """Reads observing a failed append (`aborted read`)."""
        fw = self.failed_writes
        if not fw:
            return []   # no failed appends: nothing to observe
        # only reads of keys with a failed append can hit; scanning
        # every element of every read otherwise costs ~1s per 100k txns
        fkeys = {k for k, _v in fw}
        cases = []
        for _ri, o, m in self.ok_reads:
            if m[2] and m[1] in fkeys:
                k = m[1]
                for v in m[2]:
                    w = fw.get((k, v))
                    if w is not None:
                        cases.append({"op": o, "mop": list(m),
                                      "writer": w})
        return cases

    def g1b_cases(self) -> list:
        """Reads whose final observed element is a non-final append of a
        multi-append txn (`intermediate read`)."""
        cases = []
        wo = self.writer_of
        empty: dict = {}
        for ri, o, m in self.ok_reads:
            if m[2]:
                k, v = m[1], m[2][-1]
                w = wo.get(k, empty).get(v)
                if w is not None and not w[1] and w[0] != ri:
                    cases.append({"op": o, "mop": list(m),
                                  "writer": self.txns[w[0]]})
        return cases


def graph(hist):
    """Build the sparse dependency graph. Returns (txn_ops, edges, a,
    incompatible) where txn_ops[i] is the i-th transaction (ok/info) and
    edges maps (i, j) -> set of edge-type strings.

    rw edges stay linear in history size: a read of the chain prefix
    ending at v_i anti-depends on writer(v_{i+1}) only — the *immediate*
    in-chain successor; anti-dependencies on later versions are rw;ww*
    composites reconstructed through the ww chain, which preserves both
    cycle detection and the one-vs-many-rw classification. Appends never
    observed in any read carry genuine information of their own — the
    read proves they happened after its snapshot — so each reader
    anti-depends on every never-observed :ok append of its key (crashed
    never-observed appends may not have executed)."""
    a = _Analysis(hist)
    txns = a.txns
    n_oks = len(a.oks)
    # hot path (~5 calls per op on 100k-txn histories): bitmask edge
    # accumulation inlined (an add() call per edge costs ~25% of the
    # whole build at this scale), converted once at the end to the
    # {(i, j): {type, ...}} shape consumers read (kernels owns the
    # representation); writer_of holds txn INDICES, so no id()-keyed
    # lookups anywhere
    acc: dict[tuple, int] = {}
    acc_get = acc.get

    orders, incompatible = a.version_orders()
    writer_of = a.writer_of
    empty: dict = {}
    # ww along each key's observed version chain
    for k, chain in orders.items():
        writers = writer_of.get(k, empty)
        wget = writers.get
        for v1, v2 in zip(chain, chain[1:]):
            w1, w2 = wget(v1), wget(v2)
            if w1 and w2 and w1[0] != w2[0]:
                key = (w1[0], w2[0])
                acc[key] = acc_get(key, 0) | _WW
    # never-observed :ok appends per key (not in the longest chain):
    # ok txns are exactly indices < n_oks
    unobserved: dict[Any, list] = {}
    for k, writers in writer_of.items():
        observed = set(orders.get(k, ()))
        un = [wi for v, (wi, _f) in writers.items()
              if v not in observed and wi < n_oks]
        if un:
            unobserved[k] = un
    # wr + rw per read (over the pre-extracted flat read list)
    for i_reader, _o, m in a.ok_reads:
        k = m[1]
        vs = m[2]
        writers = writer_of.get(k, empty)
        chain = orders.get(k, ())
        if vs:
            w = writers.get(vs[-1])
            if w is not None and w[0] != i_reader:
                key = (w[0], i_reader)
                acc[key] = acc_get(key, 0) | _WR
        # first in-chain successor with a known writer (observed =>
        # committed, so info writers count too). Versions with no
        # known writer — phantom values a corrupt store fabricated —
        # are skipped over, not stopped at, so the anti-dependency
        # still lands on the next real writer. If that writer is
        # the reader itself, its own ww chain edge carries the
        # composite onward and no rw edge is needed.
        p = len(vs)
        while p < len(chain):
            w2 = writers.get(chain[p])
            if w2 is not None:
                if w2[0] != i_reader:
                    key = (i_reader, w2[0])
                    acc[key] = acc_get(key, 0) | _RW
                break
            p += 1
        for wi in unobserved.get(k, ()):
            if wi != i_reader:
                key = (i_reader, wi)
                acc[key] = acc_get(key, 0) | _RW
    edges = kernels.mask_edges_to_sets(acc)
    return txns, edges, a, incompatible


DEFAULT_ANOMALIES = ("G0", "G1a", "G1b", "G1c", "G-single", "G2-item",
                     "internal", "duplicate-elements",
                     "incompatible-order")


def check(hist, anomalies=DEFAULT_ANOMALIES, mesh=None,
          additional_graphs=()) -> dict:
    """Full list-append analysis. Returns {'valid?': ..,
    'anomaly-types': [..], 'anomalies': {type: [case...]}}, matching the
    reference checker's result shape (`tests/cycle/append.clj:28-55`).
    additional_graphs names extra precedence graphs
    ('realtime'/'process') to union into the cycle search, enabling the
    -realtime/-process anomaly variants."""
    hist = as_history(hist).index()
    txns, edges, a, incompatible = graph(hist)
    if additional_graphs:
        edges = precedence.union_edges(
            edges, precedence.additional_edges(a.hist, txns,
                                               additional_graphs))
        anomalies = precedence.expand_anomalies(anomalies,
                                                additional_graphs)
    found: dict[str, list] = {}

    if a.duplicates:
        found["duplicate-elements"] = a.duplicates
    if incompatible:
        found["incompatible-order"] = incompatible
    g1a = a.g1a_cases()
    if g1a:
        found["G1a"] = g1a
    g1b = a.g1b_cases()
    if g1b:
        found["G1b"] = g1b
    internal = internal_cases(a.hist)
    if internal:
        found["internal"] = internal

    cyc = kernels.analyze_edges(len(txns), edges, mesh=mesh)
    found.update(kernels.certificates(txns, edges, cyc))

    reported = {t: cases for t, cases in found.items() if t in anomalies}
    return {
        "valid?": not reported,
        "anomaly-types": sorted(reported),
        "anomalies": reported,
        "txn-count": len(txns),
    }
