"""Elle-class rw-register checker (reference consumes
`elle.rw-register/check` via `jepsen/src/jepsen/tests/cycle/wr.clj:14-54`,
anomaly taxonomy documented there at lines 31-45).

Txns mix ['w', k, v] and ['r', k, v] micro-ops over registers. Writes are
assumed globally unique per key (duplicates are flagged); version order is
only *partially* recoverable, from:

  * the initial state: nil precedes every written value;
  * intra-txn sequencing: a txn that observes u (by read or its own
    write) and then writes v establishes u < v.

Edges: wr from each value's writer to its external readers (exact); ww
between writers of known-ordered values; rw from a reader of u to the
writers of known successors of u (a read of nil anti-depends on every
writer of that key). rw edges built from non-immediate successions are
rw;ww* composites — sound for cycle detection and classification, since
the composite still contains exactly one anti-dependency.

Single-pass anomalies: G1a (aborted read), G1b (intermediate read — a
read of a txn's non-final write), internal (txn disagrees with its own
prior ops), duplicate writes.
"""

from __future__ import annotations

from typing import Any

from ... import txn as mop
from ...history import history as as_history, is_fail, is_info, is_ok
from . import graphs as precedence, kernels

_WW, _WR, _RW = kernels._WW, kernels._WR, kernels._RW

_INIT = object()  # the unwritten initial state (reads return None)


def op_internal_case(op: dict) -> dict | None:
    """A read must agree with the txn's own latest prior op on that key."""
    # positional micro-op access (f, k, v = m): once per mop on
    # 10k-txn histories
    known: dict[Any, Any] = {}
    for m in op.get("value") or ():
        k, v = m[1], m[2]
        if m[0] == "r":
            if k in known and known[k] != v:
                return {"op": op, "mop": list(m), "expected": known[k]}
            known[k] = v
        elif m[0] == "w":
            known[k] = v
    return None


def internal_cases(hist) -> list:
    return [c for o in hist if is_ok(o)
            for c in [op_internal_case(o)] if c is not None]


class _Analysis:
    def __init__(self, hist):
        hist = as_history(hist).index().client_ops()
        self.hist = hist
        self.oks = [o for o in hist if is_ok(o)]
        self.infos = [o for o in hist if is_info(o)
                      and isinstance(o.get("value"), (list, tuple))]
        self.fails = [o for o in hist if is_fail(o)]
        # (k, v) -> (op, final?) over ok/info writes
        self.writer_of: dict[tuple, tuple] = {}
        self.duplicates: list = []
        for o in self.oks + self.infos:
            writes: dict[Any, list] = {}
            for m in o.get("value") or ():
                # a None-valued write is unresolved (e.g. a crashed
                # read-increment whose value was never filled in): it
                # identifies no version, so it carries no information
                if m[0] == "w" and m[2] is not None:
                    writes.setdefault(m[1], []).append(m[2])
            for k, vs in writes.items():
                for i, v in enumerate(vs):
                    if (k, v) in self.writer_of:
                        self.duplicates.append(
                            {"key": k, "value": v,
                             "ops": [self.writer_of[(k, v)][0], o]})
                    self.writer_of[(k, v)] = (o, i == len(vs) - 1)
        self.failed_writes = {
            (mop.key(m), mop.value(m)): o
            for o in self.fails
            for m in (o.get("value") or ())
            if mop.is_write(m) and mop.value(m) is not None}

    def version_pairs(self):
        """Known per-key order pairs {k: set of (u, v)} with u possibly
        _INIT, from intra-txn sequencing."""
        pairs: dict[Any, set] = {}
        for o in self.oks:
            cur: dict[Any, Any] = {}
            for m in o.get("value") or ():
                k, v = m[1], m[2]
                if m[0] == "r":
                    cur[k] = _INIT if v is None else v
                elif v is not None:
                    u = cur.get(k)
                    if u is not None and u != v:
                        pairs.setdefault(k, set()).add((u, v))
                    cur[k] = v
        return pairs

    def g1a_cases(self) -> list:
        cases = []
        fw = self.failed_writes
        for o in self.oks:
            for m in o.get("value") or ():
                if m[0] == "r" and m[2] is not None:
                    w = fw.get((m[1], m[2]))
                    if w is not None:
                        cases.append({"op": o, "mop": list(m),
                                      "writer": w})
        return cases

    def g1b_cases(self) -> list:
        cases = []
        wo = self.writer_of
        for o in self.oks:
            for m in o.get("value") or ():
                if m[0] == "r" and m[2] is not None:
                    w = wo.get((m[1], m[2]))
                    if w is not None and not w[1] and id(w[0]) != id(o):
                        cases.append({"op": o, "mop": list(m),
                                      "writer": w[0]})
        return cases


def graph(hist):
    """(txns, edges, analysis) — sparse dependency graph; see module
    docstring for the edge-inference rules."""
    a = _Analysis(hist)
    txns = a.oks + a.infos
    idx = {id(o): i for i, o in enumerate(txns)}
    # bitmask edge accumulation inlined, as in list_append.graph
    acc: dict[tuple, int] = {}
    acc_get = acc.get

    pairs = a.version_pairs()
    writers_by_key: dict[Any, list] = {}
    for (k, v), w in a.writer_of.items():
        writers_by_key.setdefault(k, []).append((v, w[0]))

    # ww between known-ordered writes
    for k, ps in pairs.items():
        for u, v in ps:
            wv = a.writer_of.get((k, v))
            if wv is None:
                continue
            if u is not _INIT:
                wu = a.writer_of.get((k, u))
                if wu is not None and wu[0] is not wv[0]:
                    key = (idx[id(wu[0])], idx[id(wv[0])])
                    acc[key] = acc_get(key, 0) | _WW

    # wr + rw, one ext_reads pass per op (each read-map is consumed
    # while hot rather than precomputed into a list — keeping 10k maps
    # alive simultaneously measurably worsens best-case locality):
    # wr: writer -> external reader (exact); rw: external reader of u
    # -> writers of known successors of u, and a read of nil
    # anti-depends on every writer of that key
    succ: dict[tuple, list] = {}
    for k, ps in pairs.items():
        for u, v in ps:
            succ.setdefault((k, u), []).append(v)
    for o in a.oks:
        for k, v in mop.ext_reads(o.get("value") or ()).items():
            if v is None:
                for _, w in writers_by_key.get(k, ()):
                    if w is not o:
                        key = (idx[id(o)], idx[id(w)])
                        acc[key] = acc_get(key, 0) | _RW
                continue
            w = a.writer_of.get((k, v))
            if w is not None and w[0] is not o:
                key = (idx[id(w[0])], idx[id(o)])
                acc[key] = acc_get(key, 0) | _WR
            for v2 in succ.get((k, v), ()):
                w2 = a.writer_of.get((k, v2))
                if w2 is not None and w2[0] is not o:
                    key = (idx[id(o)], idx[id(w2[0])])
                    acc[key] = acc_get(key, 0) | _RW
    edges = kernels.mask_edges_to_sets(acc)
    return txns, edges, a


DEFAULT_ANOMALIES = ("G0", "G1a", "G1b", "G1c", "G-single", "G2-item",
                     "internal", "duplicate-writes")


def check(hist, anomalies=DEFAULT_ANOMALIES, mesh=None,
          additional_graphs=()) -> dict:
    """Full rw-register analysis; result shape mirrors the reference
    checker (`tests/cycle/wr.clj:46-54`). additional_graphs names extra
    precedence graphs ('realtime'/'process') to union into the cycle
    search, enabling the -realtime/-process anomaly variants."""
    hist = as_history(hist).index()
    txns, edges, a = graph(hist)
    if additional_graphs:
        edges = precedence.union_edges(
            edges, precedence.additional_edges(a.hist, txns,
                                               additional_graphs))
        anomalies = precedence.expand_anomalies(anomalies,
                                                additional_graphs)
    found: dict[str, list] = {}
    if a.duplicates:
        found["duplicate-writes"] = a.duplicates
    g1a = a.g1a_cases()
    if g1a:
        found["G1a"] = g1a
    g1b = a.g1b_cases()
    if g1b:
        found["G1b"] = g1b
    internal = internal_cases(a.hist)
    if internal:
        found["internal"] = internal

    cyc = kernels.analyze_edges(len(txns), edges, mesh=mesh)
    found.update(kernels.certificates(txns, edges, cyc))

    reported = {t: cases for t, cases in found.items() if t in anomalies}
    return {
        "valid?": not reported,
        "anomaly-types": sorted(reported),
        "anomalies": reported,
        "txn-count": len(txns),
    }
