"""Device kernels for transactional-cycle detection.

The reference delegates cycle search to the Elle JVM library
(`jepsen/src/jepsen/tests/cycle.clj:9-16`), which runs Tarjan's SCC on a
pointer graph. TPU-native, the pipeline is heterogeneous, shaped by where
each sub-problem's structure lives:

  1. **Sparse condensation (host, linear time).** Every cycle — of any
     edge subset — lies entirely inside one strongly-connected component
     of the full ww|wr|rw graph, unioned with whatever additional
     precedence graphs (realtime/process, graphs.py) are in play (a path
     between two same-SCC nodes can never leave the SCC). SCC labels are
     computed in O(V+E) from COO edge lists; a valid history (no
     nontrivial SCC) short-circuits with zero device work. This is the
     step that makes 100k-txn histories tractable: the old dense N x N
     closure needed ~68 GB at that scale.
  2. **Dense classification (device, MXU).** Nontrivial SCCs are small
     and need *polynomial* closure-type computations to classify the
     Adya anomaly (G0 / G1c / G-single / G2-item) — exactly matmul
     shape. SCC blocks are bucketed to power-of-two sizes, batched, and
     vmapped; the batch dimension shards across a `Mesh` so many
     independent SCCs classify in parallel over ICI.
  3. **Certificates (host).** BFS path reconstruction for the
     human-readable anomaly cycles, restricted to nontrivial SCCs.

SCCs larger than `max_dense` (pathological histories) are classified
host-side: G0/G1c exactly via subgraph SCC, G-single via a bounded
rw-edge probe; see `_classify_oversized`.
"""

from __future__ import annotations

import collections
import functools
import logging
import math
import os

import numpy as np

log = logging.getLogger(__name__)

# cap on the per-analysis G2 probe memo (see g2_verified): bounds the
# memo in long-lived checker processes chewing pathological histories
G2_CACHE_CAP = 4096

_WW, _WR, _RW = 1, 2, 4
# additional precedence graphs (graphs.py): realtime (completion
# happened-before invocation) and process (same process, next op).
# They union into the same adjacency structure as the dependency edges
# so one SCC condensation covers every cycle of every edge subset.
_PROC, _RT = 8, 16
_DEP = _WW | _WR | _RW

_BIT_NAMES = ((_WW, "ww"), (_WR, "wr"), (_RW, "rw"),
              (_PROC, "process"), (_RT, "realtime"))

# mask <-> {'ww','wr','rw',...} tables.  MASK_SETS gives the graph
# builders shared frozensets (no per-edge allocation); SET_MASK lets
# analyze_edges recover the mask by hash instead of five membership
# tests.  Frozensets hash by content, so any equal frozenset hits.
MASK_SETS = {
    m: frozenset(n for bit, n in _BIT_NAMES if m & bit)
    for m in range(32)
}
SET_MASK = {s: m for m, s in MASK_SETS.items()}


def mask_edges_to_sets(acc: dict) -> dict:
    """{(i, j): bitmask} -> {(i, j): frozenset of edge-type names}.
    The graph builders accumulate edge-type bits inline ({(i, j): mask}
    with an i != j guard, no per-edge set allocation) and convert here
    at the boundary where consumers expect {'ww', ...} sets."""
    return {k: MASK_SETS[m] for k, m in acc.items()}


def type_mask(types) -> int:
    """Edge types (frozenset/set of names, or an int mask) -> int mask."""
    if isinstance(types, int):
        return types
    if isinstance(types, frozenset):
        m = SET_MASK.get(types)
        if m is not None:
            return m
    return ((_WW if "ww" in types else 0)
            | (_WR if "wr" in types else 0)
            | (_RW if "rw" in types else 0)
            | (_PROC if "process" in types else 0)
            | (_RT if "realtime" in types else 0))


def _bucket(n: int, lo: int = 8) -> int:
    """Round up to a power of two (min 8) so recompilation is rare and
    batch members share shapes."""
    b = lo
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# SCC condensation (host, linear time)
# ---------------------------------------------------------------------------

def scc_labels(n: int, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """Strongly-connected-component label per node, from COO edges."""
    try:
        from scipy.sparse import csr_matrix
        from scipy.sparse.csgraph import connected_components

        mat = csr_matrix((np.ones(len(src), np.int8), (src, dst)),
                         shape=(n, n))
        _, labels = connected_components(mat, directed=True,
                                         connection="strong")
        return labels.astype(np.int64)
    except ImportError:  # pragma: no cover - exercised via _tarjan test
        return _tarjan_labels(n, src, dst)


def _tarjan_labels(n: int, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """Iterative Tarjan SCC — pure-Python fallback when scipy is absent."""
    adj: list[list[int]] = [[] for _ in range(n)]
    for i, j in zip(src.tolist(), dst.tolist()):
        adj[i].append(j)
    index = np.full(n, -1, np.int64)
    low = np.zeros(n, np.int64)
    on_stack = np.zeros(n, bool)
    labels = np.full(n, -1, np.int64)
    stack: list[int] = []
    counter = 0
    n_sccs = 0
    for root in range(n):
        if index[root] != -1:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            recursed = False
            for k in range(pi, len(adj[v])):
                w = adj[v][k]
                if index[w] == -1:
                    work[-1] = (v, k + 1)
                    work.append((w, 0))
                    recursed = True
                    break
                elif on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recursed:
                continue
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    labels[w] = n_sccs
                    if w == v:
                        break
                n_sccs += 1
            work.pop()
            if work:
                u, _ = work[-1]
                low[u] = min(low[u], low[v])
    return labels


# ---------------------------------------------------------------------------
# Dense per-SCC classification (device)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _flags_batch_fn(e: int, steps: int):
    """jit(vmap) kernel classifying a batch of SCC subgraphs at once:
    [B, e, e] ww/wr/rw blocks -> four [B] anomaly flags plus a [B]
    ABFT checksum residue.

    The G-single/G2 split avoids both masking and double-counting: with
    E = the reflexive ww|wr closure, H1 = E.rw.E is "reachable using
    exactly one anti-dependency", so a true diagonal of H1 is a one-rw
    cycle (G-single). For G2-item, a simple cycle with >=2 rw edges
    visits each node once, so its rw edges have pairwise-distinct source
    nodes: with P = rw.reflexive-closure(full), a G2 cycle implies
    P[i,j] & P[j,i] for two distinct rw sources i != j — a test an
    unrelated weaker cycle cannot trigger, and one lap of a G-single
    cycle cannot satisfy (its only rw source is one node).

    ABFT (GCN-ABFT, arXiv 2412.18534): every squaring step P = A@A in
    the closure carries a column checksum — ones@(A@A) must equal
    (ones@A)@A, the right side a vector-matrix product through an
    independent (O(e^2)) path. Sums are exact in int32 (entries are
    counts <= e^2 < 2^31), so the residue is 0 unless a compute unit
    or an HBM word under the closure silently corrupted — any nonzero
    residue raises `corrupt` at the host check in _classify_batches."""
    import jax
    import jax.numpy as jnp

    i32 = jnp.int32

    def _closure(a, res):
        def body(c, _):
            a, res = c
            p = a @ a
            pi = p.astype(i32)              # entries <= e: exact
            ai = a.astype(i32)
            res = res + jnp.abs(
                jnp.sum(pi, axis=0)
                - jnp.sum(ai, axis=0) @ ai).sum()
            a = jnp.minimum(a + p, 1.0)
            return (a, res), None
        (a, res), _ = jax.lax.scan(body, (a, res), None, length=steps)
        return a, res

    def one(ww, wr, rw):
        res = i32(0)
        c_ww, res = _closure(ww, res)
        c_wwr, res = _closure(jnp.minimum(ww + wr, 1.0), res)
        c_full, res = _closure(jnp.minimum(ww + wr + rw, 1.0), res)
        diag = jnp.arange(e)
        has_g0 = (c_ww[diag, diag] > 0).any()
        has_g1c = (c_wwr[diag, diag] > 0).any()
        eye = jnp.eye(e)
        ec = jnp.minimum(c_wwr + eye, 1.0)
        h1 = jnp.minimum(ec @ rw @ ec, 1.0)
        has_single = (h1[diag, diag] > 0).any()
        cr = jnp.maximum(c_full, eye)
        p = jnp.minimum(rw @ cr, 1.0)
        has_g2 = ((p * p.T) * (1.0 - eye) > 0).any()
        return has_g0, has_g1c, has_single, has_g2, res

    @jax.jit
    def batch(ww, wr, rw):
        return jax.vmap(one)(ww.astype(jnp.float32),
                             wr.astype(jnp.float32),
                             rw.astype(jnp.float32))

    return batch


def _classify_batches_host(buckets: dict) -> dict:
    """Host path of the batched classifier (same contract as
    `_classify_batches`): per-SCC dense blocks -> four flag vectors.
    Selected by `JEPSEN_TPU_ELLE_HOST=1` when the device path is
    unavailable — the axon relay can wedge mid-session and lose a
    dispatch forever (r05: the first elle device compile hung while
    every WGL kernel ran; the surviving process held the chip grant),
    so a correctness verdict must never *require* the device. Exact:
    closure by boolean repeated squaring mirrors the device kernel."""
    out: dict = {}
    for e, (ww, wr, rw) in sorted(buckets.items()):
        b = ww.shape[0]
        flags = (np.zeros(b, bool), np.zeros(b, bool),
                 np.zeros(b, bool), np.zeros(b, bool))
        steps = max(1, math.ceil(math.log2(max(e, 2))))

        def closure(a):
            a = a.copy()
            for _ in range(steps):
                a = np.minimum(a + a @ a, 1.0)
            return a

        for s in range(b):
            c_ww = closure(ww[s])
            c_wwr = closure(np.minimum(ww[s] + wr[s], 1.0))
            c_full = closure(np.minimum(ww[s] + wr[s] + rw[s], 1.0))
            eye = np.eye(e)
            ec = np.minimum(c_wwr + eye, 1.0)
            h1 = np.minimum(ec @ rw[s] @ ec, 1.0)
            cr = np.maximum(c_full, eye)
            p = np.minimum(rw[s] @ cr, 1.0)
            flags[0][s] = bool(np.diag(c_ww).any())
            flags[1][s] = bool(np.diag(c_wwr).any())
            flags[2][s] = bool(np.diag(h1).any())
            flags[3][s] = bool(((p * p.T) * (1.0 - eye) > 0).any())
        out[e] = flags
    return out


def _classify_batches(buckets: dict, mesh=None) -> dict:
    """Run the batched classifier per bucket size. buckets maps
    e -> (ww[B,e,e], wr, rw) float32 numpy. Returns
    e -> (g0[B], g1c[B], single[B], g2[B]) bool numpy — per-SCC flags,
    in the caller's slot order.

    Attestation + recovery (the WGL entries' posture, scaled to this
    path): the staged adjacency stacks carry host-vs-device bit-pattern
    digests (the 'elle' bitflip-injection site corrupts the first
    stacked block), and the kernel's per-step column checksums
    (`_flags_batch_fn`) must come back zero. A classified backend
    fault — including a `corrupt` attestation mismatch — re-stages and
    retries once; a second failure decides the bucket on the host
    mirror (`_classify_batches_host`, this path's final rung), so a
    silently corrupted classification becomes a re-derived verdict
    instead of a wrong one."""
    if os.environ.get("JEPSEN_TPU_ELLE_HOST") == "1":
        return _classify_batches_host(buckets)

    import jax
    import jax.numpy as jnp

    from ..._platform import (CorruptDeviceResult, attest_enabled,
                              classify_backend_error,
                              guarded_device_get, maybe_corrupt,
                              maybe_inject_fault)
    from .. import abft

    attest_on = attest_enabled()
    out: dict = {}
    for e, (ww, wr, rw) in sorted(buckets.items()):
        steps = max(1, math.ceil(math.log2(max(e, 2))))
        fn = _flags_batch_fn(e, steps)
        b = ww.shape[0]
        for attempt in (0, 1):
            try:
                maybe_inject_fault("elle")
                canon = [ww, wr, rw]
                if mesh is not None:
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec as P)
                    axis = mesh.axis_names[0]
                    nd = mesh.devices.size
                    pad = (-b) % nd
                    if pad:
                        # inputs arrive bucket-padded (analyze_edges);
                        # this only rounds the batch up to the mesh
                        # axis, a second bounded set
                        canon = [np.concatenate(  # noqa: JTS304
                            [a, np.zeros((pad, e, e), np.float32)])
                            for a in canon]
                # corrupt AFTER padding so the canonical (padded)
                # blocks the host digests cover are exactly what ships
                staged = [maybe_corrupt("elle", canon[0])] + canon[1:]
                if mesh is not None:
                    sh = NamedSharding(mesh, P(axis, None, None))
                    args = [jax.device_put(jnp.asarray(a), sh)
                            for a in staged]
                else:
                    args = [jnp.asarray(a) for a in staged]
                if attest_on:
                    # bit-pattern digests over the shipped stacks vs
                    # the canonical host blocks. The in-kernel column
                    # checksums below CANNOT catch input corruption (a
                    # corrupted A is self-consistent under
                    # ones@(A@A) == (ones@A)@A), so this check runs on
                    # the mesh path too — the digest jit reduces the
                    # sharded stack to one scalar
                    for xj, host in zip(args, canon):
                        abft.verify_steps(
                            "elle",
                            guarded_device_get(
                                abft.digest_device(xj),
                                site="elle attest"),
                            abft.digest_host(host))
                # one guarded fetch for the whole verdict tuple: the
                # sync watchdog covers it, and a wedged backend
                # classifies into the retry below instead of hanging
                f0, f1, fs, f2, res = guarded_device_get(
                    fn(*args), site="elle classify")
                if attest_on:
                    bad = res[:b]
                    if bad.any():
                        raise CorruptDeviceResult(
                            "elle", f"closure column-checksum residue "
                                    f"{bad.max()} != 0 on {int((bad != 0).sum())} "
                                    f"SCC block(s)")
                out[e] = tuple(x[:b] for x in (f0, f1, fs, f2))
                break
            except RuntimeError as exc:
                kind = classify_backend_error(exc)
                if kind is None:
                    raise
                log.warning(
                    "elle classify: %s fault on the %d-wide bucket "
                    "(%s); %s", kind, e, exc,
                    "deciding on the host mirror" if attempt
                    else "re-staging and retrying once")
                if attempt:
                    out[e] = _classify_batches_host(
                        {e: (ww, wr, rw)})[e]
    return out


def _edges_dict(src, dst, tmask) -> tuple[dict, list]:
    """COO arrays -> ({(i, j): {types}}, [rw edges])."""
    edges: dict[tuple, set] = {}
    rw_edges = []
    for i, j, t in zip((int(x) for x in src), (int(x) for x in dst),
                       (int(x) for x in tmask)):
        types = edges.setdefault((i, j), set())
        if t & _WW:
            types.add("ww")
        if t & _WR:
            types.add("wr")
        if t & _RW:
            types.add("rw")
            rw_edges.append((i, j))
    return edges, rw_edges


def _probe_g2(src, dst, tmask, probe_cap: int = 2000) -> bool:
    """Host check for a >=2-anti-dependency cycle in a (small) subgraph:
    for each rw edge (i, j), look for a return path j => i using another
    rw edge and never revisiting i mid-path. Exact when every rw edge is
    probed; past probe_cap, defers to the device's (over-approximate)
    G2 flag rather than silently dropping a possibly-real anomaly."""
    edges, rw_edges = _edges_dict(src, dst, tmask)
    for i, j in rw_edges[:probe_cap]:
        if _find_g2_path(edges, j, i, exclude_src=i):
            return True
    return len(rw_edges) > probe_cap


def _classify_oversized(nodes: np.ndarray, src, dst, tmask,
                        probe_cap: int = 2000) -> tuple:
    """Host classification for an SCC too large for a dense block:
    G0/G1c exactly via subgraph SCC; G-single/G2-item via bounded BFS
    probes over the SCC's rw edges (exact when every rw edge is probed;
    conservative — G2 inferred from cycle existence — beyond
    probe_cap). src/dst/tmask must already be the SCC's intra-component
    edges (any cycle, of any edge subset, stays within one full-graph
    SCC, so those are the only edges that matter)."""
    sub = list(zip((int(i) for i in src), (int(j) for j in dst),
                   (int(t) for t in tmask)))
    remap = {v: ix for ix, v in enumerate(nodes.tolist())}
    m = len(nodes)

    def has_subcycle(bits):
        s = np.array([remap[i] for i, j, t in sub if t & bits], np.int64)
        d = np.array([remap[j] for i, j, t in sub if t & bits], np.int64)
        if len(s) == 0:
            return False
        lab = scc_labels(m, s, d)
        return bool((np.bincount(lab, minlength=m) >= 2).any())

    g0 = has_subcycle(_WW)
    g1c = g0 or has_subcycle(_WW | _WR)
    # probes over rw edges: G-single = a ww/wr-only return path;
    # G2-item = a return path using a second anti-dependency
    sub_edges, rw_edges = _edges_dict(*zip(*sub)) if sub else ({}, [])
    single = g2 = False
    probed_all = len(rw_edges) <= probe_cap
    for i, j in rw_edges[:probe_cap]:
        if not single and find_path(sub_edges, j, i, {"ww", "wr"}):
            single = True
        if not g2 and _find_g2_path(sub_edges, j, i, exclude_src=i):
            g2 = True
        if single and g2:
            break
    if not probed_all and not (g1c or single or g2) \
            and has_subcycle(_WW | _WR | _RW):
        # a cycle exists on these edges (the union SCC is nontrivial,
        # but a *folded level* of it may be acyclic — hence the
        # explicit check); unexplained by the probes, it needs >= 2
        # anti-dependencies
        g2 = True
    return g0, g1c, single, g2


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

# Classification runs per *level*: the base level sees only dependency
# edges; each additional level folds its precedence edges into the ww
# matrix (a precedence edge behaves exactly like a write-write order for
# cycle purposes) and re-runs the SAME classifier — so the device kernel
# and its host mirror stay byte-identical, and level batches stack along
# the vmapped batch axis.  A variant anomaly (e.g. G-single-realtime) is
# reported only for SCCs where the level's flag holds and the previous
# level's does not — a cycle that *requires* the extra edge type.
# Realtime subsumes process (a process issues its next op only after the
# previous completed), hence the realtime level folds both.
_VARIANT_BASES = ("G0", "G1c", "G-single", "G2-item")
_LEVEL_SPECS = (("-process", _PROC), ("-realtime", _PROC | _RT))

_EMPTY = {"G0": False, "G1c": False, "G-single": False, "G2-item": False}
_EMPTY.update({f"{b}{s}": False
               for s, _m in _LEVEL_SPECS for b in _VARIANT_BASES})


def _fold_level(src, dst, tmask, extra: int):
    """Project a union-graph edge set onto one classification level:
    keep dependency bits, fold the level's precedence bits into ww, drop
    edges that carry neither."""
    t = (tmask & _DEP) | np.where(tmask & extra, _WW, 0).astype(tmask.dtype)
    keep = t != 0
    return src[keep], dst[keep], t[keep]


def analyze_edges(n: int, edges: dict, mesh=None,
                  max_dense: int = 4096) -> dict:
    """Classify cycles in a sparse dependency graph.

    edges: {(i, j): set of 'ww'/'wr'/'rw'}. Returns {'G0', 'G1c',
    'G-single', 'G2-item': bool, 'cycle-nodes': np int array of nodes in
    nontrivial SCCs, 'scc-labels': per-node labels or None,
    'oversized-sccs': int} following Adya's hierarchy (G-single = exactly
    one anti-dependency in the cycle, G2-item = at least two).
    """
    out = dict(_EMPTY)
    out["cycle-nodes"] = np.zeros(0, np.int64)
    out["scc-labels"] = None
    out["oversized-sccs"] = 0
    if n == 0 or not edges:
        return out

    # self-loops are cycles all by themselves (the checkers never emit
    # them, but dense-matrix adapters and direct callers can)
    self_nodes = []
    for (i, j), types in edges.items():
        if i == j:
            self_nodes.append(i)
            if "ww" in types:
                out["G0"] = out["G1c"] = True
            elif "wr" in types:
                out["G1c"] = True
            if "rw" in types:
                out["G-single"] = True
            if not (types & {"ww", "wr", "rw"}):
                # a pure precedence self-loop: an op before itself
                if "process" in types:
                    out["G0-process"] = True
                elif "realtime" in types:
                    out["G0-realtime"] = True
    plain = {(i, j): t for (i, j), t in edges.items() if i != j}
    if not plain:
        out["cycle-nodes"] = np.asarray(sorted(set(self_nodes)), np.int64)
        return out

    m = len(plain)
    src = np.fromiter((k[0] for k in plain), np.int64, count=m)
    dst = np.fromiter((k[1] for k in plain), np.int64, count=m)
    try:
        # fast path: graph builders emit the shared frozensets, which
        # hash straight back to their masks
        tmask = np.fromiter((SET_MASK[t] for t in plain.values()),
                            np.uint8, count=m)
    except (KeyError, TypeError):   # foreign set objects / masks
        tmask = np.fromiter((type_mask(t) for t in plain.values()),
                            np.uint8, count=m)

    labels = scc_labels(n, src, dst)
    sizes = np.bincount(labels)
    out["scc-labels"] = labels
    nontrivial = np.flatnonzero(sizes >= 2)
    node_in_nt = sizes[labels] >= 2
    cyc_nodes = set(np.flatnonzero(node_in_nt).tolist()) | set(self_nodes)
    out["cycle-nodes"] = np.asarray(sorted(cyc_nodes), np.int64)
    if nontrivial.size == 0:
        return out

    # local index of each nontrivial-SCC node within its SCC (stable
    # order by node id) — trivial nodes are never looked up
    nt_nodes = np.flatnonzero(node_in_nt)
    order = nt_nodes[np.argsort(labels[nt_nodes], kind="stable")]
    local = np.zeros(n, np.int64)
    seen_count: dict[int, int] = {}
    for v in order.tolist():
        lab = int(labels[v])
        c = seen_count.get(lab, 0)
        local[v] = c
        seen_count[lab] = c + 1

    # intra-SCC edges only
    esel = (labels[src] == labels[dst]) & node_in_nt[src]
    e_src, e_dst, e_t = src[esel], dst[esel], tmask[esel]
    e_lab = labels[e_src]

    # classification levels: base always; an additional level per
    # precedence graph present in some nontrivial SCC (gated on the
    # intra-SCC edges, not the whole graph — realtime edges connect
    # nearly every non-concurrent op pair, but only the ones inside an
    # SCC can participate in a cycle, so levels without any such edge
    # would just replicate the base level's device work)
    levels = [("", 0)]
    for suffix, extra in _LEVEL_SPECS:
        new_bits = extra & ~(_DEP | levels[-1][1])
        if bool((e_t & new_bits).any()):
            levels.append((suffix, extra))
    n_levels = len(levels)

    # per-SCC G2 probes, memoized by (label, level): the dense
    # distinct-rw-sources test over-approximates, so each flagged SCC is
    # host-verified with the stricter simple-path probe. LRU with a
    # size cap: a pathological history can flag thousands of SCCs
    # across several levels, and an uncapped memo would hold every
    # probe result for the whole call — evicting the oldest entries
    # only costs a re-probe if the same (label, level) is asked again.
    _g2_cache: "collections.OrderedDict[tuple, bool]" = \
        collections.OrderedDict()

    def g2_verified(lab: int, li: int) -> bool:
        key = (lab, li)
        got = _g2_cache.get(key)
        if got is None:
            emask = e_lab == lab
            got = _probe_g2(*_fold_level(
                e_src[emask], e_dst[emask], e_t[emask], levels[li][1]))
            _g2_cache[key] = got
            if len(_g2_cache) > G2_CACHE_CAP:
                _g2_cache.popitem(last=False)
        else:
            _g2_cache.move_to_end(key)
        return got

    def combine(per_level: list) -> None:
        """OR one SCC's per-level (g0, g1c, single, g2) flags into out.
        Base level reports directly; each later level reports only what
        the previous level could not explain — cycles that *require*
        that level's precedence edges."""
        for li, (suffix, _x) in enumerate(levels):
            f = per_level[li]
            if li:
                f = tuple(a and not b
                          for a, b in zip(f, per_level[li - 1]))
            for base, v in zip(_VARIANT_BASES, f):
                if v:
                    out[base + suffix] = True

    # group SCCs into power-of-two buckets; oversized ones go host-side
    by_bucket: dict[int, list] = {}
    for lab in nontrivial.tolist():
        size = int(sizes[lab])
        if size > max_dense:
            out["oversized-sccs"] += 1
            nodes = np.flatnonzero(labels == lab)
            emask = e_lab == lab
            combine([_classify_oversized(nodes, *_fold_level(
                e_src[emask], e_dst[emask], e_t[emask], extra))
                for _suffix, extra in levels])
        else:
            by_bucket.setdefault(_bucket(size), []).append(lab)

    buckets: dict[int, tuple] = {}
    for e, labs in by_bucket.items():
        b = len(labs)
        # bucket the batch axis like the SCC size: the classifier
        # kernel is jitted per (B, e, e) shape, so an exact B would
        # recompile the triple closure for every distinct SCC count —
        # pad with zero blocks (no edges -> no anomaly flags), sliced
        # off by the bp-strided read below
        bp = _bucket(b, lo=1)
        ww = np.zeros((bp, e, e), np.float32)
        wr = np.zeros((bp, e, e), np.float32)
        rw = np.zeros((bp, e, e), np.float32)
        aux = [np.zeros((bp, e, e), np.float32) for _ in levels[1:]]
        slot = {lab: ix for ix, lab in enumerate(labs)}
        mask = np.isin(e_lab, labs)
        for i, j, t, lab in zip(e_src[mask], e_dst[mask], e_t[mask],
                                e_lab[mask]):
            s = slot[int(lab)]
            r, c = int(local[i]), int(local[j])
            if t & _WW:
                ww[s, r, c] = 1.0
            if t & _WR:
                wr[s, r, c] = 1.0
            if t & _RW:
                rw[s, r, c] = 1.0
            for lx, (_suffix, extra) in enumerate(levels[1:]):
                if t & extra:
                    aux[lx][s, r, c] = 1.0
        # levels stack along the batch axis (same kernel, one launch):
        # level li's ww block is ww with its precedence edges folded in
        buckets[e] = (
            np.concatenate([ww] + [np.maximum(ww, a) for a in aux]),
            np.concatenate([wr] * n_levels),
            np.concatenate([rw] * n_levels))
    if buckets:
        flags = _classify_batches(buckets, mesh=mesh)
        for e, (f0, f1, fs, f2) in flags.items():
            labs = by_bucket[e]
            bp = _bucket(len(labs), lo=1)
            for ix, lab in enumerate(labs):
                per_level = []
                for li in range(n_levels):
                    o = li * bp + ix
                    per_level.append((
                        bool(f0[o]), bool(f1[o]), bool(fs[o]),
                        bool(f2[o]) and g2_verified(lab, li)))
                combine(per_level)
    return out


def analyze_graph(ww: np.ndarray, wr: np.ndarray, rw: np.ndarray,
                  mesh=None) -> dict:
    """Dense-matrix adapter over `analyze_edges` (kept for golden tests
    and small graphs)."""
    edges: dict[tuple, set] = {}
    for mat, typ in ((ww, "ww"), (wr, "wr"), (rw, "rw")):
        for i, j in zip(*np.nonzero(mat)):
            edges.setdefault((int(i), int(j)), set()).add(typ)
    return analyze_edges(len(ww), edges, mesh=mesh)


@functools.lru_cache(maxsize=32)
def _closure_fn(n: int, steps: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def closure(a):
        a = a.astype(jnp.float32)

        def body(a, _):
            a = jnp.minimum(a + a @ a, 1.0)
            return a, None

        a, _ = jax.lax.scan(body, a, None, length=steps)
        return a > 0

    return closure


def transitive_closure(adj: np.ndarray, mesh=None) -> np.ndarray:
    """Closure of a boolean adjacency matrix on device by repeated
    squaring (log2(n) MXU matmuls). With a mesh, the matrix is
    row-sharded and XLA partitions the matmuls over ICI."""
    import jax
    import jax.numpy as jnp

    n = len(adj)
    if n == 0:
        return np.zeros((0, 0), bool)
    e = _bucket(n, lo=128)
    padded = np.zeros((e, e), np.float32)
    padded[:n, :n] = adj
    steps = max(1, math.ceil(math.log2(max(n, 2))))
    fn = _closure_fn(e, steps)
    x = jnp.asarray(padded)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = mesh.axis_names[0]
        x = jax.device_put(x, NamedSharding(mesh, P(axis, None)))
    from ..._platform import guarded_device_get
    return guarded_device_get(fn(x), site="elle closure")[:n, :n]


# ---------------------------------------------------------------------------
# Host-side certificates
# ---------------------------------------------------------------------------

def find_cycle(edges: dict, start: int, allowed: set) -> list | None:
    """Host-side shortest cycle through `start` using only edge types in
    `allowed` — the human-readable certificate once the device has said a
    cycle exists. edges: {(i, j): set of edge types}."""
    from collections import deque

    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        if types & allowed:
            adj.setdefault(i, []).append(j)
    # BFS from start back to start
    q = deque([(start, [start])])
    seen = {start}
    while q:
        node, path = q.popleft()
        for nxt in adj.get(node, ()):
            if nxt == start:
                return path + [start]
            if nxt not in seen:
                seen.add(nxt)
                q.append((nxt, path + [nxt]))
    return None


def find_path(edges: dict, src: int, dst: int, allowed: set) -> list | None:
    """Shortest src -> dst path (list of nodes incl. both ends) using only
    edge types in `allowed`; [src] if src == dst."""
    from collections import deque

    if src == dst:
        return [src]
    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        if types & allowed:
            adj.setdefault(i, []).append(j)
    q = deque([(src, [src])])
    seen = {src}
    while q:
        node, path = q.popleft()
        for nxt in adj.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                q.append((nxt, path + [nxt]))
    return None


def _find_g2_path(edges: dict, src: int, dst: int,
                  exclude_src: int | None = None,
                  step_budget: int = 200_000,
                  allowed: set | None = None) -> list | None:
    """A *simple* src -> dst path over all edges that traverses at
    least one rw edge — closing a G2 cycle with the rw edge
    (exclude_src -> src), whose own rw must not be double-counted
    (rw edges out of exclude_src don't set the flag).

    Simple-path search is what makes the answer exact: a walk that
    revisits a node stitches two one-rw cycles into a figure-eight,
    which is not a simple cycle and must not count as G2 (two G-single
    cycles sharing a node are still G-single). DFS with per-path
    visited sets is exponential in the worst case, so a step budget
    guards it; on exhaustion we fall back to the polynomial
    state-BFS over (node, rw-used?) — an over-approximation that can
    mislabel a figure-eight as G2, conservative toward reporting the
    (definitely present) cyclic anomaly.

    `allowed` restricts the traversable edge types (None = all); the
    certificate layer passes it so a base-level G2 search never walks
    the precedence (process/realtime) edges of a union graph."""
    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        if allowed is not None and not (types & allowed):
            continue
        counts = "rw" in types and i != exclude_src
        adj.setdefault(i, []).append((j, counts))

    stack: list = [(src, False, (src,))]
    steps = 0
    while stack:
        steps += 1
        if steps > step_budget:
            return _g2_walk_fallback(adj, src, dst)
        node, used, path = stack.pop()
        for nxt, is_rw in adj.get(node, ()):
            u = used or is_rw
            if nxt == dst:
                if u:
                    return list(path) + [nxt]
                continue  # dst is an endpoint, never an intermediate
            if nxt == exclude_src or nxt in path:
                continue
            stack.append((nxt, u, path + (nxt,)))
    return None


def _g2_walk_fallback(adj: dict, src: int, dst: int) -> list | None:
    """Polynomial over-approximation used past the simple-path budget:
    shortest walk with >= 1 counted rw, nodes reusable."""
    from collections import deque

    q = deque([(src, False, [src])])
    seen = {(src, False)}
    while q:
        node, used, path = q.popleft()
        for nxt, is_rw in adj.get(node, ()):
            u = used or is_rw
            if nxt == dst:
                if u:
                    return path + [nxt]
                continue
            if (nxt, u) not in seen:
                seen.add((nxt, u))
                q.append((nxt, u, path + [nxt]))
    return None


def _find_path_requiring(edges: dict, src: int, dst: int,
                         allowed: set, required: str) -> list | None:
    """Shortest src -> dst walk over `allowed`-typed edges that uses at
    least one edge of type `required` — state-BFS over (node, used?).
    Certificate-quality: a node may appear twice (once per state)."""
    from collections import deque

    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        if types & allowed:
            adj.setdefault(i, []).append((j, required in types))
    q = deque([(src, False, [src])])
    seen = {(src, False)}
    while q:
        node, used, path = q.popleft()
        for nxt, is_req in adj.get(node, ()):
            u = used or is_req
            if nxt == dst:
                if u:
                    return path + [nxt]
                continue
            if (nxt, u) not in seen:
                seen.add((nxt, u))
                q.append((nxt, u, path + [nxt]))
    return None


# variant certificate searches: which precedence types the cycle may
# traverse, and which one its existence proves it needs
_VARIANT_CERT = (("-process", {"process"}, "process"),
                 ("-realtime", {"process", "realtime"}, "realtime"))


def certificates(txns: list, edges: dict, cyc: dict,
                 brief=None) -> dict:
    """Host-side certificates for whichever cycle anomalies the device
    reported. Each certificate is a node cycle (first == last) whose edge
    types actually exhibit the claimed anomaly: G0 uses only ww, G1c only
    ww/wr, G-single exactly one rw, G2-item at least two rw; the
    -process/-realtime variants additionally traverse (and, where the
    search can enforce it, require) a precedence edge of that type.

    Candidate start nodes / typed edges are restricted to nontrivial
    SCCs ('cycle-nodes' / 'scc-labels' from analyze_edges), since every
    cycle lives inside one."""
    if brief is None:
        brief = _brief_op
    out: dict = {}
    on_cycle = cyc.get("cycle-nodes")
    if on_cycle is None:
        on_cycle = np.flatnonzero(np.diag(cyc["closure"]))
    labels = cyc.get("scc-labels")
    cyc_set = set(int(i) for i in on_cycle)

    def typed_edges(t):
        return [(i, j) for (i, j), types in edges.items()
                if t in types and i in cyc_set and j in cyc_set
                and (labels is None or labels[i] == labels[j])]

    rw_edges = typed_edges("rw")

    def emit(typ, cert):
        out[typ] = [{"cycle": [brief(txns[i]) for i in cert]
                     if cert else None}]

    for typ, allowed in (("G0", {"ww"}), ("G1c", {"ww", "wr"})):
        if cyc[typ]:
            cert = None
            for i in on_cycle:
                cert = find_cycle(edges, int(i), allowed)
                if cert:
                    break
            emit(typ, cert)
    if cyc["G-single"]:
        cert = None
        for i, j in rw_edges:
            back = find_path(edges, j, i, {"ww", "wr"})
            if back is not None:
                cert = [i] + back  # i -rw-> j =ww/wr=> i
                break
        emit("G-single", cert)
    if cyc["G2-item"]:
        cert = None
        for i, j in rw_edges:
            back = _find_g2_path(edges, j, i, exclude_src=i,
                                 allowed={"ww", "wr", "rw"})
            if back is not None:
                cert = [i] + back
                break
        emit("G2-item", cert)

    for suffix, extra, req in _VARIANT_CERT:
        req_edges = None  # computed lazily, only when a variant fired
        for typ, allowed in (("G0", {"ww"}), ("G1c", {"ww", "wr"})):
            if not cyc.get(typ + suffix):
                continue
            if req_edges is None:
                req_edges = typed_edges(req)
            cert = None
            for i, j in req_edges:
                back = find_path(edges, j, i, allowed | extra)
                if back is not None:
                    cert = [i] + back  # i -req-> j =allowed=> i
                    break
            emit(typ + suffix, cert)
        if cyc.get("G-single" + suffix):
            cert = None
            for i, j in rw_edges:
                if req in edges.get((i, j), ()):
                    # the anti-dependency edge itself carries the
                    # precedence type; any ww/wr return path closes it
                    back = find_path(edges, j, i, {"ww", "wr"} | extra)
                else:
                    back = _find_path_requiring(
                        edges, j, i, {"ww", "wr"} | extra, req)
                if back is not None:
                    cert = [i] + back
                    break
            emit("G-single" + suffix, cert)
        if cyc.get("G2-item" + suffix):
            cert = fallback = None
            for i, j in rw_edges:
                back = _find_g2_path(
                    edges, j, i, exclude_src=i,
                    allowed={"ww", "wr", "rw"} | extra)
                if back is None:
                    continue
                nodes = [i] + back
                if fallback is None:
                    fallback = nodes
                if any(req in edges.get((u, v), ())
                       for u, v in zip(nodes, nodes[1:])):
                    cert = nodes
                    break
            emit("G2-item" + suffix, cert or fallback)
    return out


def _brief_op(op: dict) -> dict:
    return {"index": op.get("index"), "process": op.get("process"),
            "value": op.get("value")}
