"""Device kernels for transactional-cycle detection.

The reference delegates cycle search to the Elle JVM library
(`jepsen/src/jepsen/tests/cycle.clj:9-16`), which runs Tarjan's SCC on a
pointer graph. TPU-native, the dependency graph is a dense boolean
adjacency matrix and cycle questions become linear algebra on the MXU:

  * transitive closure by repeated squaring: log2(n) boolean matmuls
    (each a float32 matmul thresholded at >0 — exactly the large, batched
    matmul shape XLA tiles onto the systolic array);
  * "is there a cycle?" = any true diagonal of the closure;
  * "is there a G-single?" = any rw edge (i,j) with closure(ww|wr)[j,i];
  * SCC membership (for host-side explanation) = closure & closure^T.

For histories beyond one chip, `closure` runs under a row-sharded
`NamedSharding`: XLA partitions the matmul and inserts the all-gathers
over ICI itself (scaling-book recipe: annotate, don't hand-schedule).
"""

from __future__ import annotations

import functools
import math

import numpy as np


def _bucket(n: int, lo: int = 128) -> int:
    """Round up to a power-of-two multiple of 128 so the MXU tiles cleanly
    and recompilation is rare."""
    b = lo
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=32)
def _closure_fn(n: int, steps: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def closure(a):
        a = a.astype(jnp.float32)

        def body(a, _):
            a = jnp.minimum(a + a @ a, 1.0)
            return a, None

        a, _ = jax.lax.scan(body, a, None, length=steps)
        return a > 0

    return closure


def transitive_closure(adj: np.ndarray, mesh=None) -> np.ndarray:
    """Closure of a boolean adjacency matrix on device. With a mesh, the
    matrix is row-sharded across it and XLA partitions the matmuls."""
    import jax
    import jax.numpy as jnp

    n = len(adj)
    if n == 0:
        return np.zeros((0, 0), bool)
    e = _bucket(n)
    padded = np.zeros((e, e), np.float32)
    padded[:n, :n] = adj
    steps = max(1, math.ceil(math.log2(max(n, 2))))
    fn = _closure_fn(e, steps)
    x = jnp.asarray(padded)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = mesh.axis_names[0]
        x = jax.device_put(x, NamedSharding(mesh, P(axis, None)))
    return np.asarray(fn(x))[:n, :n]


@functools.lru_cache(maxsize=32)
def _analyze_fn(n: int, steps: int):
    """One fused kernel answering every cycle question at once:
    (has_g0, has_g1c, has_single, has_g2, closure_full).

    The G-single/G2 split avoids both masking and double-counting: with
    E = the reflexive ww|wr closure, H1 = E·rw·E is "reachable using
    exactly one anti-dependency", so a true diagonal of H1 is a one-rw
    cycle (G-single). For G2-item, a simple cycle with >=2 rw edges
    visits each node once, so its rw edges have pairwise-distinct source
    nodes: with P = rw·reflexive-closure(full), a G2 cycle implies
    P[i,j] & P[j,i] for two distinct rw sources i != j — a test an
    unrelated weaker cycle cannot trigger, and one lap of a G-single
    cycle cannot satisfy (its only rw source is one node)."""
    import jax
    import jax.numpy as jnp

    def _closure(a):
        def body(a, _):
            a = jnp.minimum(a + a @ a, 1.0)
            return a, None
        a, _ = jax.lax.scan(body, a, None, length=steps)
        return a

    @jax.jit
    def analyze(ww, wr, rw):
        ww = ww.astype(jnp.float32)
        wr = wr.astype(jnp.float32)
        rw = rw.astype(jnp.float32)
        c_ww = _closure(ww)
        c_wwr = _closure(jnp.minimum(ww + wr, 1.0))
        full = jnp.minimum(ww + wr + rw, 1.0)
        c_full = _closure(full)
        diag = jnp.arange(ww.shape[0])
        has_g0 = (c_ww[diag, diag] > 0).any()
        has_g1c = (c_wwr[diag, diag] > 0).any()
        eye = jnp.eye(ww.shape[0])
        e = jnp.minimum(c_wwr + eye, 1.0)
        h1 = jnp.minimum(e @ rw @ e, 1.0)   # exactly one rw segment
        has_single = (h1[diag, diag] > 0).any()
        cr = jnp.maximum(c_full, eye)
        p = jnp.minimum(rw @ cr, 1.0)       # rw hop, then any path
        has_g2 = ((p * p.T) * (1.0 - eye) > 0).any()
        return has_g0, has_g1c, has_single, has_g2, c_full > 0

    return analyze


def analyze_graph(ww: np.ndarray, wr: np.ndarray, rw: np.ndarray,
                  mesh=None) -> dict:
    """Classify cycles in the dependency graph on device.

    Returns {'G0': bool, 'G1c': bool, 'G-single': bool, 'G2-item': bool,
    'closure': np.ndarray} following Adya's hierarchy: G0 ⊆ G1c ⊆ ...;
    G-single = exactly one anti-dependency edge in the cycle; G2-item =
    a cycle requiring ≥2 rw edges (any full-graph cycle not already
    explained by G1c or G-single).
    """
    import jax
    import jax.numpy as jnp

    n = len(ww)
    if n == 0:
        return {"G0": False, "G1c": False, "G-single": False,
                "G2-item": False, "closure": np.zeros((0, 0), bool)}
    e = _bucket(n)

    def pad(a):
        p = np.zeros((e, e), np.float32)
        p[:n, :n] = a
        return jnp.asarray(p)

    steps = max(1, math.ceil(math.log2(max(n, 2))))
    fn = _analyze_fn(e, steps)
    args = [pad(ww), pad(wr), pad(rw)]
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = mesh.axis_names[0]
        sh = NamedSharding(mesh, P(axis, None))
        args = [jax.device_put(a, sh) for a in args]
    g0, g1c, single, g2, closure = fn(*args)
    return {
        "G0": bool(g0),
        "G1c": bool(g1c),
        "G-single": bool(single),
        "G2-item": bool(g2),
        "closure": np.asarray(closure)[:n, :n],
    }


def find_cycle(edges: dict, start: int, allowed: set) -> list | None:
    """Host-side shortest cycle through `start` using only edge types in
    `allowed` — the human-readable certificate once the device has said a
    cycle exists. edges: {(i, j): set of edge types}."""
    from collections import deque

    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        if types & allowed:
            adj.setdefault(i, []).append(j)
    # BFS from start back to start
    q = deque([(start, [start])])
    seen = {start}
    while q:
        node, path = q.popleft()
        for nxt in adj.get(node, ()):
            if nxt == start:
                return path + [start]
            if nxt not in seen:
                seen.add(nxt)
                q.append((nxt, path + [nxt]))
    return None


def find_path(edges: dict, src: int, dst: int, allowed: set) -> list | None:
    """Shortest src -> dst path (list of nodes incl. both ends) using only
    edge types in `allowed`; [src] if src == dst."""
    from collections import deque

    if src == dst:
        return [src]
    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        if types & allowed:
            adj.setdefault(i, []).append(j)
    q = deque([(src, [src])])
    seen = {src}
    while q:
        node, path = q.popleft()
        for nxt in adj.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                q.append((nxt, path + [nxt]))
    return None


def _find_g2_path(edges: dict, src: int, dst: int) -> list | None:
    """Shortest src -> dst path over all edges that traverses at least one
    rw edge — state-augmented BFS (node, rw-used?)."""
    from collections import deque

    adj: dict[int, list] = {}
    for (i, j), types in edges.items():
        adj.setdefault(i, []).append((j, "rw" in types))
    q = deque([(src, False, [src])])
    seen = {(src, False)}
    while q:
        node, used, path = q.popleft()
        for nxt, is_rw in adj.get(node, ()):
            u = used or is_rw
            if nxt == dst and u:
                return path + [nxt]
            if (nxt, u) not in seen:
                seen.add((nxt, u))
                q.append((nxt, u, path + [nxt]))
    return None


def certificates(txns: list, edges: dict, cyc: dict,
                 brief=None) -> dict:
    """Host-side certificates for whichever cycle anomalies the device
    reported. Each certificate is a node cycle (first == last) whose edge
    types actually exhibit the claimed anomaly: G0 uses only ww, G1c only
    ww/wr, G-single exactly one rw, G2-item at least two rw."""
    if brief is None:
        brief = _brief_op
    out: dict = {}
    closure = cyc["closure"]
    on_cycle = np.flatnonzero(np.diag(closure))
    rw_edges = [(i, j) for (i, j), types in edges.items()
                if "rw" in types]

    def emit(typ, cert):
        out[typ] = [{"cycle": [brief(txns[i]) for i in cert]
                     if cert else None}]

    for typ, allowed in (("G0", {"ww"}), ("G1c", {"ww", "wr"})):
        if cyc[typ]:
            cert = None
            for i in on_cycle:
                cert = find_cycle(edges, int(i), allowed)
                if cert:
                    break
            emit(typ, cert)
    if cyc["G-single"]:
        cert = None
        for i, j in rw_edges:
            back = find_path(edges, j, i, {"ww", "wr"})
            if back is not None:
                cert = [i] + back  # i -rw-> j =ww/wr=> i
                break
        emit("G-single", cert)
    if cyc["G2-item"]:
        cert = None
        for i, j in rw_edges:
            back = _find_g2_path(edges, j, i)
            if back is not None:
                cert = [i] + back
                break
        emit("G2-item", cert)
    return out


def _brief_op(op: dict) -> dict:
    return {"index": op.get("index"), "process": op.get("process"),
            "value": op.get("value")}
