"""Dependency-free SVG plotting, standing in for gnuplot.

The reference shells out to an external native gnuplot binary for every
performance/clock graph (`jepsen/src/jepsen/checker/perf.clj:417-482`);
this environment has neither gnuplot nor matplotlib, so we render the
same plot model — series with point/line styles, log y scales, shaded
nemesis regions, vertical event lines, an outside legend — directly to
SVG, which the store's web browser serves natively.

The plot maps mirror the reference's gnuplot option maps: a Plot has
series/xrange/yrange/logscale, `broaden_range` mirrors
`perf.clj:334-357`, and `with_range` fills ranges from data the same
way (`perf.clj:370-394`).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence
from xml.sax.saxutils import escape

# canvas geometry (reference: `set term png size 900,400`)
WIDTH = 900
HEIGHT = 400
# past this many points a series renders translucent, so overplotted
# regions read as density
DENSE_POINTS = 1500
DENSE_ALPHA = 0.35
MARGIN_L = 72
MARGIN_R = 168   # legend lives here ("set key outside top right")
MARGIN_T = 34
MARGIN_B = 48

POINT_SHAPES = ("circle", "square", "triangle", "diamond", "cross", "plus")


class NoPoints(Exception):
    """Raised when a plot has no data at all (reference ::no-points)."""


# qualitative series palette (Tol bright), cycled by per-process plots
PALETTE = ("#4477aa", "#ee6677", "#228833", "#ccbb44",
           "#66ccee", "#aa3377")


def merged_windows(s: int, points: list) -> list:
    """[lower, upper] windows of s elements around each point, with
    overlapping windows merged (the reference's merged-windows,
    `sequential.clj:139-158` / `monotonic.clj:242-263`; touching
    windows stay separate, as there)."""
    if not points:
        return []
    points = sorted(points)
    windows = []
    lower, upper = points[0] - s, points[0] + s
    for p in points[1:]:
        if upper <= p - s:
            windows.append([lower, upper])
            lower = p - s
        upper = p + s
    windows.append([lower, upper])
    return windows


def regression_spots(pairs: list, global_too: bool = False) -> list:
    """Indices where a value regresses, given (process, value) pairs in
    plot order: per-process decreases, plus — when global_too —
    decreases between consecutive pairs regardless of process (the two
    anomaly shapes the sequential/timestamp-value checkers flag)."""
    last: dict = {}
    prev = None
    spots = []
    for i, (p, v) in enumerate(pairs):
        pv = last.get(p)
        if (pv is not None and v < pv) or \
                (global_too and prev is not None and v < prev):
            spots.append(i)
        last[p] = v
        prev = v
    return spots


def process_series(by_process: dict) -> list:
    """One linespoints Series per process, palette-cycled — the shared
    shape of the per-process value plots (dgraph sequential, faunadb
    timestamp-value)."""
    return [Series(title=str(p), data=pts, mode="linespoints",
                   color=PALETTE[i % len(PALETTE)])
            for i, (p, pts) in enumerate(sorted(by_process.items()))]


@dataclass
class Series:
    title: Optional[str]
    data: Sequence  # [(x, y), ...]
    color: str = "#4477aa"
    mode: str = "points"  # points | lines | linespoints | steps
    point_type: int = 0   # index into POINT_SHAPES
    line_width: float = 1.0


@dataclass
class Region:
    """A shaded vertical band: x in [x0, x1] (x1 None = plot edge),
    y given as graph fractions (0 bottom, 1 top)."""
    x0: float
    x1: Optional[float]
    y0_frac: float = 0.0
    y1_frac: float = 1.0
    color: str = "#cccccc"
    alpha: float = 0.6


@dataclass
class VLine:
    x: float
    color: str = "#cccccc"
    width: float = 1.0


@dataclass
class Plot:
    title: str = ""
    xlabel: str = "Time (s)"
    ylabel: str = ""
    series: list = field(default_factory=list)
    regions: list = field(default_factory=list)
    vlines: list = field(default_factory=list)
    logscale_y: bool = False
    xrange: Optional[tuple] = None
    yrange: Optional[tuple] = None
    draw_fewer_on_top: bool = False
    width: int = WIDTH
    height: int = HEIGHT


def broaden_range(rng: tuple) -> tuple:
    """Expand [lo, hi] slightly to land on integral boundaries
    (`perf.clj:334-357`)."""
    a, b = rng
    if a == b:
        return (a - 1, a + 1)
    size = abs(float(b) - float(a))
    grid = size / 10
    scale = 10 ** round(math.log10(grid))
    a2 = a - (a % scale)
    m = b % scale
    b2 = b if (m / scale) < 0.001 else scale + (b - m)
    return (min(a, a2), max(b, b2))


def has_data(plot: Plot) -> bool:
    return any(len(s.data) for s in plot.series)


def without_empty_series(plot: Plot) -> Plot:
    plot.series = [s for s in plot.series if len(s.data)]
    return plot


def with_range(plot: Plot) -> Plot:
    """Fill missing x/y ranges from the series data
    (`perf.clj:370-394`)."""
    data = [p for s in plot.series for p in s.data]
    if not data:
        raise NoPoints()
    xs = [p[0] for p in data]
    ys = [p[1] for p in data]
    if plot.logscale_y:
        # nonpositive values can't be drawn on a log scale; gnuplot
        # drops them, and including them in the range would stretch the
        # axis across a dozen useless decades
        ys = [y for y in ys if y > 0]
        if not ys:
            raise NoPoints()
    if plot.xrange is None:
        plot.xrange = broaden_range((min(xs), max(xs)))
    if plot.yrange is None:
        lo, hi = min(ys), max(ys)
        # log plots aren't broadened — that would push the floor to <= 0
        plot.yrange = (lo, hi) if plot.logscale_y \
            else broaden_range((lo, hi))
    return plot


def _nice_ticks(lo: float, hi: float, n: int = 6) -> list[float]:
    if hi <= lo:
        return [lo]
    raw = (hi - lo) / n
    mag = 10 ** math.floor(math.log10(raw))
    for mult in (1, 2, 2.5, 5, 10):
        if raw <= mult * mag:
            step = mult * mag
            break
    first = math.ceil(lo / step) * step
    ticks, t = [], first
    while t <= hi + step * 1e-9:
        ticks.append(round(t, 10))
        t += step
    return ticks


def _log_ticks(lo: float, hi: float) -> list[float]:
    lo = max(lo, 1e-12)
    ticks = []
    e = math.floor(math.log10(lo))
    while 10 ** e <= hi * (1 + 1e-9):
        if 10 ** e >= lo * (1 - 1e-9):
            ticks.append(10 ** e)
        e += 1
    return ticks or [lo, hi]


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    a = abs(v)
    if a >= 1e5 or a < 1e-3:
        return f"{v:.0e}"
    if a >= 100 or float(v).is_integer():
        return f"{v:.0f}"
    if a >= 1:
        return f"{v:g}"
    return f"{v:.3g}"


def _marker(shape: str, x: float, y: float, r: float, color: str) -> str:
    if shape == "circle":
        return (f'<circle cx="{x:.1f}" cy="{y:.1f}" r="{r}" '
                f'fill="{color}"/>')
    if shape == "square":
        return (f'<rect x="{x - r:.1f}" y="{y - r:.1f}" width="{2 * r}" '
                f'height="{2 * r}" fill="{color}"/>')
    if shape == "triangle":
        pts = f"{x:.1f},{y - r:.1f} {x - r:.1f},{y + r:.1f} " \
              f"{x + r:.1f},{y + r:.1f}"
        return f'<polygon points="{pts}" fill="{color}"/>'
    if shape == "diamond":
        pts = f"{x:.1f},{y - r:.1f} {x + r:.1f},{y:.1f} " \
              f"{x:.1f},{y + r:.1f} {x - r:.1f},{y:.1f}"
        return f'<polygon points="{pts}" fill="{color}"/>'
    if shape == "cross":
        return (f'<path d="M{x - r:.1f} {y - r:.1f}L{x + r:.1f} {y + r:.1f}'
                f'M{x - r:.1f} {y + r:.1f}L{x + r:.1f} {y - r:.1f}" '
                f'stroke="{color}" stroke-width="1.2" fill="none"/>')
    return (f'<path d="M{x - r:.1f} {y:.1f}L{x + r:.1f} {y:.1f}'
            f'M{x:.1f} {y - r:.1f}L{x:.1f} {y + r:.1f}" '
            f'stroke="{color}" stroke-width="1.2" fill="none"/>')


def render(plot: Plot) -> str:
    """Render a Plot to an SVG document string."""
    plot = with_range(plot)
    x0p, x1p = MARGIN_L, plot.width - MARGIN_R
    y0p, y1p = plot.height - MARGIN_B, MARGIN_T
    xmin, xmax = plot.xrange
    ymin, ymax = plot.yrange
    if xmax == xmin:
        xmax = xmin + 1
    if plot.logscale_y:
        ymin = max(ymin, 1e-12)
        if ymax <= ymin:
            ymax = ymin * 10
        lymin, lymax = math.log10(ymin), math.log10(ymax)
        if lymax == lymin:
            lymax += 1

        def ty(y):
            y = max(y, 1e-12)
            return y0p + (math.log10(y) - lymin) / (lymax - lymin) \
                * (y1p - y0p)
        yticks = _log_ticks(ymin, ymax)
    else:
        if ymax == ymin:
            ymax = ymin + 1

        def ty(y):
            return y0p + (y - ymin) / (ymax - ymin) * (y1p - y0p)
        yticks = _nice_ticks(ymin, ymax)

    def tx(x):
        return x0p + (x - xmin) / (xmax - xmin) * (x1p - x0p)

    out = [f'<svg xmlns="http://www.w3.org/2000/svg" '
           f'width="{plot.width}" height="{plot.height}" '
           f'viewBox="0 0 {plot.width} {plot.height}" '
           f'font-family="sans-serif" font-size="11">',
           f'<rect width="{plot.width}" height="{plot.height}" '
           f'fill="white"/>']

    # shaded regions + vlines go under the data, clipped to the frame
    out.append(f'<clipPath id="frame"><rect x="{x0p}" y="{y1p}" '
               f'width="{x1p - x0p}" height="{y0p - y1p}"/></clipPath>')
    out.append('<g clip-path="url(#frame)">')
    for rg in plot.regions:
        rx0 = tx(max(rg.x0, xmin))
        rx1 = tx(min(rg.x1, xmax)) if rg.x1 is not None else x1p
        ry1 = y0p + rg.y1_frac * (y1p - y0p)
        ry0 = y0p + rg.y0_frac * (y1p - y0p)
        out.append(f'<rect x="{rx0:.1f}" y="{ry1:.1f}" '
                   f'width="{max(rx1 - rx0, 0.5):.1f}" '
                   f'height="{abs(ry0 - ry1):.1f}" fill="{rg.color}" '
                   f'opacity="{rg.alpha}"/>')
    for vl in plot.vlines:
        if xmin <= vl.x <= xmax:
            vx = tx(vl.x)
            out.append(f'<line x1="{vx:.1f}" y1="{y0p}" x2="{vx:.1f}" '
                       f'y2="{y1p}" stroke="{vl.color}" '
                       f'stroke-width="{vl.width}"/>')
    out.append('</g>')

    # grid + axes + ticks
    for t in _nice_ticks(xmin, xmax):
        px = tx(t)
        out.append(f'<line x1="{px:.1f}" y1="{y0p}" x2="{px:.1f}" '
                   f'y2="{y1p}" stroke="#eeeeee"/>')
        out.append(f'<text x="{px:.1f}" y="{y0p + 16}" '
                   f'text-anchor="middle">{_fmt(t)}</text>')
    for t in yticks:
        py = ty(t)
        out.append(f'<line x1="{x0p}" y1="{py:.1f}" x2="{x1p}" '
                   f'y2="{py:.1f}" stroke="#eeeeee"/>')
        out.append(f'<text x="{x0p - 6}" y="{py + 4:.1f}" '
                   f'text-anchor="end">{_fmt(t)}</text>')
    out.append(f'<rect x="{x0p}" y="{y1p}" width="{x1p - x0p}" '
               f'height="{y0p - y1p}" fill="none" stroke="#333333"/>')

    # axis labels + title
    out.append(f'<text x="{(x0p + x1p) / 2:.0f}" y="{plot.height - 10}" '
               f'text-anchor="middle">{escape(plot.xlabel)}</text>')
    if plot.ylabel:
        out.append(f'<text x="16" y="{(y0p + y1p) / 2:.0f}" '
                   f'text-anchor="middle" transform="rotate(-90 16 '
                   f'{(y0p + y1p) / 2:.0f})">{escape(plot.ylabel)}</text>')
    if plot.title:
        out.append(f'<text x="{(x0p + x1p) / 2:.0f}" y="20" '
                   f'text-anchor="middle" font-size="14">'
                   f'{escape(plot.title)}</text>')

    # series, clipped to the frame; optionally densest-first so sparse
    # series stay visible (`perf.clj:441-457` draw-fewer-on-top)
    series = list(plot.series)
    if plot.draw_fewer_on_top:
        series = sorted(series, key=lambda s: -len(s.data))
    out.append('<g clip-path="url(#frame)">')
    for s in series:
        pts = [(tx(x), ty(y)) for x, y in s.data
               if y is not None and not (plot.logscale_y and y <= 0)]
        if not pts:
            continue
        shape = POINT_SHAPES[s.point_type % len(POINT_SHAPES)]
        if s.mode in ("lines", "linespoints", "steps"):
            d = [f"M{pts[0][0]:.1f} {pts[0][1]:.1f}"]
            for (px0, py0), (px1, py1) in zip(pts, pts[1:]):
                if s.mode == "steps":
                    d.append(f"L{px1:.1f} {py0:.1f}")
                d.append(f"L{px1:.1f} {py1:.1f}")
            out.append(f'<path d="{"".join(d)}" stroke="{s.color}" '
                       f'stroke-width="{s.line_width}" fill="none"/>')
        if s.mode in ("points", "linespoints"):
            r = 2.4 if s.mode == "points" else 2.8
            if len(pts) > DENSE_POINTS:
                # dense clouds: PER-MARKER translucency, so overlapping
                # points darken each other and overplotted regions read
                # as density (the reference wants this, its plan.md
                # "make points somewhat transparent"). Group-level
                # opacity would composite the layer as one unit and
                # flatten the overlaps.
                out.append(f'<g fill-opacity="{DENSE_ALPHA}" '
                           f'stroke-opacity="{DENSE_ALPHA}">')
                out.extend(_marker(shape, px, py, r, s.color)
                           for px, py in pts)
                out.append('</g>')
            else:
                out.extend(_marker(shape, px, py, r, s.color)
                           for px, py in pts)
    out.append('</g>')

    # legend, outside top right
    lx, ly = x1p + 10, y1p + 4
    entries = [s for s in plot.series if s.title]
    for i, s in enumerate(entries):
        py = ly + i * 16
        shape = POINT_SHAPES[s.point_type % len(POINT_SHAPES)]
        if s.mode in ("lines", "steps"):
            out.append(f'<line x1="{lx}" y1="{py + 4}" x2="{lx + 14}" '
                       f'y2="{py + 4}" stroke="{s.color}" '
                       f'stroke-width="{max(s.line_width, 2)}"/>')
        else:
            out.append(_marker(shape, lx + 7, py + 4, 3, s.color))
        out.append(f'<text x="{lx + 20}" y="{py + 8}">'
                   f'{escape(str(s.title))}</text>')
    out.append('</svg>')
    return "\n".join(out)


def write(plot: Plot, path: str) -> str:
    """Render a plot to an SVG file; returns the path, or None when the
    plot has no data (the reference's :no-points outcome)."""
    try:
        svg = render(plot)
    except NoPoints:
        return None
    with open(path, "w") as f:
        f.write(svg)
    return path
