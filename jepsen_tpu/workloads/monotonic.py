"""Monotonic-register workload (reference `tidb/src/tidb/monotonic.clj`
and `cockroachdb/src/jepsen/cockroach/monotonic.clj`): clients bump
registers via read-then-write-v+1 transactions and read them back.
Every committed write of a key is its predecessor plus one, so:

  * a lost update makes two txns write the same value — the rw-register
    checker's `duplicate-writes` case;
  * a stale read (the register "going backwards") closes a dependency
    cycle only through a realtime or process precedence edge — exactly
    what `additional_graphs` exists for (`monotonic.clj` passes
    `:additional-graphs` at its lines 108/164/212). The anomaly
    surfaces as G-single-realtime / G-single-process.

Ops: {'f': 'inc', 'value': [['r', k, nil], ['w', k, nil]]} — the client
fills the read and writes read+1 — and {'f': 'read', 'value':
[['r', k, nil] ...]} multi-key reads.
"""

from __future__ import annotations

import dataclasses

from .. import generator as gen
from ..checker import elle

DEFAULT_GRAPHS = ("realtime", "process")


@dataclasses.dataclass(frozen=True)
class _MonotonicGen(gen.Gen):
    key_count: int
    read_len: int

    def op(self, test, ctx):
        if gen.rng.random() < 0.5:
            k = gen.rng.randrange(self.key_count)
            o = gen.fill_in_op(
                {"f": "inc", "value": [["r", k, None], ["w", k, None]]},
                ctx)
        else:
            n = min(self.read_len, self.key_count)
            ks = gen.rng.sample(range(self.key_count), n)
            o = gen.fill_in_op(
                {"f": "read", "value": [["r", k, None] for k in ks]},
                ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, self

    def update(self, test, ctx, event):
        return self


def generator(key_count: int = 4, read_len: int = 2) -> gen.Gen:
    return _MonotonicGen(key_count, read_len)


def workload(opts: dict | None = None) -> dict:
    """Options: 'key-count', 'read-len', 'anomalies' (default up to
    G-single — monotonicity, not full serializability), and
    'additional-graphs' (default realtime + process)."""
    opts = opts or {}
    anomalies = tuple(opts.get("anomalies", ("G0", "G1", "G-single")))
    graphs = tuple(opts.get("additional-graphs", DEFAULT_GRAPHS))
    return {
        "checker": elle.rw_register_checker(
            anomalies, mesh=opts.get("mesh"), additional_graphs=graphs),
        "generator": generator(opts.get("key-count", 4),
                               opts.get("read-len", 2)),
    }
