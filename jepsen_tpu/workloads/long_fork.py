"""Long-fork detection: an anomaly legal in parallel snapshot isolation
but prohibited by SI (reference `jepsen/src/jepsen/tests/long_fork.clj`;
the algorithm is documented at length in its lines 1-95).

Write txns write a single key once ([['w', k, 1]]); read txns read a whole
key *group* ([['r', k1, None], ['r', k2, None], ...]). Since each key is
written exactly once, a total order over reads exists iff every pair of
reads in a group is comparable under "a dominates b when a's non-nil
observations are a superset of b's". An incomparable pair is a long fork:
r1 saw x but not y while r2 saw y but not x.
"""

from __future__ import annotations

import dataclasses
import itertools

from .. import generator as gen
from .. import txn as mop
from ..checker import Checker, UNKNOWN
from ..history import history as as_history, is_invoke, is_ok


def group_for(n: int, k: int) -> list[int]:
    """The collection of keys for key k's group (`long_fork.clj:97-104`)."""
    lo = k - (k % n)
    return list(range(lo, lo + n))


def read_txn_for(n: int, k: int) -> list:
    """A txn reading k's whole group, in shuffled order
    (`long_fork.clj:106-112`)."""
    ks = group_for(n, k)
    gen.rng.shuffle(ks)
    return [["r", k2, None] for k2 in ks]


@dataclasses.dataclass(frozen=True)
class Generator(gen.Gen):
    """Single-key writes of fresh keys, interleaved with group reads of
    recently written groups (`long_fork.clj:117-150`). workers maps a
    thread to the key it just wrote (it reads that group next)."""
    n: int
    next_key: int
    workers: tuple  # ((thread, key-or-None), ...)

    def _last_written(self, thread):
        for t, k in self.workers:
            if t == thread:
                return k
        return None

    def _with(self, thread, k):
        pairs = tuple((t, x) for t, x in self.workers if t != thread)
        return dataclasses.replace(self,
                                   workers=pairs + ((thread, k),))

    def op(self, test, ctx):
        process = gen.some_free_process(ctx)
        worker = gen.process_to_thread(ctx, process)
        if worker is None:
            return gen.PENDING, self
        k = self._last_written(worker)
        if k is not None:
            # we wrote a key; read its group and clear our last-written
            op = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k)}, ctx)
            return op, self._with(worker, None)
        active = [key for _, key in self.workers if key is not None]
        if gen.rng.random() < 0.5 and active:
            # read some other active group
            k2 = active[gen.rng.randrange(len(active))]
            op = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k2)}, ctx)
            return op, self
        # write a fresh key
        op = gen.fill_in_op(
            {"process": process, "f": "write",
             "value": [["w", self.next_key, 1]]}, ctx)
        return op, dataclasses.replace(
            self._with(worker, self.next_key), next_key=self.next_key + 1)

    def update(self, test, ctx, event):
        return self


def generator(n: int) -> Generator:
    return Generator(n, 0, ())


class IllegalHistory(Exception):
    def __init__(self, info: dict):
        self.info = info
        super().__init__(info.get("msg", "illegal history"))


def read_compare(a: dict, b: dict):
    """-1 if a dominates, 0 if equal, 1 if b dominates, None if
    incomparable (`long_fork.clj:158-196`)."""
    if len(a) != len(b) or set(a) != set(b):
        raise IllegalHistory(
            {"type": "illegal-history", "reads": [a, b],
             "msg": "These reads did not query for the same keys, and "
                    "therefore cannot be compared."})
    res = 0
    for k in a:
        va, vb = a[k], b[k]
        if va == vb:
            continue
        if vb is None:      # a observed more here
            if res > 0:
                return None
            res = -1
        elif va is None:    # b observed more here
            if res < 0:
                return None
            res = 1
        else:
            raise IllegalHistory(
                {"type": "illegal-history", "key": k, "reads": [a, b],
                 "msg": "These two read states contain distinct values "
                        "for the same key; this checker assumes only one "
                        "write occurs per key."})
    return res


def read_op_value_map(op: dict) -> dict:
    """A read op's txn as {key: value} (`long_fork.clj:198-206`)."""
    return {mop.key(m): mop.value(m) for m in op["value"]}


def distinct_pairs(coll):
    return list(itertools.combinations(coll, 2))


def find_forks(ops) -> list:
    """Mutually incomparable read pairs (`long_fork.clj:216-224`)."""
    forks = []
    for a, b in distinct_pairs(ops):
        if read_compare(read_op_value_map(a),
                        read_op_value_map(b)) is None:
            forks.append([a, b])
    return forks


def is_read_txn(txn) -> bool:
    return all(mop.is_read(m) for m in txn)


def is_write_txn(txn) -> bool:
    return len(txn) == 1 and mop.is_write(txn[0])


def op_read_keys(op: dict) -> frozenset:
    return frozenset(mop.key(m) for m in op["value"])


def groups(n: int, read_ops) -> list:
    """Partition read ops by key-group; a read observing the wrong number
    of keys is illegal (`long_fork.clj:248-261`)."""
    by_group: dict[frozenset, list] = {}
    for o in read_ops:
        by_group.setdefault(op_read_keys(o), []).append(o)
    out = []
    for group, ops in by_group.items():
        if len(group) != n:
            raise IllegalHistory(
                {"type": "illegal-history", "op": ops[0],
                 "msg": f"Every read in this history should have observed "
                        f"exactly {n} keys, but this read observed "
                        f"{len(group)} instead: {sorted(group)}"})
        out.append(ops)
    return out


def ensure_no_long_forks(n: int, reads) -> dict | None:
    forks = [f for ops in groups(n, reads) for f in find_forks(ops)]
    if forks:
        return {"valid?": False, "forks": forks}
    return None


def ensure_no_multiple_writes_to_one_key(hist) -> dict | None:
    seen: set = set()
    for o in hist:
        if is_invoke(o) and is_write_txn(o.get("value") or []):
            k = mop.key(o["value"][0])
            if k in seen:
                return {"valid?": UNKNOWN,
                        "error": ["multiple-writes", k]}
            seen.add(k)
    return None


def ok_reads(hist) -> list:
    return [o for o in hist
            if is_ok(o) and is_read_txn(o.get("value") or [])]


def early_reads(reads) -> list:
    """Reads that are too early to tell us anything (all nil)
    (`long_fork.clj:297-302`)."""
    return [o["value"] for o in reads
            if not any(mop.value(m) for m in o["value"])]


def late_reads(reads) -> list:
    """Reads that are too late to tell us anything (all written)
    (`long_fork.clj:304-309`)."""
    return [o["value"] for o in reads
            if all(mop.value(m) for m in o["value"])]


class LongForkChecker(Checker):
    """Searches for read pairs that order concurrent writes inconsistently
    (`long_fork.clj:311-324`)."""

    def __init__(self, n: int):
        self.n = n

    def check(self, test, hist, opts):
        hist = as_history(hist)
        reads = ok_reads(hist)
        out = {"reads-count": len(reads),
               "early-read-count": len(early_reads(reads)),
               "late-read-count": len(late_reads(reads))}
        try:
            err = (ensure_no_multiple_writes_to_one_key(hist)
                   or ensure_no_long_forks(self.n, reads)
                   or {"valid?": True})
        except IllegalHistory as e:
            err = {"valid?": UNKNOWN, "error": e.info}
        out.update(err)
        return out


def checker(n: int = 2) -> Checker:
    return LongForkChecker(n)


def workload(n: int = 2) -> dict:
    """Checker + generator hunting long forks; n is the group size
    (`long_fork.clj:326-332`)."""
    return {"checker": checker(n), "generator": generator(n)}
