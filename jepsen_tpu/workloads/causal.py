"""Causal-consistency register workload (reference
`jepsen/src/jepsen/tests/causal.clj`).

A causal order of 5 ops (read-init, write 1, read, write 2, read) is issued
per key by a single site; ops carry 'position' (this op's position id) and
'link' (the position this op causally follows, or 'init'). The
CausalRegister model steps through completions, rejecting broken links,
out-of-order writes, and unwritten reads.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import history as as_history, is_ok
from ..models import inconsistent, is_inconsistent


@dataclasses.dataclass(frozen=True)
class CausalRegister:
    """value/counter/last_pos state machine (`causal.clj:33-88`)."""
    value: int = 0
    counter: int = 0
    last_pos: Any = None

    def step(self, op: dict):
        c = self.counter + 1
        v = op.get("value")
        pos = op.get("position")
        link = op.get("link")
        if link != "init" and link != self.last_pos:
            return inconsistent(
                f"Cannot link {link!r} to last-seen position "
                f"{self.last_pos!r}")
        f = op["f"]
        if f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return inconsistent(
                f"expected value {c} attempting to write {v} instead")
        if f == "read-init":
            if self.counter == 0 and v not in (0, None):
                return inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return inconsistent(
                f"can't read {v} from register {self.value}")
        if f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return inconsistent(
                f"can't read {v} from register {self.value}")
        return inconsistent(f"unknown f {f!r}")


def causal_register() -> CausalRegister:
    return CausalRegister()


class CausalChecker(Checker):
    """Steps the model through every :ok op in order
    (`causal.clj:90-112`)."""

    def __init__(self, model):
        self.model = model

    def check(self, test, hist, opts):
        s = self.model
        for op in as_history(hist):
            if not is_ok(op):
                continue
            s = s.step(op)
            if is_inconsistent(s):
                return {"valid?": False, "error": s.msg}
        return {"valid?": True, "model": s}


def check(model=None) -> Checker:
    return CausalChecker(model if model is not None else causal_register())


# Generators (`causal.clj:115-118`) — one causal chain per key.
def r(test, ctx):
    return {"type": "invoke", "f": "read"}


def ri(test, ctx):
    return {"type": "invoke", "f": "read-init"}


def cw1(test, ctx):
    return {"type": "invoke", "f": "write", "value": 1}


def cw2(test, ctx):
    return {"type": "invoke", "f": "write", "value": 2}


def test(opts: dict | None = None) -> dict:
    """Workload bundle: per-key causal chains, staggered, with a
    start/stop nemesis cycle (`causal.clj:120-132`)."""
    opts = opts or {}
    chain = [gen.once(g) for g in (ri, cw1, r, cw2, r)]
    g = gen.stagger(
        1, independent.concurrent_generator(1, itertools.count(),
                                            lambda k: chain))
    g = gen.nemesis(
        gen.cycle(gen.concat(gen.sleep(10), {"type": "info", "f": "start"},
                             gen.sleep(10), {"type": "info", "f": "stop"})),
        g)
    return {
        "checker": independent.checker(check(causal_register())),
        "generator": gen.time_limit(opts.get("time-limit", 60), g),
    }
