"""Table-visibility workload (reference `tidb/src/tidb/table.clj`):
one process stream creates numbered tables while everyone else races
inserts into them. Once a `create-table` has *completed*, every insert
*invoked* later must see the table — "table doesn't exist" after the
create's completion is a realtime visibility anomaly (a schema change
that un-happened). The checker derives that precedence with the Elle
additional-graphs layer's interval machinery
(`checker/elle/graphs.node_intervals`) rather than wall-clock times.

Ops: {'f': 'create-table', 'value': t} and {'f': 'insert', 'value':
[t, k]}; an insert that finds no table fails with error
['table-missing', t] (allowed while the create is still in flight).
"""

from __future__ import annotations

import dataclasses

from .. import generator as gen
from ..checker import Checker
from ..checker.elle import graphs
from ..history import history as as_history, is_fail, is_info, is_ok


@dataclasses.dataclass(frozen=True)
class _TableGen(gen.Gen):
    create_prob: float
    next_table: int
    next_row: int

    def op(self, test, ctx):
        if gen.rng.random() < self.create_prob:
            o = gen.fill_in_op(
                {"f": "create-table", "value": self.next_table}, ctx)
            if o is gen.PENDING:
                return gen.PENDING, self
            return o, dataclasses.replace(
                self, next_table=self.next_table + 1)
        # inserts may target the not-yet-created next table: that race
        # is the point of the workload
        t = gen.rng.randrange(self.next_table + 1)
        o = gen.fill_in_op(
            {"f": "insert", "value": [t, self.next_row]}, ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, dataclasses.replace(self, next_row=self.next_row + 1)

    def update(self, test, ctx, event):
        return self


def generator(create_prob: float = 0.2) -> gen.Gen:
    return _TableGen(create_prob, 0, 0)


def _is_missing(op: dict) -> bool:
    err = op.get("error")
    return (isinstance(err, (list, tuple)) and len(err) == 2
            and err[0] == "table-missing")


class TableChecker(Checker):
    """Flags inserts that failed 'table-missing' though the table's
    create completed before they were invoked, and inserts that
    succeeded into a table no create (ok or :info — maybe-applied)
    ever touched."""

    def check(self, test, hist, opts):
        hist = as_history(hist).index().client_ops()
        nodes = [o for o in hist
                 if (is_ok(o) or is_fail(o) or is_info(o))
                 and o.get("f") in ("create-table", "insert")]
        iv = graphs.node_intervals(hist, nodes)
        create_done: dict = {}   # table -> earliest create-ok comp pos
        created_any: set = set()
        for o, (_ip, cp, ok) in zip(nodes, iv):
            if o.get("f") != "create-table":
                continue
            t = o.get("value")
            if ok:
                create_done[t] = min(cp, create_done.get(t, cp))
                created_any.add(t)
            elif is_info(o):
                created_any.add(t)
        missing_after_create = []
        phantom = []
        for o, (ip, _cp, ok) in zip(nodes, iv):
            if o.get("f") != "insert":
                continue
            t = (o.get("value") or [None])[0]
            if ok and t not in created_any:
                phantom.append(o)
            elif is_fail(o) and _is_missing(o) \
                    and create_done.get(t, ip + 1) < ip:
                missing_after_create.append(o)
        errors = {}
        if missing_after_create:
            errors["missing-after-create"] = missing_after_create
        if phantom:
            errors["phantom-table"] = phantom
        return {"valid?": not errors,
                "table-count": len(created_any),
                **errors}


def checker() -> Checker:
    return TableChecker()


def workload(opts: dict | None = None) -> dict:
    opts = opts or {}
    return {"checker": checker(),
            "generator": generator(opts.get("create-prob", 0.2))}
