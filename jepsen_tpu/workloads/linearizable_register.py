"""Linearizability over a set of independent registers (reference
`jepsen/src/jepsen/tests/linearizable_register.clj`).

Clients understand three functions over (key, value) tuples:

    {'type': 'invoke', 'f': 'write', 'value': (k, v)}
    {'type': 'invoke', 'f': 'read',  'value': (k, None)}
    {'type': 'invoke', 'f': 'cas',   'value': (k, (v, v2))}

The checker is the flagship TPU path: independent/checker batches every
key's subhistory into one vmapped WGL kernel call (see independent.py and
checker/wgl.py).
"""

from __future__ import annotations

import itertools

from .. import generator as gen
from .. import independent
from ..checker import linearizable
from ..models import cas_register


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rng.randrange(5)}


def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": (gen.rng.randrange(5), gen.rng.randrange(5))}


def test(opts: dict | None = None) -> dict:
    """A partial test: generator, model, checker; you provide the client
    (`linearizable_register.clj:22-53`)."""
    opts = opts or {}
    n = len(opts.get("nodes", ["n1", "n2", "n3", "n4", "n5"]))
    model = opts.get("model", cas_register())
    per_key_limit = opts.get("per-key-limit")
    process_limit = opts.get("process-limit", 20)

    def fgen(k):
        g = gen.reserve(n, r, gen.mix([w, cas, cas]))
        if per_key_limit:
            # randomize the limit so keys drift out of phase
            g = gen.limit(
                max(1, round((0.9 + gen.rng.random() * 0.2)
                             * per_key_limit)), g)
        return gen.process_limit(process_limit, g)

    # A bare Linearizable subchecker (not compose-wrapped) lets
    # independent.checker take the batched one-kernel-call TPU path.
    return {
        "checker": independent.checker(linearizable(model)),
        "generator": independent.concurrent_generator(
            2 * n, itertools.count(), fgen),
    }
