"""Workload bundles: generator + checker (+ model) packages for standard
consistency tests, mirroring the reference's `jepsen/src/jepsen/tests/`
namespace family.

Each module exposes a `workload(...)`/`test(...)` builder returning a dict
with at least 'generator' and 'checker' entries, merged into a test map by
suites (pattern: `zookeeper.clj:106-129`).
"""

from . import adya, append, bank, causal, causal_reverse, comments, \
    linearizable_register, long_fork, monotonic, sequential, table, \
    wr  # noqa: F401

__all__ = ["adya", "append", "bank", "causal", "causal_reverse",
           "comments", "linearizable_register", "long_fork", "monotonic",
           "sequential", "table", "wr"]
