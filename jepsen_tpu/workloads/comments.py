"""Comments workload (reference
`cockroachdb/src/jepsen/cockroach/comments.clj`): writers insert
uniquely-numbered rows ("comments") and readers list every row they can
see. Under strict serializability a reader can never observe a later
insert while missing an earlier one that *completed before the later
one began* — the classic "comment 5 appears before comment 3" gap
CockroachDB's non-linearizable timestamp allocation makes possible.
That ordering is exactly the realtime precedence relation, so the
checker leans on the Elle additional-graphs layer
(`checker/elle/graphs.node_intervals`) for the completed-before-invoked
pairs instead of trusting wall clocks.

Ops: {'f': 'write', 'value': id} and {'f': 'read', 'value': None},
whose :ok carries the list of observed ids.
"""

from __future__ import annotations

import dataclasses

from .. import generator as gen
from ..checker import Checker
from ..checker.elle import graphs
from ..history import history as as_history, is_info, is_ok


@dataclasses.dataclass(frozen=True)
class _CommentsGen(gen.Gen):
    next_id: int

    def op(self, test, ctx):
        if gen.rng.random() < 0.5:
            o = gen.fill_in_op({"f": "write", "value": self.next_id},
                               ctx)
            if o is gen.PENDING:
                return gen.PENDING, self
            return o, dataclasses.replace(self, next_id=self.next_id + 1)
        o = gen.fill_in_op({"f": "read", "value": None}, ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, self

    def update(self, test, ctx, event):
        return self


def generator() -> gen.Gen:
    return _CommentsGen(0)


class CommentsChecker(Checker):
    """Hunts realtime gaps: a read that observed id b but not id a,
    where a's insert completed before b's insert was invoked; and stale
    reads: a read invoked after a's insert completed that misses a."""

    def check(self, test, hist, opts):
        from bisect import bisect_left

        hist = as_history(hist).index().client_ops()
        writes = [o for o in hist
                  if o.get("f") == "write" and (is_ok(o) or is_info(o))]
        reads = [o for o in hist if o.get("f") == "read" and is_ok(o)]
        w_iv = graphs.node_intervals(hist, writes)
        r_iv = graphs.node_intervals(hist, reads)
        inv_of = {o["value"]: ip for o, (ip, _cp, _ok)
                  in zip(writes, w_iv)}
        # acknowledged writes only, sorted by completion position: an
        # :info insert may never have happened, so missing it proves
        # nothing. comp_rank lets each read count its seen-and-
        # relevant writes in O(|seen|); only a read with a genuine
        # mismatch (an anomaly) pays for the prefix scan — a valid
        # 100k-op history stays linear in total read size.
        acked = sorted(((cp, o["value"], o) for o, (_ip, cp, ok)
                        in zip(writes, w_iv) if ok))
        comps = [cp for cp, _a, _o in acked]
        comp_rank = {a: i for i, (_cp, a, _o) in enumerate(acked)}
        gaps = []
        stale = []
        for o, (r_ip, _cp, _ok) in zip(reads, r_iv):
            if not isinstance(o.get("value"), (list, tuple, set)):
                continue
            seen = set(o["value"])
            latest_inv = max((inv_of[b] for b in seen if b in inv_of),
                             default=-1)
            bound = max(r_ip, latest_inv)
            n_prefix = bisect_left(comps, bound)
            n_matched = sum(1 for b in seen
                            if comp_rank.get(b, n_prefix) < n_prefix)
            if n_matched == n_prefix:
                continue  # every realtime-preceding write was observed
            for comp, a, wop in acked[:n_prefix]:
                if a in seen:
                    continue
                if comp < r_ip:
                    stale.append({"read": o, "missing": wop})
                else:
                    gaps.append({"read": o, "missing": wop})
        errors = {}
        if gaps:
            errors["realtime-gaps"] = gaps
        if stale:
            errors["stale-reads"] = stale
        return {"valid?": not errors,
                "read-count": len(reads),
                "write-count": len(acked),
                **errors}


def checker() -> Checker:
    return CommentsChecker()


def workload(opts: dict | None = None) -> dict:
    opts = opts or {}
    return {"checker": checker(), "generator": generator()}
