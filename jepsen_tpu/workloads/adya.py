"""Adya G2 anti-dependency-cycle probe over *predicates* (reference
`jepsen/src/jepsen/tests/adya.clj`; see Adya's thesis for the anomaly
taxonomy).

For each key, exactly two concurrent :insert txns run: one holding an
a-table id, one a b-table id ({'f': 'insert', 'value': (key, [a_id,
b_id])} with exactly one id non-None). Each txn reads both tables by
predicate and inserts only if both reads are empty — so under
serializability at most one insert per key can commit.
"""

from __future__ import annotations

import itertools
import threading

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import history as as_history, is_ok


def g2_gen():
    """Pairs of insert ops per concurrent unique key
    (`adya.clj:12-57`)."""
    ids = itertools.count(1)
    lock = threading.Lock()

    def next_id() -> int:
        with lock:
            return next(ids)

    def fgen(k):
        return [
            gen.once(lambda test, ctx:
                     {"type": "invoke", "f": "insert",
                      "value": [None, next_id()]}),
            gen.once(lambda test, ctx:
                     {"type": "invoke", "f": "insert",
                      "value": [next_id(), None]}),
        ]

    return independent.concurrent_generator(2, itertools.count(), fgen)


class G2Checker(Checker):
    """At most one :insert may succeed per key (`adya.clj:59-87`)."""

    def check(self, test, hist, opts):
        keys: dict = {}
        for op in as_history(hist):
            if op.get("f") != "insert":
                continue
            v = op.get("value")
            k = v.key if isinstance(v, independent.KV) else None
            if k is None:
                continue
            if is_ok(op):
                keys[k] = keys.get(k, 0) + 1
            else:
                keys.setdefault(k, 0)
        illegal = {k: c for k, c in sorted(keys.items()) if c > 1}
        insert_count = sum(1 for c in keys.values() if c > 0)
        return {"valid?": not illegal,
                "key-count": len(keys),
                "legal-count": insert_count - len(illegal),
                "illegal-count": len(illegal),
                "illegal": illegal}


def g2_checker() -> Checker:
    return G2Checker()


def workload() -> dict:
    return {"checker": g2_checker(), "generator": g2_gen()}
