"""Strict-serializability G-single probe: T1 < T2, but T2 is visible
without T1 (reference `jepsen/src/jepsen/tests/causal_reverse.clj`).

Concurrent blind writes of distinct values per key; reads return the set
of visible values. Replaying the history, every write w_i records the set
of writes acknowledged before w_i's invocation; a read that sees w_i but
misses some w_j in that set violates strict serializability.
"""

from __future__ import annotations

import itertools

from .. import generator as gen
from .. import independent
from ..checker import Checker, compose
from ..history import history as as_history, is_invoke, is_ok


def graph(hist) -> dict:
    """value -> set of values acknowledged before its write was invoked
    (`causal_reverse.clj:21-47`)."""
    completed: set = set()
    expected: dict = {}
    for op in as_history(hist):
        if op.get("f") != "write":
            continue
        if is_invoke(op):
            expected[op["value"]] = frozenset(completed)
        elif is_ok(op):
            completed.add(op["value"])
    return expected


def errors(hist, expected: dict) -> list:
    """Reads that saw a write but missed one of its predecessors
    (`causal_reverse.clj:49-71`)."""
    errs = []
    for op in as_history(hist):
        if not (is_ok(op) and op.get("f") == "read"):
            continue
        seen = set(op.get("value") or ())
        our_expected: set = set()
        for v in seen:
            our_expected |= set(expected.get(v, ()))
        missing = our_expected - seen
        if missing:
            err = dict(op)
            err.pop("value", None)
            err["missing"] = sorted(missing)
            err["expected-count"] = len(our_expected)
            errs.append(err)
    return errs


class CausalReverseChecker(Checker):
    def check(self, test, hist, opts):
        expected = graph(hist)
        errs = errors(hist, expected)
        return {"valid?": not errs, "errors": errs}


def checker() -> Checker:
    return CausalReverseChecker()


def workload(opts: dict | None = None) -> dict:
    """Generator + checker bundle (`causal_reverse.clj:87-110`)."""
    opts = opts or {}
    n = len(opts.get("nodes", ["n1", "n2", "n3", "n4", "n5"]))
    per_key = opts.get("per-key-limit", 500)

    def fgen(k):
        writes = (lambda test, ctx:
                  {"f": "write", "value": next(counter)})
        counter = iter(range(10**9))
        return gen.limit(per_key, gen.stagger(
            1 / 100, gen.mix([gen.repeat({"f": "read", "value": None}),
                              writes])))

    return {
        "checker": compose(
            {"sequential": independent.checker(checker())}),
        "generator": independent.concurrent_generator(
            n, itertools.count(), fgen),
    }
