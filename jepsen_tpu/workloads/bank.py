"""Bank test: simulated transfers between accounts; every read must show
the same total balance (reference `jepsen/src/jepsen/tests/bank.clj`).

Test map options: 'accounts' (ids), 'total-amount', 'max-transfer'.
Ops: {'f': 'read'} -> value {account: balance}; {'f': 'transfer',
'value': {'from': a, 'to': b, 'amount': n}}.

The checker is an O(n) fold over ok reads; balance sums are vectorized
with numpy per read (host-side — this checker is bandwidth-trivial; the
TPU budget goes to linearizability/Elle kernels).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import generator as gen
from ..checker import Checker, compose
from ..history import history as as_history, is_ok


def read(test, ctx) -> dict:
    """A generator of read operations (`bank.clj:20-23`)."""
    return {"type": "invoke", "f": "read"}


def transfer(test, ctx) -> dict:
    """A random transfer between two random accounts (`bank.clj:25-33`)."""
    accounts = test.get("accounts", list(range(8)))
    return {"type": "invoke", "f": "transfer",
            "value": {"from": gen.rng.choice(accounts),
                      "to": gen.rng.choice(accounts),
                      "amount": 1 + gen.rng.randrange(
                          test.get("max-transfer", 5))}}


def diff_transfer():
    """Transfers only between distinct accounts (`bank.clj:35-39`)."""
    return gen.filter(
        lambda op: op["value"]["from"] != op["value"]["to"], transfer)


def generator():
    """A mixture of reads and transfers (`bank.clj:41-44`)."""
    return gen.mix([diff_transfer(), read])


def err_badness(test, err: dict) -> float:
    """How egregious is this error? (`bank.clj:46-55`)"""
    t = err["type"]
    if t == "unexpected-key":
        return len(err["unexpected"])
    if t == "nil-balance":
        return len(err["nils"])
    if t == "wrong-total":
        total_amount = test.get("total-amount", 100)
        return abs((err["total"] - total_amount) / total_amount)
    if t == "negative-value":
        return -sum(err["negative"])
    return 0


def check_op(accts: set, total: int, negative_balances: bool,
             op: dict) -> dict | None:
    """Errors in a single read's balance map (`bank.clj:57-82`)."""
    value = op.get("value") or {}
    ks = list(value.keys())
    balances = list(value.values())
    unexpected = [k for k in ks if k not in accts]
    if unexpected:
        return {"type": "unexpected-key", "unexpected": unexpected,
                "op": op}
    nils = {k: v for k, v in value.items() if v is None}
    if nils:
        return {"type": "nil-balance", "nils": nils, "op": op}
    arr = np.asarray(balances, dtype=np.int64) if balances \
        else np.zeros(0, np.int64)
    got = int(arr.sum())
    if got != total:
        return {"type": "wrong-total", "total": got, "op": op}
    if not negative_balances and bool((arr < 0).any()):
        return {"type": "negative-value",
                "negative": [int(b) for b in arr[arr < 0]], "op": op}
    return None


class BankChecker(Checker):
    """All reads sum to total-amount; balances non-negative unless
    'negative-balances?' (`bank.clj:84-121`)."""

    def __init__(self, opts: dict | None = None):
        self.opts = opts or {}

    def check(self, test, hist, opts):
        accts = set(test.get("accounts", list(range(8))))
        total = test.get("total-amount", 100)
        neg_ok = bool(self.opts.get("negative-balances?"))
        hist = as_history(hist).index()
        reads = [o for o in hist if is_ok(o) and o["f"] == "read"]
        errors: dict[str, list] = {}
        for o in reads:
            err = check_op(accts, total, neg_ok, o)
            if err is not None:
                errors.setdefault(err["type"], []).append(err)
        all_errs = [e for errs in errors.values() for e in errs]
        out: dict[str, Any] = {
            "valid?": not all_errs,
            "read-count": len(reads),
            "error-count": len(all_errs),
            "first-error": min(
                (e for e in all_errs),
                key=lambda e: e["op"].get("index", 0), default=None),
            "errors": {},
        }
        for typ, errs in errors.items():
            entry = {"count": len(errs), "first": errs[0],
                     "worst": max(errs,
                                  key=lambda e: err_badness(test, e)),
                     "last": errs[-1]}
            if typ == "wrong-total":
                entry["lowest"] = min(errs, key=lambda e: e["total"])
                entry["highest"] = max(errs, key=lambda e: e["total"])
            out["errors"][typ] = entry
        return out


def checker(opts: dict | None = None) -> Checker:
    return BankChecker(opts)


def test(opts: dict | None = None) -> dict:
    """A partial test bundling default accounts/amounts with generator and
    checker; caller opts override the defaults (`bank.clj:179-192`)."""
    opts = opts or {"negative-balances?": False}
    out = {
        "max-transfer": 5,
        "total-amount": 100,
        "accounts": list(range(8)),
        "checker": compose({"SI": checker(opts)}),
        "generator": generator(),
    }
    out.update({k: v for k, v in opts.items()
                if k != "negative-balances?"})
    return out
