"""Write/read register workload bundle (reference
`jepsen/src/jepsen/tests/cycle/wr.clj`): single-register txns with unique
writes; the Elle-class checker recovers what version order it can and
hunts dependency cycles on device."""

from __future__ import annotations

from ..checker import elle


def workload(opts: dict | None = None) -> dict:
    opts = opts or {}
    anomalies = tuple(opts.get("anomalies", ("G1", "G2")))
    return {
        "checker": elle.rw_register_checker(
            anomalies, mesh=opts.get("mesh"),
            additional_graphs=tuple(opts.get("additional-graphs", ()))),
        "generator": elle.wr_gen(
            key_count=opts.get("key-count", 5),
            min_txn_length=opts.get("min-txn-length", 1),
            max_txn_length=opts.get("max-txn-length", 4),
            max_writes_per_key=opts.get("max-writes-per-key", 16)),
    }
