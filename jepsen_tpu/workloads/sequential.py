"""Sequential-consistency workload (reference
`tidb/src/tidb/sequential.clj` and
`cockroachdb/src/jepsen/cockroach/sequential.clj`): each pair id i owns
two keys (2i, 2i+1); one thread writes key 2i, then — in a *separate*
transaction — key 2i+1, while readers read the pair in reverse order
(2i+1 first). Observing the second write but not the first violates
sequential consistency: the reader anti-depends on W(2i), which
process-precedes W(2i+1), which the reader observed —

    reader -rw-> W(2i) -process-> W(2i+1) -wr-> reader

a cycle invisible to ww/wr/rw edges alone. It classifies as
G-single-process, courtesy of the process precedence graph
(`checker/elle/graphs.py`).
"""

from __future__ import annotations

import dataclasses

from .. import generator as gen
from ..checker import elle

DEFAULT_GRAPHS = ("process",)


@dataclasses.dataclass(frozen=True)
class _SequentialGen(gen.Gen):
    """pending maps a thread to the pair id whose second write it still
    owes; recent holds completed pair ids for readers to probe."""
    next_pair: int
    pending: tuple   # ((thread, pair-id), ...)
    recent: tuple    # recently finished pair ids

    def op(self, test, ctx):
        process = gen.some_free_process(ctx)
        thread = gen.process_to_thread(ctx, process)
        if thread is None:
            return gen.PENDING, self
        owed = next((i for t, i in self.pending if t == thread), None)
        if owed is not None:
            o = gen.fill_in_op(
                {"process": process, "f": "write",
                 "value": [["w", 2 * owed + 1, owed + 1]]}, ctx)
            if o is gen.PENDING:
                return gen.PENDING, self
            return o, dataclasses.replace(
                self,
                pending=tuple((t, i) for t, i in self.pending
                              if t != thread),
                recent=(self.recent + (owed,))[-8:])
        if self.recent and gen.rng.random() < 0.5:
            i = self.recent[gen.rng.randrange(len(self.recent))]
            o = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": [["r", 2 * i + 1, None], ["r", 2 * i, None]]},
                ctx)
            if o is gen.PENDING:
                return gen.PENDING, self
            return o, self
        i = self.next_pair
        o = gen.fill_in_op(
            {"process": process, "f": "write",
             "value": [["w", 2 * i, i + 1]]}, ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, dataclasses.replace(
            self, next_pair=i + 1,
            pending=self.pending + ((thread, i),))

    def update(self, test, ctx, event):
        return self


def generator() -> gen.Gen:
    return _SequentialGen(0, (), ())


def workload(opts: dict | None = None) -> dict:
    """Options: 'anomalies' (default up to G-single) and
    'additional-graphs' (default process — the graph this workload's
    violation needs)."""
    opts = opts or {}
    anomalies = tuple(opts.get("anomalies", ("G0", "G1", "G-single")))
    graphs = tuple(opts.get("additional-graphs", DEFAULT_GRAPHS))
    return {
        "checker": elle.rw_register_checker(
            anomalies, mesh=opts.get("mesh"), additional_graphs=graphs),
        "generator": generator(),
    }
