"""List-append workload bundle (reference
`jepsen/src/jepsen/tests/cycle/append.clj`): clients append unique values
to per-key lists and read whole lists; the Elle-class checker infers the
dependency graph and hunts cycles on device."""

from __future__ import annotations

from ..checker import elle


def workload(opts: dict | None = None) -> dict:
    """Options: 'key-count', 'min-txn-length', 'max-txn-length',
    'max-writes-per-key', 'anomalies' (default ['G1', 'G2'], matching
    `append.clj:34-40`), 'additional-graphs' (e.g. ('realtime',) per
    `append.clj:48-50`), 'consistency-models' alias accepted."""
    opts = opts or {}
    anomalies = tuple(opts.get("anomalies", ("G1", "G2")))
    return {
        "checker": elle.list_append_checker(
            anomalies, mesh=opts.get("mesh"),
            additional_graphs=tuple(opts.get("additional-graphs", ()))),
        "generator": elle.append_gen(
            key_count=opts.get("key-count", 5),
            min_txn_length=opts.get("min-txn-length", 1),
            max_txn_length=opts.get("max-txn-length", 4),
            max_writes_per_key=opts.get("max-writes-per-key", 16)),
    }
