"""Control-node filesystem cache for expensive artifacts.

Reference: `jepsen/src/jepsen/fs_cache.clj` — caches files/strings/data
under `/tmp/jepsen/cache`, keyed by arbitrary "path" values (strings,
numbers, tuples...), written atomically via rename so concurrent tests
never observe partial writes (`fs_cache.clj:57-155`); `deploy-remote!`
(:223) pushes a cached file to the current remote node.

Encoding: each path component is made filesystem-safe by escaping; data
values are stored as JSON (the reference uses EDN).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
from typing import Any

DEFAULT_DIR = "/tmp/jepsen/cache"

_dir = DEFAULT_DIR
_lock = threading.Lock()


def set_dir(d: str) -> None:
    global _dir
    _dir = d


def _escape_component(c: Any) -> str:
    s = str(c)
    if re.fullmatch(r"\.+", s):  # "." / ".." would traverse out of _dir
        return s.replace(".", "%2e")
    return re.sub(r"[^A-Za-z0-9._-]", lambda m: f"%{ord(m.group(0)):02x}",
                  s) or "_"


def _as_components(path) -> list[str]:
    if isinstance(path, (list, tuple)):
        return [_escape_component(c) for c in path]
    return [_escape_component(path)]


def file_path(path) -> str:
    """The local cache file for a cache path (`fs_cache.clj:57-80`)."""
    return os.path.join(_dir, *_as_components(path))


def cached(path) -> bool:
    return os.path.exists(file_path(path))


def _atomic_write(dest: str, write_fn) -> str:
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest),
                               prefix=".cache-tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
        os.replace(tmp, dest)  # atomic on POSIX (`fs_cache.clj:96-110`)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return dest


def save_file(local_file: str, path) -> str:
    """Cache a local file's contents under path; returns the cache file."""
    with open(local_file, "rb") as src:
        data = src.read()
    return _atomic_write(file_path(path), lambda f: f.write(data))


def save_bytes(content: bytes, path) -> str:
    return _atomic_write(file_path(path), lambda f: f.write(content))


def save_string(content: str, path) -> str:
    return save_bytes(content.encode(), path)


def save_data(value: Any, path) -> str:
    """Cache a JSON-serializable value (reference caches EDN)."""
    return save_string(json.dumps(value), path)


def load_bytes(path) -> bytes | None:
    try:
        with open(file_path(path), "rb") as f:
            return f.read()
    except FileNotFoundError:
        return None


def load_string(path) -> str | None:
    b = load_bytes(path)
    return None if b is None else b.decode()


def load_data(path) -> Any:
    s = load_string(path)
    return None if s is None else json.loads(s)


def load_file(path) -> str | None:
    """The cache file path, if cached."""
    f = file_path(path)
    return f if os.path.exists(f) else None


def fetch(path, miss_fn) -> str:
    """Return the cache file for path, computing it with miss_fn() → bytes
    on a miss. Locked so concurrent misses compute once."""
    with _lock:
        f = load_file(path)
        if f is not None:
            return f
        return save_bytes(miss_fn(), path)


def clear(path=None) -> None:
    import shutil

    target = _dir if path is None else file_path(path)
    if os.path.isdir(target):
        shutil.rmtree(target, ignore_errors=True)
    elif os.path.exists(target):
        os.unlink(target)


def deploy_remote(path, remote_path: str) -> str:
    """Upload a cached file to the current control node+dir
    (`fs_cache.clj:223`)."""
    from . import control

    f = load_file(path)
    assert f is not None, f"nothing cached under {path!r}"
    return control.upload(f, remote_path)
