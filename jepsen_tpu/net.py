"""Network manipulation: the Net protocol and iptables/ipfilter backends.

Reference: `jepsen/src/jepsen/net.clj` (`Net` protocol :15-26, `drop-all!`
fast path :29-44, iptables impl :58-111, ipfilter :113-145),
`jepsen/src/jepsen/net/proto.clj` (PartitionAll batch drop), and
`jepsen/src/jepsen/control/net.clj` (ip lookup via getent, local-ip,
control-ip).

A *grudge* is {node: set-of-nodes-to-drop-traffic-from}.
"""

from __future__ import annotations

import threading

from . import control as c
from .control.core import RemoteError, lit
from .util import real_pmap

TC = "/sbin/tc"


# -- control.net helpers ----------------------------------------------------

_ip_cache: dict[str, str] = {}
_ip_lock = threading.Lock()


def reachable(node: str) -> bool:
    """Can the current node ping node? (`control/net.clj:8-12`)"""
    try:
        c.exec_("ping", "-w", 1, node)
        return True
    except RemoteError:
        return False


def local_ip() -> str:
    """The current node's IP (`control/net.clj:14-17`)."""
    return c.exec_("hostname", "-I").split()[0]


def ip_uncached(host: str) -> str:
    """Resolve host via getent on the current node
    (`control/net.clj:19-35`)."""
    res = c.exec_("getent", "ahosts", host)
    first = res.split("\n")[0]
    ip = first.split()[0] if first.split() else ""
    if not ip:
        raise RemoteError(f"blank getent ip for {host}: {res!r}")
    return ip


def ip(host: str) -> str:
    """Memoized hostname→IP (`control/net.clj:37-39`)."""
    with _ip_lock:
        if host not in _ip_cache:
            _ip_cache[host] = ip_uncached(host)
        return _ip_cache[host]


def control_ip() -> str:
    """The control node's IP as seen by the current DB node
    (`control/net.clj:41-52`)."""
    with c.binding(sudo=None):  # $SSH_CLIENT doesn't survive sudo subshells
        out = c.exec_("bash", "-c", "echo $SSH_CLIENT")
    return out.split()[0]


# -- Net protocol -----------------------------------------------------------

class Net:
    def drop(self, test: dict, src: str, dest: str) -> None:
        """Drop traffic from src as seen at dest."""
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        """End all drops; restore fast operation."""
        raise NotImplementedError

    def slow(self, test: dict, mean_ms: float = 50, variance_ms: float = 10,
             distribution: str = "normal") -> None:
        """Delay packets cluster-wide."""
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        """Randomized packet loss cluster-wide."""
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        """Remove delays/loss."""
        raise NotImplementedError


class PartitionAll:
    """Optional fast path: apply a whole grudge in one batched call per
    node (`net/proto.clj:5-12`)."""

    def drop_all(self, test: dict, grudge: dict) -> None:
        raise NotImplementedError


def drop_all(test: dict, grudge: dict) -> None:
    """Apply a grudge to the test's net, batched when supported
    (`net.clj:29-44`)."""
    net = test["net"]
    if isinstance(net, PartitionAll) or callable(
            getattr(net, "drop_all", None)):
        net.drop_all(test, grudge)
        return
    pairs = [(src, dst) for dst, srcs in grudge.items() for src in srcs]
    real_pmap(lambda p: net.drop(test, p[0], p[1]), pairs)


class Noop(Net):
    """Does nothing (`net.clj:48-56`)."""

    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, mean_ms=50, variance_ms=10,
             distribution="normal"):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass


noop = Noop()


def _each_node(test, f):
    c.on_nodes(test, lambda t, n: f())


class IPTables(Net, PartitionAll):
    """Default iptables implementation (`net.clj:58-111`)."""

    def drop(self, test, src, dest):
        with c.on(dest), c.su():
            c.exec_("iptables", "-A", "INPUT", "-s", ip(src),
                    "-j", "DROP", "-w")

    def heal(self, test):
        def f():
            with c.su():
                c.exec_("iptables", "-F", "-w")
                c.exec_("iptables", "-X", "-w")
        _each_node(test, f)

    def slow(self, test, mean_ms=50, variance_ms=10,
             distribution="normal"):
        def f():
            with c.su():
                c.exec_(TC, "qdisc", "add", "dev", "eth0", "root",
                        "netem", "delay", f"{mean_ms}ms",
                        f"{variance_ms}ms", "distribution", distribution)
        _each_node(test, f)

    def flaky(self, test):
        def f():
            with c.su():
                c.exec_(TC, "qdisc", "add", "dev", "eth0", "root",
                        "netem", "loss", "20%", "75%")
        _each_node(test, f)

    def fast(self, test):
        def f():
            try:
                with c.su():
                    c.exec_(TC, "qdisc", "del", "dev", "eth0", "root")
            except RemoteError as e:
                # no qdisc installed — already fast (`net.clj:95-99`)
                if "RTNETLINK answers: No such file or directory" not in \
                        str(e):
                    raise
        _each_node(test, f)

    def drop_all(self, test, grudge):
        def snub(t, node):
            srcs = grudge.get(node)
            if srcs:
                with c.su():
                    c.exec_("iptables", "-A", "INPUT", "-s",
                            ",".join(ip(s) for s in sorted(srcs)),
                            "-j", "DROP", "-w")
        c.on_nodes(test, snub, nodes=list(grudge.keys()))


iptables = IPTables()


class IPFilter(Net):
    """ipf(8) implementation for BSD-ish systems (`net.clj:113-145`)."""

    def drop(self, test, src, dest):
        with c.on(dest), c.su():
            c.exec_("echo", "block", "in", "from", src, "to", "any",
                    lit("|"), "ipf", "-f", "-")

    def heal(self, test):
        def f():
            with c.su():
                c.exec_("ipf", "-Fa")
        _each_node(test, f)

    def slow(self, test, mean_ms=50, variance_ms=10,
             distribution="normal"):
        def f():
            with c.su():
                c.exec_(TC, "qdisc", "add", "dev", "eth0", "root",
                        "netem", "delay", f"{mean_ms}ms",
                        f"{variance_ms}ms", "distribution", distribution)
        _each_node(test, f)

    def flaky(self, test):
        def f():
            with c.su():
                c.exec_(TC, "qdisc", "add", "dev", "eth0", "root",
                        "netem", "loss", "20%", "75%")
        _each_node(test, f)

    def fast(self, test):
        def f():
            with c.su():
                c.exec_(TC, "qdisc", "del", "dev", "eth0", "root")
        _each_node(test, f)


ipfilter = IPFilter()
