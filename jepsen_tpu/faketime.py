"""libfaketime wrappers: make a DB's clocks run at skewed *rates*.

Reference: `jepsen/src/jepsen/faketime.clj` — installs the jepsen fork of
libfaketime 0.9.6 (patched for CLOCK_*_COARSE) by building from source on
the node (:8-22), replaces DB executables with a `faketime -m -f` wrapper
script moving the original to `x.no-faketime` (:36-47 wrap!), and picks
random rate factors distributed around 1 (:57-65 rand-factor).
"""

from __future__ import annotations

import random

from . import control as c
from .control import util as cu

REPO = "https://github.com/jepsen-io/libfaketime.git"
TAG = "0.9.6-jepsen1"


def install() -> None:
    """Clone + make install the jepsen libfaketime fork on the node
    (`faketime.clj:8-22`)."""
    with c.su():
        c.exec_("mkdir", "-p", "/tmp/jepsen")
        with c.cd("/tmp/jepsen"):
            if not cu.exists("libfaketime-jepsen"):
                c.exec_("git", "clone", REPO, "libfaketime-jepsen")
            with c.cd("libfaketime-jepsen"):
                c.exec_("git", "checkout", TAG)
                c.exec_("make")
                c.exec_("make", "install")


def script(cmd: str, init_offset: float, rate: float) -> str:
    """A sh script invoking cmd under faketime with an initial offset
    (seconds) and clock rate (`faketime.clj:24-34`)."""
    off = int(init_offset)
    sign = "-" if off < 0 else "+"
    return ("#!/bin/bash\n"
            f'faketime -m -f "{sign}{abs(off)}s x{float(rate)}" '
            f'{c.expand_path(cmd)} "$@"\n')


def wrap(cmd: str, init_offset: float, rate: float) -> None:
    """Replace an executable with a faketime wrapper, moving the original
    to cmd.no-faketime; idempotent (`faketime.clj:36-47`)."""
    orig = cmd + ".no-faketime"
    wrapper = script(orig, init_offset, rate)
    if not cu.exists(orig):
        c.exec_("mv", cmd, orig)
    cu.write_file(wrapper, cmd)
    c.exec_("chmod", "a+x", cmd)


def unwrap(cmd: str) -> None:
    """Remove a wrapper, restoring the original binary
    (`faketime.clj:49-55`)."""
    orig = cmd + ".no-faketime"
    if cu.exists(orig):
        c.exec_("mv", orig, cmd)


def rand_factor(factor: float, rng: random.Random | None = None) -> float:
    """A random rate near 1 with max = factor * min, so the fastest clock
    is at most `factor`× the slowest (`faketime.clj:57-65`)."""
    r = rng or random
    hi = 2.0 / (1.0 + 1.0 / factor)
    lo = hi / factor
    return lo + r.random() * (hi - lo)
