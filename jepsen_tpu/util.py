"""Kitchen-sink utilities shared across layers.

Behavioral parity targets from the reference's `jepsen/src/jepsen/util.clj`:
`real-pmap` (crash-safe parallel map, :65), `with-relative-time` /
`relative-time-nanos` (:333-347), `timeout` (:370), `await-fn` (:383),
`nemesis-intervals` (:736), `history->latencies` (:700),
`integer-interval-set-str` (:629), `named-locks` (:860).
"""

from __future__ import annotations

import concurrent.futures
import math
import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence


# ---------------------------------------------------------------------------
# Relative time
# ---------------------------------------------------------------------------

# Active origins, newest last. A stack removed-by-identity (not a
# saved/restored single slot) so CONCURRENT runs — e.g. several tests
# feeding one verification service — can't leak a dead run's origin:
# with save/restore, interleaved exits re-installed a sibling's saved
# value after that sibling had already finished. Overlapping runs
# still share the newest origin (op times are per-run relative and
# the interpreter's workers must see their spawner's origin, so a
# thread-local can't work here); exits are now always clean.
_ORIGIN_STACK: list["relative_time"] = []


class relative_time:
    """Context manager establishing t=0 for a test run; all op :time fields
    are nanoseconds since this origin (reference util.clj:333-347). Nesting
    restores the enclosing origin on exit, like dynamic binding."""

    def __enter__(self):
        self.origin = _time.monotonic_ns()
        _ORIGIN_STACK.append(self)
        return self

    def __exit__(self, *exc):
        try:
            # remove THIS context wherever it sits (identity ==), not
            # necessarily the top: a concurrent sibling may have
            # entered after us and still be running
            _ORIGIN_STACK.remove(self)
        except ValueError:
            pass
        return False


def relative_time_nanos() -> int:
    if not _ORIGIN_STACK:
        raise RuntimeError("relative_time_nanos called outside relative_time")
    return _time.monotonic_ns() - _ORIGIN_STACK[-1].origin


def ms_to_nanos(ms: float) -> int:
    return int(ms * 1_000_000)


def nanos_to_ms(ns: float) -> float:
    return ns / 1_000_000


def secs_to_nanos(s: float) -> int:
    return int(s * 1_000_000_000)


def nanos_to_secs(ns: float) -> float:
    return ns / 1_000_000_000


# ---------------------------------------------------------------------------
# Parallelism
# ---------------------------------------------------------------------------

def real_pmap(fn: Callable, coll: Iterable) -> list:
    """Parallel map over real threads, one per element. If any element's fn
    throws, the first *interesting* exception propagates after all threads
    finish — barrier/interrupt noise from sibling branches is passed over
    so it can't mask a root cause (reference real-pmap / dom-top)."""
    items = list(coll)
    if not items:
        return []
    if len(items) == 1:
        return [fn(items[0])]
    with concurrent.futures.ThreadPoolExecutor(max_workers=len(items)) as ex:
        futures = [ex.submit(fn, x) for x in items]
        results, excs = [], []
        for fut in futures:
            try:
                results.append(fut.result())
            except BaseException as e:  # noqa: BLE001 — propagate any crash
                excs.append(e)
        if excs:
            # Prefer an *interesting* exception: when one branch crashes
            # for a real reason, sibling branches often die with barrier/
            # interrupt noise that would mask the root cause (reference
            # dom-top real-pmap-helper).
            boring = (threading.BrokenBarrierError, InterruptedError)
            raise next((e for e in excs if not isinstance(e, boring)),
                       excs[0])
        return results


def bounded_pmap(fn: Callable, coll: Iterable, max_workers: int = 16) -> list:
    """Parallel map with bounded concurrency, preserving order."""
    items = list(coll)
    if not items:
        return []
    workers = max(1, min(max_workers, len(items)))
    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
        return list(ex.map(fn, items))


class Timeout(Exception):
    pass


class _TimedOut:
    """Sentinel type for TIMED_OUT; falsy so guards read naturally."""

    def __repr__(self):
        return "<util.TIMED_OUT>"

    def __bool__(self):
        return False


# Distinct from anything a wrapped fn could return: pass
# ``default=TIMED_OUT`` to timeout() and compare with ``is``.
TIMED_OUT = _TimedOut()


def timeout(seconds: float, fn: Callable[[], Any],
            default: Any = Timeout, name: str | None = None) -> Any:
    """Run fn in a daemon worker thread; if it exceeds the deadline
    return ``default`` (or raise Timeout when no default is given).

    The reference's `timeout` (util.clj:370) *interrupts* its thread;
    Python threads cannot be interrupted, so the worker here is
    **abandoned**, not killed: fn keeps running in the background until
    it finishes on its own, and its late return value (or late
    exception) is discarded — it is never delivered to any caller. fns
    must therefore tolerate running to completion after their caller
    has moved on (idempotent teardown, no half-owned locks). Pass
    ``default=TIMED_OUT`` to get a sentinel distinct from anything fn
    itself could return. ``name`` labels the worker thread, so an
    abandoned hang is attributable in a thread dump (the device-sync
    watchdog names its guards after the sync site)."""
    box: list = []

    def run():
        try:
            box.append(("ok", fn()))
        except BaseException as e:  # noqa: BLE001
            box.append(("err", e))

    t = threading.Thread(target=run, daemon=True, name=name)
    t.start()
    t.join(seconds)
    if not box:
        if default is Timeout:
            raise Timeout(f"timed out after {seconds}s")
        return default
    tag, val = box[0]
    if tag == "err":
        raise val
    return val


def await_fn(fn: Callable[[], Any], retry_interval: float = 1.0,
             timeout_secs: float = 60.0, log_message: str | None = None,
             log_interval: float | None = 10.0) -> Any:
    """Invoke fn until it returns without throwing; retry every
    retry_interval seconds up to timeout_secs (reference util.clj:383-424)."""
    deadline = _time.monotonic() + timeout_secs
    last_log = _time.monotonic()
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            now = _time.monotonic()
            if now >= deadline:
                raise Timeout(
                    f"timed out after {timeout_secs}s awaiting "
                    f"{log_message or fn}") from e
            if (log_message and log_interval
                    and now - last_log >= log_interval):
                print(log_message)
                last_log = now
            _time.sleep(min(retry_interval, max(0.0, deadline - now)))


class NamedLocks:
    """A family of locks, one per name (reference util.clj:860)."""

    def __init__(self):
        self._locks: dict = {}
        self._guard = threading.Lock()

    def lock(self, name) -> threading.Lock:
        with self._guard:
            if name not in self._locks:
                self._locks[name] = threading.Lock()
            return self._locks[name]


# ---------------------------------------------------------------------------
# History analysis helpers
# ---------------------------------------------------------------------------

def history_latencies(hist) -> list[dict]:
    """The same history, but every invocation gains :latency (ns to
    completion) and :completion (the completing op); completions gain
    :latency too. Pending invocations pass through unannotated
    (reference util.clj history->latencies, :700)."""
    from .history import is_invoke
    out: list[dict] = []
    open_idx: dict = {}  # process -> index into out
    for o in hist:
        if is_invoke(o):
            out.append(o)
            open_idx[o["process"]] = len(out) - 1
        else:
            i = open_idx.pop(o["process"], None)
            if i is not None:
                inv = out[i]
                latency = o["time"] - inv["time"]
                o = dict(o)
                o["latency"] = latency
                out[i] = {**inv, "latency": latency, "completion": o}
            out.append(o)
    return out


def nemesis_intervals(hist, start_fs: set | None = None,
                      stop_fs: set | None = None) -> list[tuple]:
    """Pairs of (start-op, stop-op-or-None) nemesis activity intervals
    (reference util.clj nemesis-intervals, :736-782). Nemesis ops arrive in
    invoke/complete pairs; a stop pair closes *all* open start pairs:
    start1 start2 stop1 yields [s1a stop1a] [s1b stop1b] [s2a stop1a]
    [s2b stop1b]. Unclosed starts pair with None."""
    from .history import NEMESIS
    start_fs = start_fs or {"start"}
    stop_fs = stop_fs or {"stop"}
    nem = [o for o in hist if o.get("process") == NEMESIS]
    # Group into invoke/complete pairs with matching :f.
    pairs = [(a, b) for a, b in zip(nem[::2], nem[1::2])
             if a.get("f") == b.get("f")]
    intervals: list[tuple] = []
    starts: list[tuple] = []
    for a, b in pairs:
        if a["f"] in start_fs:
            starts.append((a, b))
        elif a["f"] in stop_fs:
            for s1, s2 in starts:
                intervals.append((s1, a))
                intervals.append((s2, b))
            starts = []
    for s1, s2 in starts:
        intervals.append((s1, None))
        intervals.append((s2, None))
    return intervals


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Compact string for a set of integers: '#{1 3..5 7}'
    (reference util.clj integer-interval-set-str, :629-654). Any run of
    length >= 2 renders as 'start..end'; None elements fall back to a
    plain set rendering."""
    xs = list(xs)
    if any(x is None for x in xs):
        return "#{" + " ".join(str(x) for x in xs) + "}"
    xs = sorted(set(xs))
    parts = []
    i = 0
    while i < len(xs):
        j = i
        while j + 1 < len(xs) and xs[j + 1] == xs[j] + 1:
            j += 1
        parts.append(str(xs[i]) if j == i else f"{xs[i]}..{xs[j]}")
        i = j + 1
    return "#{" + " ".join(parts) + "}"


def longest_common_prefix(seqs: Sequence[Sequence]) -> list:
    if not seqs:
        return []
    prefix = list(seqs[0])
    for s in seqs[1:]:
        n = 0
        for a, b in zip(prefix, s):
            if a != b:
                break
            n += 1
        prefix = prefix[:n]
        if not prefix:
            break
    return prefix


def majority(n: int) -> int:
    """Smallest majority of n: majority(5) = 3."""
    return n // 2 + 1


def quantile(sorted_xs: Sequence[float], q: float) -> float:
    """Nearest-rank quantile of a pre-sorted sequence."""
    if not sorted_xs:
        return math.nan
    i = min(len(sorted_xs) - 1, max(0, math.ceil(q * len(sorted_xs)) - 1))
    return sorted_xs[i]


def fraction(a: float, b: float) -> float:
    """a/b, but 1 when b is 0 (reference checker stats convention)."""
    return a / b if b else 1.0
