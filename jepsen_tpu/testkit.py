"""Hermetic test scaffolding: the noop test map and an in-process CAS
register backed by a lock-guarded cell (reference `jepsen/src/jepsen/
tests.clj:12-67` — noop-test, atom-db, atom-client).

These make a complete end-to-end run (generator -> interpreter -> history
-> checker) possible in one process with no cluster, which is the
reference's core test strategy (`core_test.clj:62-121`).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from . import client as jclient
from . import nemesis as jnemesis
from . import net as jnet
from .checker import unbridled_optimism


class AtomState:
    """A compare-and-swappable cell with a lock, standing in for the
    database under test."""

    def __init__(self, value: Any = None):
        self.value = value
        self.lock = threading.Lock()
        self.meta_log: list = []

    def reset(self, v):
        with self.lock:
            self.value = v

    def cas(self, old, new) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False

    def read(self):
        with self.lock:
            return self.value


class AtomClient(jclient.Client):
    """CAS-register client against an AtomState. Sleeps ~1 ms per invoke
    so histories exhibit real concurrency (`tests.clj:50-51`)."""

    def __init__(self, state: AtomState, latency_s: float = 0.001):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        self.state.meta_log.append("open")
        return self

    def setup(self, test):
        self.state.meta_log.append("setup")

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        out = dict(op)
        f = op["f"]
        if f == "write":
            self.state.reset(op["value"])
            out["type"] = "ok"
        elif f == "cas":
            old, new = op["value"]
            out["type"] = "ok" if self.state.cas(old, new) else "fail"
        elif f == "read":
            out["type"] = "ok"
            out["value"] = self.state.read()
        else:
            raise ValueError(f"unknown f {f!r}")
        return out

    def teardown(self, test):
        self.state.meta_log.append("teardown")

    def close(self, test):
        self.state.meta_log.append("close")

    def reusable(self, test):
        return True


def atom_client(state: Optional[AtomState] = None,
                latency_s: float = 0.001) -> AtomClient:
    return AtomClient(state if state is not None else AtomState(0),
                      latency_s)


def noop_test() -> dict:
    """Boring test stub, a basis for more complex tests
    (`tests.clj:12-25`)."""
    return {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "name": "noop",
        "concurrency": 5,
        "client": jclient.noop,
        "nemesis": jnemesis.noop,
        "net": jnet.iptables,
        "generator": None,
        "checker": unbridled_optimism(),
    }


class AtomDB:
    """In-process 'database' over an AtomState: setup zeroes the cell,
    teardown marks it 'done' (`tests.clj:27-43`)."""

    def __init__(self, state: AtomState):
        self.state = state

    def setup(self, test, node):
        self.state.reset(0)

    def teardown(self, test, node):
        self.state.reset("done")


def atom_db(state: AtomState) -> AtomDB:
    return AtomDB(state)
