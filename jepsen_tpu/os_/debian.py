"""Debian OS automation: apt, hostfiles, repos, JDK install.

Reference: `jepsen/src/jepsen/os/debian.clj:13-197` — hostfile loopback
fixup, `apt-get update` rate-limited to daily, installed-package queries
via dpkg, `install`/`uninstall!`, `add-repo!` with apt-key, and the
default OS setup (core packages + hostname).
"""

from __future__ import annotations

import logging
import time as _time

from .. import control as c
from ..control import util as cu
from ..control.core import lit
from . import OS

log = logging.getLogger(__name__)


def setup_hostfile() -> None:
    """Ensure /etc/hosts has a loopback entry for the local hostname
    (`os/debian.clj:13-27`)."""
    hosts = c.exec_("cat", "/etc/hosts")
    lines = ["127.0.0.1\tlocalhost"
             if line.startswith("127.0.0.1\t") else line
             for line in hosts.split("\n")]
    new = "\n".join(lines)
    if new != hosts:
        with c.su():
            cu.write_file(new, "/etc/hosts")


def time_since_last_update() -> int:
    """Seconds since the last apt-get update (`os/debian.clj:29-33`).
    Unparsable output (e.g. a no-op dummy remote) reads as freshly
    updated, so hermetic runs skip apt entirely."""
    try:
        now = int(c.exec_("date", "+%s"))
        then = int(c.exec_("stat", "-c", "%Y",
                           "/var/cache/apt/pkgcache.bin", lit("||"),
                           "echo", "0"))
    except ValueError:
        return 0
    return now - then


def update() -> None:
    with c.su():
        c.exec_("apt-get", "--allow-releaseinfo-change", "update")


def maybe_update() -> None:
    """apt-get update at most daily (`os/debian.clj:40-43`)."""
    if time_since_last_update() > 86400:
        update()


def installed(pkgs) -> set[str]:
    """The subset of pkgs currently installed (`os/debian.clj:45-56`)."""
    pkgs = [str(p) for p in pkgs]
    out = c.exec_("dpkg", "--get-selections", *pkgs)
    found = set()
    for line in out.split("\n"):
        parts = line.split()
        if len(parts) >= 2 and parts[1] == "install":
            found.add(parts[0].replace(":amd64", "").replace(":i386", ""))
    return found


def is_installed(pkg_or_pkgs) -> bool:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    return set(str(p) for p in pkgs) <= installed(pkgs)


def installed_version(pkg: str) -> str | None:
    """Installed version of pkg, or None (`os/debian.clj:73-79`)."""
    import re

    out = c.exec_("apt-cache", "policy", str(pkg))
    m = re.search(r"Installed: (\S+)", out)
    v = m.group(1) if m else None
    return None if v in (None, "(none)") else v


def uninstall(pkg_or_pkgs) -> None:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    present = installed(pkgs)
    if present:
        with c.su():
            c.exec_("apt-get", "remove", "--purge", "-y", *sorted(present))


def install(pkg_or_pkgs, force: bool = False) -> None:
    """Install packages unless already present. Accepts a name, a
    collection of names, or a dict of name -> pinned version — the
    reference's map form, rendered as apt's pkg=version syntax
    (`os/debian.clj:81-103`)."""
    if isinstance(pkg_or_pkgs, dict):
        versions = {str(k): str(v) for k, v in pkg_or_pkgs.items()}
    elif isinstance(pkg_or_pkgs, (list, tuple, set)):
        versions = {str(p): None for p in pkg_or_pkgs}
    else:
        versions = {str(pkg_or_pkgs): None}
    names = sorted(versions)
    missing = names if force else sorted(set(names) - installed(names))
    if not missing:
        return
    maybe_update()
    specs = [p if versions[p] is None else f"{p}={versions[p]}"
             for p in missing]
    with c.su():
        c.exec_("env", lit("DEBIAN_FRONTEND=noninteractive"),
                "apt-get", "install", "-y", *specs)


def add_repo(repo_name: str, apt_line: str,
             keyserver: str | None = None, key: str | None = None) -> None:
    """Add an apt source + optional key (`os/debian.clj:115-132`)."""
    path = f"/etc/apt/sources.list.d/{repo_name}.list"
    with c.su():
        if not cu.exists(path):
            if keyserver and key:
                c.exec_("apt-key", "adv", "--keyserver", keyserver,
                        "--recv", key)
            cu.write_file(apt_line + "\n", path)
            update()


def install_jdk11() -> None:
    """Install a JDK (`os/debian.clj:134-151` install-jdk11!)."""
    install(["openjdk-11-jdk-headless"])


class Debian(OS):
    """Default Debian setup: hostfile + core packages
    (`os/debian.clj:158-197`)."""

    packages = ["curl", "faketime", "iptables", "logrotate", "man-db",
                "net-tools", "ntpdate", "psmisc", "rsyslog", "sudo",
                "tar", "unzip", "vim", "wget"]

    def setup(self, test: dict, node: str) -> None:
        log.info("%s setting up debian", node)
        setup_hostfile()
        maybe_update()
        install(self.packages)

    def teardown(self, test: dict, node: str) -> None:
        pass


os = Debian()
