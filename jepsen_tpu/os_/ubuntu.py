"""Ubuntu OS automation: Debian tooling + Ubuntu package set + net heal.

Reference: `jepsen/src/jepsen/os/ubuntu.clj` — reuses the debian
helpers, installs the Ubuntu package list, and heals the network on
setup (so a crashed prior run's partitions don't leak in).
"""

from __future__ import annotations

import logging

from . import OS, debian

log = logging.getLogger(__name__)


class Ubuntu(OS):
    packages = ["apt-transport-https", "wget", "curl", "vim", "man-db",
                "faketime", "ntpdate", "unzip", "iptables", "psmisc",
                "tar", "bzip2", "iputils-ping", "iproute2", "rsyslog",
                "sudo", "logrotate"]

    def setup(self, test: dict, node: str) -> None:
        log.info("%s setting up ubuntu", node)
        debian.setup_hostfile()
        debian.maybe_update()
        debian.install(self.packages)
        net = test.get("net")
        if net is not None:
            try:
                net.heal(test)
            except Exception:
                pass

    def teardown(self, test: dict, node: str) -> None:
        pass


os = Ubuntu()
