"""CentOS OS automation: yum-based package management.

Reference: `jepsen/src/jepsen/os/centos.clj` — hostfile fixup that
*appends* the hostname to the loopback line, yum update rate-limited to
daily, installed-package queries via `yum list installed`, and the
default setup package list.
"""

from __future__ import annotations

import logging
import re

from .. import control as c
from ..control import util as cu
from . import OS

log = logging.getLogger(__name__)


def setup_hostfile() -> None:
    """Append the local hostname to the loopback line if missing
    (`os/centos.clj:12-25`)."""
    name = c.exec_("hostname")
    hosts = c.exec_("cat", "/etc/hosts")
    lines = [line + " " + name
             if line.startswith("127.0.0.1") and name not in line
             else line
             for line in hosts.split("\n")]
    with c.su():
        cu.write_file("\n".join(lines), "/etc/hosts")


def time_since_last_update() -> int:
    now = int(c.exec_("date", "+%s"))
    then = int(c.exec_("stat", "-c", "%Y", "/var/log/yum.log"))
    return now - then


def update() -> None:
    with c.su():
        c.exec_("yum", "-y", "update")


def maybe_update() -> None:
    """yum update at most daily; on any error, update anyway
    (`os/centos.clj:37-43`)."""
    try:
        if time_since_last_update() > 86400:
            update()
    except Exception:
        update()


def installed(pkgs) -> set[str]:
    """The subset of pkgs yum reports installed (`os/centos.clj:45-57`)."""
    want = {str(p) for p in pkgs}
    out = c.exec_("yum", "list", "installed")
    have = set()
    for line in out.split("\n"):
        name_arch = line.split()[0] if line.split() else ""
        m = re.match(r"(.*)\.[^\-]+$", name_arch)
        if m:
            have.add(m.group(1))
    return want & have


def is_installed(pkg_or_pkgs) -> bool:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    return {str(p) for p in pkgs} <= installed(pkgs)


def uninstall(pkg_or_pkgs) -> None:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    present = installed(pkgs)
    if present:
        with c.su():
            c.exec_("yum", "-y", "remove", *sorted(present))


def install(pkg_or_pkgs) -> None:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    missing = sorted({str(p) for p in pkgs} - installed(pkgs))
    if missing:
        with c.su():
            c.exec_("yum", "-y", "install", *missing)


class CentOS(OS):
    packages = ["curl", "faketime", "iptables", "logrotate", "man-db",
                "net-tools", "ntpdate", "psmisc", "rsyslog", "sudo",
                "tar", "unzip", "vim", "wget"]

    def setup(self, test: dict, node: str) -> None:
        log.info("%s setting up centos", node)
        setup_hostfile()
        maybe_update()
        install(self.packages)

    def teardown(self, test: dict, node: str) -> None:
        pass


os = CentOS()
