"""SmartOS OS automation: pkgin-based package management — the only
non-apt/yum OS the reference supports.

Reference: `jepsen/src/jepsen/os/smartos.clj` — hostfile fixup that
appends the hostname to the tab-separated loopback line, pkgin update
rate-limited to daily (timestamp of /var/db/pkgin/sql.log), installed
queries via `pkgin -p list` (semicolon-separated, name-version split
on the final dash), versioned installs, and svcadm-enabled ipfilter
(SmartOS's firewall — the ipfilter Net backend pairs with it).
"""

from __future__ import annotations

import logging
import re

from .. import control as c
from ..control import util as cu
from ..control.core import RemoteError
from . import OS

log = logging.getLogger(__name__)

PKGIN_DB_LOG = "/var/db/pkgin/sql.log"


def setup_hostfile() -> None:
    """Append the local hostname to the loopback entry if missing
    (`os/smartos.clj:12-25` — SmartOS uses a tab after 127.0.0.1)."""
    name = c.exec_("hostname")
    hosts = c.exec_("cat", "/etc/hosts")
    lines = [line + " " + name
             if line.startswith("127.0.0.1\t") and name not in line
             else line
             for line in hosts.split("\n")]
    with c.su():
        cu.write_file("\n".join(lines), "/etc/hosts")


def time_since_last_update() -> int:
    """Seconds since the last pkgin update (`os/smartos.clj:27-31`)."""
    now = int(c.exec_("date", "+%s"))
    then = int(c.exec_("stat", "-c", "%Y", PKGIN_DB_LOG))
    return now - then


def update() -> None:
    with c.su():
        c.exec_("pkgin", "update")


def maybe_update() -> None:
    """pkgin update at most daily; on any error, update anyway
    (`os/smartos.clj:37-43`)."""
    try:
        if time_since_last_update() > 86400:
            update()
    except Exception:  # noqa: BLE001 — missing db log etc.
        update()


def _name_of(entry: str) -> str | None:
    """pkgin list entries are 'name-version;description'; the package
    name is everything before the final dash (`os/smartos.clj:45-57`)."""
    head = entry.split(";")[0]
    m = re.match(r"(.*)-[^-]+$", head)
    return m.group(1) if m else None


def _version_of(entry: str) -> str | None:
    head = entry.split(";")[0]
    m = re.search(r".*-([^-]+)$", head)
    return m.group(1) if m else None


def installed(pkgs) -> set[str]:
    """The subset of pkgs pkgin reports installed."""
    want = {str(p) for p in pkgs}
    out = c.exec_("pkgin", "-p", "list")
    have = {_name_of(line) for line in out.split("\n") if line}
    return want & have


def installed_p(pkg_or_pkgs) -> bool:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    return installed(pkgs) == {str(p) for p in pkgs}


def installed_version(pkg: str) -> str | None:
    """The installed version of pkg, or None (`os/smartos.clj:72-84`)."""
    out = c.exec_("pkgin", "-p", "list")
    for line in out.split("\n"):
        if _name_of(line) == str(pkg):
            return _version_of(line)
    return None


def uninstall(pkg_or_pkgs) -> None:
    pkgs = pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set)) \
        else [pkg_or_pkgs]
    present = installed(pkgs)
    if present:
        with c.su():
            c.exec_("pkgin", "-y", "remove", *sorted(present))


def install(pkgs) -> None:
    """Ensure packages are installed: a collection installs any
    version; a {pkg: version} map pins versions
    (`os/smartos.clj:86-106`)."""
    if isinstance(pkgs, dict):
        for pkg, version in pkgs.items():
            if installed_version(pkg) != version:
                log.info("Installing %s %s", pkg, version)
                with c.su():
                    c.exec_("pkgin", "-y", "install",
                            f"{pkg}-{version}")
        return
    want = {str(p) for p in pkgs}
    missing = want - installed(want)
    if missing:
        with c.su():
            log.info("Installing %s", sorted(missing))
            c.exec_("pkgin", "-y", "install", *sorted(missing))


class SmartOS(OS):
    """`os/smartos.clj:108-131`: hostfile, rate-limited pkgin update,
    base packages, svcadm-enabled ipfilter, net heal."""

    def setup(self, test, node):
        log.info("%s setting up smartos", node)
        setup_hostfile()
        maybe_update()
        install(["wget", "curl", "vim", "unzip", "rsyslog",
                 "logrotate"])
        with c.su():
            c.exec_("svcadm", "enable", "-r", "ipfilter")
        try:
            test["net"].heal(test)
        except (RemoteError, KeyError):
            pass

    def teardown(self, test, node):
        pass


os = SmartOS()
