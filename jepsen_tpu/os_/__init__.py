"""OS protocol: operating-system setup/teardown on DB nodes.

Reference: `jepsen/src/jepsen/os.clj:4-8` — the two-method `OS` protocol
plus a noop. Concrete impls (debian/centos/ubuntu) live in sibling
modules.
"""

from __future__ import annotations


class OS:
    def setup(self, test: dict, node: str) -> None:
        """Set up the operating system on this node."""

    def teardown(self, test: dict, node: str) -> None:
        """Tear down the operating system on this node."""


class Noop(OS):
    pass


noop = Noop()
