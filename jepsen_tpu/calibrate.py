"""Online-calibrated cost model: measured device-seconds per modeled
element-op.

`wgl.select_engine` prices kernel shapes in *modeled* element-ops —
constants hand-fit against one hardware round — while the telemetry
layer (PR 10) records ground-truth chunk latency at every dispatch
site. This module closes that loop (ROADMAP: "measured cost model +
adaptive service scheduling"; the AccelSync posture of driving
scheduling from live instrumentation, arXiv 2605.07881):

  * **Robust running fit.** Each engine variant (``dense`` /
    ``sort`` / ``hash``) keeps one coefficient — measured seconds per
    modeled element-op — updated per observation by a
    bounded-influence running regression through the origin: the
    observed ratio is clipped to within ``CLIP_FACTOR``× of the
    current estimate (one wedged 60 s chunk cannot blow up the fit)
    and folded in with a step that decays from plain averaging to an
    EWMA (``ALPHA_MIN``), so the fit converges fast from cold and
    still tracks drift (thermal throttling, a relay slowdown).
  * **Persistence.** Coefficients live in a small JSON file *next to
    the JAX compile cache* (per platform:
    ``calibration-<platform>.json``), written by the service daemon
    at drain and loaded at daemon start — a restarted fleet prices
    work in measured device-seconds from its first chunk.
  * **Activation.** Nothing observes or consults calibration unless a
    `Calibration` is explicitly activated (:func:`activate` — the
    daemon does; `VerificationService` instances calibrate their own
    private instance either way). `select_engine` compares families
    by measured seconds only once BOTH compared variants have
    ``MIN_OBSERVATIONS`` — a half-calibrated model never flips an
    engine choice on one noisy ratio.

Observation sites: the service's stream pump (per chunk, the primary
loop) and wgl's offline chunked dispatch. Both skip a stream's first
chunk — compile latency is not execution latency.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading

from . import telemetry as _telemetry

log = logging.getLogger(__name__)

# the engine variants select_engine chooses between (the sort family
# runs at the XLA lex-sort OR the Pallas hash-dedup cost — different
# silicon, different coefficient)
VARIANTS = ("dense", "sort", "hash")

# observations of a variant before its coefficient is trusted for
# engine *decisions* (budget pricing uses whatever is known earlier)
MIN_OBSERVATIONS = 16
# bounded influence: an observed seconds/elementop ratio is clipped to
# [coeff/CLIP_FACTOR, coeff*CLIP_FACTOR] before it moves the estimate
CLIP_FACTOR = 8.0
# the running fit's step decays 1/n down to this floor (EWMA tail), so
# a long-lived daemon still tracks coefficient drift
ALPHA_MIN = 0.05
# pre-calibration conversion: 1e9 modeled element-ops ~ 1 device-
# second. Scaling BOTH costs and budget capacity by one constant keeps
# uncalibrated scheduling identical to the historical element-op
# budget; calibration then corrects each variant's slope individually.
NOMINAL_SECONDS_PER_ELEMENTOP = 1e-9

_M_OBS = _telemetry.counter(
    "jepsen_tpu_wgl_calibration_observations_total",
    "Chunk-latency observations folded into the measured cost model",
    ("variant",))
_M_COEFF = _telemetry.gauge(
    "jepsen_tpu_wgl_calibration_ratio",
    "Measured seconds per modeled element-op, per engine variant",
    ("variant",))


def detect_platform() -> str:
    """The platform key calibration files are keyed by. Env first
    (JAX_PLATFORMS=cpu is how the CPU CI pins itself) so this never
    imports jax just to name a file."""
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        return env.split(",")[0].strip() or "cpu"
    try:
        import jax
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — naming a file must not require a backend
        return "cpu"


def default_path(platform: str | None = None) -> str:
    """`calibration-<platform>.json` next to the JAX compile cache
    (same placement lever as `_platform.enable_compilation_cache`):
    the compile cache keeps kernels warm across daemon restarts, this
    file keeps the cost model warm."""
    base = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "jepsen-tpu", "jax")
    return os.path.join(os.path.dirname(base.rstrip(os.sep)),
                        f"calibration-{platform or detect_platform()}"
                        ".json")


class Calibration:
    """Per-variant robust running coefficients (see module
    docstring). Thread-safe: the service's stream workers observe
    concurrently."""

    def __init__(self, platform: str | None = None):
        self.platform = platform or detect_platform()
        self._lock = threading.Lock()
        # variant -> [coeff (s/elementop), n observations]
        self._fits: dict[str, list] = {}    # guarded-by: _lock

    # -- fitting -------------------------------------------------------------

    def observe(self, variant: str, elementops: float,
                seconds: float) -> float:
        """Fold one (modeled element-ops, observed seconds) chunk pair
        into the variant's coefficient; returns the updated
        coefficient."""
        ratio = max(float(seconds), 1e-9) / max(float(elementops), 1.0)
        with self._lock:
            fit = self._fits.get(variant)
            if fit is None:
                self._fits[variant] = fit = [ratio, 1]
            else:
                coeff, n = fit
                clipped = min(max(ratio, coeff / CLIP_FACTOR),
                              coeff * CLIP_FACTOR)
                alpha = max(ALPHA_MIN, 1.0 / (n + 1))
                fit[0] = (1.0 - alpha) * coeff + alpha * clipped
                fit[1] = n + 1
            coeff = fit[0]
        _M_OBS.labels(variant=variant).inc()
        _M_COEFF.labels(variant=variant).set(coeff)
        return coeff

    # -- reading -------------------------------------------------------------

    def count(self, variant: str) -> int:
        with self._lock:
            fit = self._fits.get(variant)
            return fit[1] if fit else 0

    def coeff(self, variant: str) -> float | None:
        """The variant's measured coefficient, or — for a variant this
        process never ran — the geometric mean of the measured ones
        (right order of magnitude beats the nominal constant). None
        when nothing at all is measured."""
        with self._lock:
            fit = self._fits.get(variant)
            if fit:
                return fit[0]
            if not self._fits:
                return None
            logs = [math.log(f[0]) for f in self._fits.values()]
            return math.exp(sum(logs) / len(logs))

    def ready(self, *variants: str) -> bool:
        """True when EVERY named variant has a trusted (directly
        measured, >= MIN_OBSERVATIONS) coefficient — the bar for
        letting measurement flip an engine decision."""
        with self._lock:
            return all(
                (self._fits.get(v) or [0, 0])[1] >= MIN_OBSERVATIONS
                for v in variants)

    def seconds(self, variant: str, elementops: float) -> float:
        """Price modeled element-ops in device-seconds: measured
        coefficient when known (or the cross-variant fallback),
        nominal conversion otherwise."""
        c = self.coeff(variant)
        if c is None:
            c = NOMINAL_SECONDS_PER_ELEMENTOP
        return float(elementops) * c

    def coefficients(self) -> dict:
        """{variant: {"seconds-per-elementop": c, "observations": n}}
        — the status()/CLI shape."""
        with self._lock:
            return {v: {"seconds-per-elementop": f[0],
                        "observations": f[1]}
                    for v, f in sorted(self._fits.items())}

    # -- persistence ---------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {"version": 1, "platform": self.platform,
                    "families": {v: {"coeff": f[0], "n": f[1]}
                                 for v, f in self._fits.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "Calibration":
        cal = cls(platform=d.get("platform"))
        for v, f in (d.get("families") or {}).items():
            try:
                coeff, n = float(f["coeff"]), int(f["n"])
            except (KeyError, TypeError, ValueError):
                continue
            if coeff > 0 and n > 0:
                cal._fits[v] = [coeff, n]
        return cal

    def save(self, path: str | None = None) -> str:
        path = path or default_path(self.platform)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # pid-unique tmp: concurrent savers (two daemons sharing one
        # cache dir) must not unlink each other's staging file
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(self.to_dict(), fh, indent=1)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str | None = None,
             platform: str | None = None) -> "Calibration":
        """The persisted calibration, or a fresh one when the file is
        missing/corrupt (a bad calibration file must never stop the
        daemon — it just starts cold)."""
        path = path or default_path(platform)
        try:
            with open(path) as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            return cls(platform=platform)
        cal = cls.from_dict(d)
        if platform and cal.platform != platform:
            # a cpu file must not price a tpu backend
            return cls(platform=platform)
        return cal


# -- the process-wide active calibration -------------------------------------
#
# Deliberately opt-in: tests and library users get deterministic
# modeled costs unless something (the service daemon, a bench A/B)
# activates measurement. observe()/active() are the only globals.

_active_lock = threading.Lock()
_active: Calibration | None = None      # guarded-by: _active_lock


def activate(cal: Calibration) -> Calibration:
    global _active
    with _active_lock:
        _active = cal
    return cal


def deactivate() -> None:
    global _active
    with _active_lock:
        _active = None


def active() -> Calibration | None:
    with _active_lock:
        return _active


def observe(variant: str, elementops: float, seconds: float) -> None:
    """Feed the active calibration, if any — the instrumentation-site
    helper (a strict no-op when nothing is activated)."""
    cal = active()
    if cal is not None:
        cal.observe(variant, elementops, seconds)


def price(cal: Calibration | None, variant: str,
          elementops: float) -> float:
    """Device-seconds for modeled element-ops under `cal` (None =
    nominal conversion) — the budget-pricing helper."""
    if cal is None:
        return float(elementops) * NOMINAL_SECONDS_PER_ELEMENTOP
    return cal.seconds(variant, elementops)
