"""`python -m jepsen_tpu` — the default main: the store web server
(reference `jepsen/src/jepsen/cli.clj:520-523`)."""

from .cli import main

main()
