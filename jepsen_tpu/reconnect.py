"""Stateful auto-reconnecting connection wrapper.

Reference: `jepsen/src/jepsen/reconnect.clj` — a read/write-locked mutable
wrapper around an open/close/name function triple: many threads may use
the connection concurrently (read lock); reopening it takes the write
lock so exactly one reopen happens and in-flight users drain first.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class _RWLock:
    """Writer-preferring read/write lock (stdlib has none)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class Wrapper:
    """A reconnectable connection: `wrapper(open=..., close=..., name=...)`
    (`reconnect.clj:16-32`). Use with_conn/reopen."""

    def __init__(self, open: Callable[[], Any],
                 close: Callable[[Any], None] = lambda c: None,
                 log: Callable[[str], None] | None = None,
                 name: str | None = None):
        self._open = open
        self._close = close
        self._log = log
        self.name = name
        self._lock = _RWLock()
        self._conn: Any = None
        self._opened = False

    def open(self) -> "Wrapper":
        self._lock.acquire_write()
        try:
            if not self._opened:
                self._conn = self._open()
                self._opened = True
        finally:
            self._lock.release_write()
        return self

    def conn(self) -> Any:
        if not self._opened:
            self.open()
        return self._conn

    def reopen(self) -> "Wrapper":
        """Close and reopen under the write lock (`reconnect.clj:60-80`)."""
        self._lock.acquire_write()
        try:
            if self._log:
                self._log(f"Reopening connection {self.name or ''}")
            if self._opened:
                try:
                    self._close(self._conn)
                except Exception:
                    pass
            self._conn = self._open()
            self._opened = True
        finally:
            self._lock.release_write()
        return self

    def close(self) -> None:
        self._lock.acquire_write()
        try:
            if self._opened:
                try:
                    self._close(self._conn)
                finally:
                    self._conn = None
                    self._opened = False
        finally:
            self._lock.release_write()

    def with_conn(self, f: Callable[[Any], Any]) -> Any:
        """Run f(conn) under the read lock; on error, reopen the
        connection and re-raise (`reconnect.clj:82-110`)."""
        if not self._opened:
            self.open()  # before the read lock: open() takes the write lock
        self._lock.acquire_read()
        try:
            return f(self._conn)
        except Exception:
            self._lock.release_read()
            try:
                self.reopen()
            except Exception:
                pass
            self._lock.acquire_read()  # rebalance for finally
            raise
        finally:
            self._lock.release_read()


def wrapper(open, close=lambda c: None, log=None, name=None) -> Wrapper:
    return Wrapper(open, close, log, name)
