"""Client protocol: applies operations to the database under test
(reference `jepsen/src/jepsen/client.clj:9-27`).

A client's lifecycle: `open(test, node)` returns a connected client bound
to one node; `setup(test)` prepares DB state; `invoke(test, op)` applies
one operation and returns its completion; `teardown(test)`; `close(test)`.
Open/close must not affect the logical state of the test.

Clients whose `reusable(test)` returns True survive process crashes;
otherwise the interpreter closes and reopens them for each fresh process
(`client.clj:29-34`).
"""

from __future__ import annotations

from typing import Any


class Client:
    def open(self, test: dict, node: str) -> "Client":
        """Connect to `node`; returns a client ready for invoke."""
        return self

    def close(self, test: dict) -> None:
        pass

    def setup(self, test: dict) -> None:
        pass

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply op; return the completion op (type ok/fail/info)."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def reusable(self, test: dict) -> bool:
        """May this client be reused by a fresh process after a crash?"""
        return False


class Noop(Client):
    """Does nothing, successfully (`client.clj:46-53`)."""

    def invoke(self, test, op):
        out = dict(op)
        out["type"] = "ok"
        return out

    def reusable(self, test):
        return True


noop = Noop()


class InvalidCompletion(Exception):
    def __init__(self, op, op2, problems):
        self.op, self.op2, self.problems = op, op2, problems
        super().__init__(
            "client returned an invalid completion: "
            + "; ".join(problems) + f" — invoke {op!r}, completion {op2!r}")


class Validate(Client):
    """Wraps a client, asserting its completions are well-formed
    (`client.clj:64-109`)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        res = self.client.open(test, node)
        if not isinstance(res, Client):
            raise TypeError(
                f"expected open to return a Client, got {res!r}")
        return Validate(res)

    def close(self, test):
        self.client.close(test)

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        op2 = self.client.invoke(test, op)
        problems = []
        if not isinstance(op2, dict):
            problems.append("should be a dict")
        else:
            if op2.get("type") not in ("ok", "info", "fail"):
                problems.append(":type should be ok, info, or fail")
            if op2.get("process") != op.get("process"):
                problems.append(":process should be the same")
            if op2.get("f") != op.get("f"):
                problems.append(":f should be the same")
        if problems:
            raise InvalidCompletion(op, op2, problems)
        return op2

    def teardown(self, test):
        self.client.teardown(test)

    def reusable(self, test):
        return self.client.reusable(test)


def validate(client: Client) -> Client:
    return Validate(client)


def is_reusable(client: Any, test: dict) -> bool:
    try:
        return bool(client.reusable(test))
    except Exception:
        return False
