"""Reporting helper: redirect prints into a store file.

Reference: `jepsen/src/jepsen/report.clj` — the `to` macro captures
stdout to a file while still teeing to the console (:7-16)."""

from __future__ import annotations

import contextlib
import io
import sys


class _Tee(io.TextIOBase):
    def __init__(self, *sinks):
        self.sinks = sinks

    def write(self, s):
        for sink in self.sinks:
            sink.write(s)
        return len(s)

    def flush(self):
        for sink in self.sinks:
            sink.flush()


@contextlib.contextmanager
def to(filename: str, tee: bool = True):
    """Context manager: stdout inside the block is written to filename
    (and still echoed when tee=True) — the reference's `report/to`."""
    with open(filename, "w") as f:
        old = sys.stdout
        sys.stdout = _Tee(f, old) if tee else f
        try:
            yield f
        finally:
            sys.stdout = old
