"""Reporting helper: redirect prints into a store file.

Reference: `jepsen/src/jepsen/report.clj` — the `to` macro captures
stdout to a file while still teeing to the console (:7-16)."""

from __future__ import annotations

import contextlib
import io
import sys


class _Tee(io.TextIOBase):
    def __init__(self, *sinks):
        self.sinks = sinks

    def write(self, s):
        for sink in self.sinks:
            sink.write(s)
        return len(s)

    def flush(self):
        for sink in self.sinks:
            sink.flush()


def recovery_line(results: dict) -> str:
    """One printable line summarizing a result's device-fault recovery
    trail, or '' when the result never faulted — for report `to`
    blocks and the web results page."""
    rec = (results or {}).get("recovered")
    if not isinstance(rec, dict):
        # workload checkers reuse the 'recovered' key for their own
        # payloads (e.g. the set checker's recovered-element string);
        # a device-fault trail is always a dict
        return ""
    line = (f"recovered from backend faults: "
            f"{', '.join(rec.get('faults', []))} "
            f"({rec.get('retries', 0)} retries")
    if "resumed-from-chunk" in rec:
        line += f", resumed from chunk {rec['resumed-from-chunk']}"
    return line + ")"


def tier_line(results: dict) -> str:
    """One printable line summarizing a result's tiered-verification
    outcome, or '' when the result never went through tier 1 (older
    stored results included)."""
    r = results or {}
    esc = r.get("escalated")
    if isinstance(esc, dict):
        line = (f"tier-1 screen escalated ({esc.get('why', '?')}, "
                f"suspicion {esc.get('suspicion', 0):g}) to the full "
                f"checker")
        eng = esc.get("engine")
        if isinstance(eng, dict) and eng.get("family"):
            line += (f" [{eng['family']}, modeled cost "
                     f"{eng.get('cost', 0):.3g}]")
        return line
    if r.get("screened"):
        return (f"tier-1 screen passed (suspicion "
                f"{r.get('suspicion', 0):g}, no escalation)")
    return ""


def telemetry_line(results: dict) -> str:
    """One printable line summarizing a run's pipeline telemetry —
    device chunks, tier-1 escalations, recovery retries, attestation
    failures — or '' when the results carry none of it (older stored
    results included)."""
    r = results or {}
    subs = [r] + [v for v in r.values() if isinstance(v, dict)]
    chunks = sum(s["chunks"] for s in subs
                 if isinstance(s.get("chunks"), int))
    escalated = sum(1 for s in subs
                    if isinstance(s.get("escalated"), dict))
    retries = corrupt = 0
    for s in subs:
        rec = s.get("recovered")
        if isinstance(rec, dict):
            retries += int(rec.get("retries", 0) or 0)
            corrupt += sum(1 for k in rec.get("faults", [])
                           if k == "corrupt")
    # degradation-ladder stamps (service verdicts; tier-full streams
    # carry none — older stored results never do)
    ladder = r.get("ladder") if isinstance(r.get("ladder"), dict) \
        else None
    deferred = sum(1 for s in subs if s.get("deferred")
                   and s.get("ladder-tier"))
    if not (chunks or escalated or retries or corrupt or ladder
            or deferred):
        return ""
    line = (f"telemetry: {chunks} device chunks, {escalated} "
            f"escalated, {retries} recovery retries, {corrupt} "
            f"attest failures")
    if ladder:
        line += (f"; ladder tier {ladder.get('tier', '?')} "
                 f"(max {ladder.get('max-tier', '?')}, "
                 f"{ladder.get('transitions', 0)} transitions)")
    if deferred:
        line += (f"; {deferred} device verdict"
                 f"{'s' if deferred != 1 else ''} deferred to offline")
    return line


def service_line(status: dict) -> str:
    """One printable line summarizing a verification service's status
    (the /healthz shape from service.VerificationService.status), or
    '' for anything else — for operator logs and the web index."""
    st = status or {}
    streams = st.get("streams")
    if not isinstance(streams, dict):
        return ""
    by_state: dict = {}
    for s in streams.values():
        by_state[s.get("state", "?")] = \
            by_state.get(s.get("state", "?"), 0) + 1
    parts = [f"{n} {state}" for state, n in sorted(by_state.items())]
    line = (f"service {st.get('state', '?')}: "
            f"{', '.join(parts) if parts else 'no streams'}")
    # degraded-tier streams (adaptive overload control; older
    # services' status dicts carry no ladder-tier fields)
    degraded = sum(1 for s in streams.values()
                   if s.get("ladder-tier") not in (None, "full"))
    if degraded:
        line += f"; {degraded} ladder-degraded"
    # crash-consistency fields (older services' status dicts carry
    # none of these)
    if st.get("recovered-total"):
        line += (f"; {st['recovered-total']} recovered"
                 f" (epoch {st.get('epoch', 0)})")
    sessions = st.get("sessions") or {}
    if sessions.get("replays"):
        line += f"; {sessions['replays']} op replays deduped"
    if st.get("fenced"):
        line += "; FENCED (another replica owns the store)"
    budget = st.get("budget") or {}
    if budget.get("initial"):
        line += (f"; budget {budget.get('capacity', 0):.3g}/"
                 f"{budget['initial']:.3g}")
        events = []
        if budget.get("ooms"):
            events.append(f"{budget['ooms']} OOM backpressure events")
        if budget.get("cuts"):
            events.append(f"{budget['cuts']} AIMD cuts")
        if events:
            line += f" ({', '.join(events)})"
    ladder = st.get("ladder") or {}
    if ladder.get("transitions"):
        line += f"; {ladder['transitions']} ladder transitions"
    return line


def search_line(results: dict) -> str:
    """One printable line summarizing a coverage-guided scenario
    search (the search.driver.run_search result shape), or '' for
    anything else — for report `to` blocks and operator logs."""
    r = results or {}
    if not isinstance(r.get("coverage-bits"), int) \
            or "simulations" not in r:
        return ""
    line = (f"search ({r.get('strategy', '?')}): "
            f"{r['simulations']} simulations over "
            f"{r.get('generations-run', 0)} generations, "
            f"{r['coverage-bits']} coverage bits, "
            f"corpus {r.get('corpus-size', 0)} genomes")
    viols = r.get("violations") or []
    if viols:
        steps = sum(int(v.get("shrink-steps", 0) or 0)
                    for v in viols)
        line += (f"; {len(viols)} violation"
                 f"{'s' if len(viols) != 1 else ''}, minimized in "
                 f"{steps} shrink steps")
    return line


def chaos_line(results: dict) -> str:
    """One printable line summarizing a self-chaos fuzz of the
    verification pipeline (the chaos.driver.run_chaos result shape),
    or '' for anything else."""
    r = results or {}
    if not isinstance(r.get("coverage-bits"), int) \
            or "schedules" not in r:
        return ""
    line = (f"chaos ({r.get('strategy', '?')}): "
            f"{r['schedules']} schedules, "
            f"{r['coverage-bits']} coverage bits, "
            f"corpus {r.get('corpus-size', 0)} genomes, "
            f"{r.get('conjunction-hits', 0)} replay-conjunction "
            f"hit{'s' if r.get('conjunction-hits', 0) != 1 else ''}")
    fails = r.get("failures") or []
    if fails:
        oracles = sorted({o for f in fails
                          for o in (f.get("oracles") or [])})
        line += (f"; {len(fails)} oracle failure"
                 f"{'s' if len(fails) != 1 else ''} "
                 f"({', '.join(oracles)}), shrunk in "
                 f"{r.get('shrink-steps', 0)} steps")
    return line


@contextlib.contextmanager
def to(filename: str, tee: bool = True):
    """Context manager: stdout inside the block is written to filename
    (and still echoed when tee=True) — the reference's `report/to`."""
    with open(filename, "w") as f:
        old = sys.stdout
        sys.stdout = _Tee(f, old) if tee else f
        try:
            yield f
        finally:
            sys.stdout = old
