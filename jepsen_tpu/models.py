"""System models for linearizability checking.

Equivalents of knossos.model (the reference consumes these via
`jepsen/src/jepsen/checker.clj:185-216` and per-suite model definitions,
e.g. `jepsen/src/jepsen/tests/linearizable_register.clj:37`).

A model is an immutable, hashable value with a ``step(op) -> model`` method;
stepping with an impossible op returns an ``Inconsistent`` describing why.
Device kernels use the *enumerable* subset (register family, mutex) via
integer state encodings declared here; arbitrary Python models fall back to
the host checker.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from .history import DeviceEncodingError, UQ_COUNT_MAX, UQ_VALUES, F_CAS, F_READ, F_WRITE, NIL


class Inconsistent:
    """A terminal model state: the op could not have happened."""

    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"

    def __eq__(self, other):
        return isinstance(other, Inconsistent) and self.msg == other.msg

    def __hash__(self):
        return hash(("inconsistent", self.msg))


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m: Any) -> bool:
    return isinstance(m, Inconsistent)


class Model:
    """Base class. Subclasses must be immutable and hashable."""

    def step(self, op: dict) -> "Model | Inconsistent":
        raise NotImplementedError

    # -- device lowering ----------------------------------------------------
    # Models that can run on TPU provide an integer state encoding plus the
    # name of a registered device step function (see checker/wgl.py).
    device_model: Optional[str] = None

    def device_state(self) -> int:
        raise NotImplementedError(f"{type(self).__name__} has no device form")


@dataclasses.dataclass(frozen=True)
class CASRegister(Model):
    """A register supporting read/write/cas (knossos cas-register)."""
    value: Any = None

    device_model = "cas-register"

    def step(self, op: dict):
        f, v = op["f"], op["value"]
        if f in ("write", "w"):
            return CASRegister(v)
        if f == "cas":
            old, new = v
            if self.value != old:
                return inconsistent(
                    f"can't CAS {self.value!r} from {old!r} to {new!r}")
            return CASRegister(new)
        if f in ("read", "r"):
            if v is None or self.value == v:
                return self
            return inconsistent(f"can't read {v!r} from {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")

    def device_state(self) -> int:
        return NIL if self.value is None else int(self.value)


@dataclasses.dataclass(frozen=True)
class Register(Model):
    """A read/write register (knossos register)."""
    value: Any = None

    device_model = "register"

    def step(self, op: dict):
        f, v = op["f"], op["value"]
        if f in ("write", "w"):
            return Register(v)
        if f in ("read", "r"):
            if v is None or self.value == v:
                return self
            return inconsistent(f"can't read {v!r} from {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")

    def device_state(self) -> int:
        return NIL if self.value is None else int(self.value)


@dataclasses.dataclass(frozen=True)
class Mutex(Model):
    """A lock with acquire/release (knossos mutex)."""
    locked: bool = False

    device_model = "mutex"

    def step(self, op: dict):
        f = op["f"]
        if f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a held lock")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("cannot release a free lock")
            return Mutex(False)
        return inconsistent(f"unknown op f={f!r}")

    def device_state(self) -> int:
        return 1 if self.locked else 0


@dataclasses.dataclass(frozen=True)
class NoOp(Model):
    """A model which considers any op legal (knossos noop)."""

    def step(self, op: dict):
        return self


@dataclasses.dataclass(frozen=True)
class Counter(Model):
    """A counter: adds always apply, reads must observe the current
    value (knossos's counter model family; the reference offloads
    counter checking to the O(n) bounds checker, `checker.clj:737-795`
    — this model makes it *linearizability*-checkable on device)."""
    value: int = 0

    device_model = "counter"

    def step(self, op: dict):
        f, v = op["f"], op["value"]
        if f == "add":
            return Counter(self.value + int(v))
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(
                f"read {v!r} but counter is {self.value}")
        return inconsistent(f"unknown op f={f!r}")

    def device_state(self) -> int:
        return self.value


@dataclasses.dataclass(frozen=True)
class GSet(Model):
    """A grow-only set: adds accumulate, reads observe the exact
    current membership (the CRDT G-Set the hazelcast suite's map
    workload exercises, `hazelcast.clj:652-767`)."""
    members: frozenset = frozenset()

    device_model = "g-set"

    def step(self, op: dict):
        f, v = op["f"], op["value"]
        if f == "add":
            return GSet(self.members | {v})
        if f == "read":
            if v is None or frozenset(v) == self.members:
                return self
            return inconsistent(
                f"read {sorted(v)!r} but set is "
                f"{sorted(self.members)!r}")
        return inconsistent(f"unknown op f={f!r}")

    def device_state(self) -> int:
        state = 0
        for v in self.members:
            v = int(v)
            if not 0 <= v < 31:
                raise DeviceEncodingError(
                    f"g-set element {v} outside the device bitmask "
                    "[0, 31) — use the host model")
            state |= 1 << v
        return state


@dataclasses.dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue where dequeues may return any enqueued-but-not-yet-dequeued
    element (knossos unordered-queue). State is a frozen multiset.

    Device form: the multiset packs into an int32 as 4-bit per-value
    counts when values are ints in [0, 7) and multiplicities stay
    under 16 — enough for token/CP-menu queue workloads; anything
    wider falls back to this host model."""
    pending: frozenset = frozenset()  # of (value, dup-count) expanded pairs

    device_model = "unordered-queue"

    def device_state(self) -> int:
        counts = [0] * UQ_VALUES
        for (v, _i) in self.pending:
            v = int(v)
            if not 0 <= v < UQ_VALUES:
                raise DeviceEncodingError(
                    f"queue value {v} outside the device digit range "
                    f"[0, {UQ_VALUES}) — use the host model")
            counts[v] += 1
            if counts[v] > UQ_COUNT_MAX:
                raise DeviceEncodingError(
                    f"more than {UQ_COUNT_MAX} copies of {v} in the "
                    "initial queue state would carry into the next "
                    "digit — use the host model")
        return sum(c << (4 * v) for v, c in enumerate(counts))

    @staticmethod
    def _add(pending: frozenset, v: Any) -> frozenset:
        n = sum(1 for (x, _) in pending if x == v)
        return pending | {(v, n)}

    @staticmethod
    def _remove(pending: frozenset, v: Any):
        matches = [(x, i) for (x, i) in pending if x == v]
        if not matches:
            return None
        return pending - {max(matches, key=lambda t: t[1])}

    def step(self, op: dict):
        f, v = op["f"], op["value"]
        if f == "enqueue":
            return UnorderedQueue(self._add(self.pending, v))
        if f == "dequeue":
            rest = self._remove(self.pending, v)
            if rest is None:
                return inconsistent(f"can't dequeue {v!r}: not in queue")
            return UnorderedQueue(rest)
        return inconsistent(f"unknown op f={f!r}")


@dataclasses.dataclass(frozen=True)
class FIFOQueue(Model):
    """A strictly-ordered queue (knossos fifo-queue)."""
    items: tuple = ()

    def step(self, op: dict):
        f, v = op["f"], op["value"]
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent(f"can't dequeue {v!r} from empty queue")
            if self.items[0] != v:
                return inconsistent(
                    f"can't dequeue {v!r}: head is {self.items[0]!r}")
            return FIFOQueue(self.items[1:])
        return inconsistent(f"unknown op f={f!r}")


@dataclasses.dataclass(frozen=True)
class MultiRegister(Model):
    """Registers addressed by key, stepped by whole transactions: ops
    carry `value` = [[f, k, v], ...] with f in {"r", "w"}. A nil read
    is always legal. Mirrors the reference's MultiRegister knossos
    model (`yugabyte/src/yugabyte/multi_key_acid.clj:16-38`)."""
    values: tuple = ()   # sorted ((k, v), ...) so the model hashes

    def step(self, op: dict):
        state = dict(self.values)
        for f, k, v in op["value"]:
            if f in ("r", "read"):
                if v is not None and state.get(k) != v:
                    return inconsistent(
                        f"can't read {v!r} from key {k!r} = "
                        f"{state.get(k)!r}")
            elif f in ("w", "write"):
                state[k] = v
            else:
                return inconsistent(f"unknown micro-op f={f!r}")
        return MultiRegister(tuple(sorted(state.items())))


def cas_register(value: Any = None) -> CASRegister:
    return CASRegister(value)


def multi_register(values: dict | None = None) -> MultiRegister:
    return MultiRegister(tuple(sorted((values or {}).items())))


def register(value: Any = None) -> Register:
    return Register(value)


def mutex() -> Mutex:
    return Mutex()


def noop() -> NoOp:
    return NoOp()


def counter(value: int = 0) -> Counter:
    return Counter(value)


def gset() -> GSet:
    return GSet()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


# ---------------------------------------------------------------------------
# Device step semantics (shared by host oracle and TPU kernel golden tests)
# ---------------------------------------------------------------------------

def device_step_register(state: int, f: int, a: int, b: int,
                         cas: bool) -> tuple[bool, int]:
    """Pure integer semantics of the register family; the JAX kernel in
    checker/wgl.py implements exactly this with jnp ops.

    Returns (legal, new_state). NIL means 'never written'.
    """
    if f == F_READ:
        return (a == NIL or state == a), state
    if f == F_WRITE:
        return True, a
    if f == F_CAS and cas:
        return state == a, (b if state == a else state)
    return False, state


def device_step_mutex(state: int, f: int, a: int, b: int) \
        -> tuple[bool, int]:
    """f: 0 = acquire, 1 = release."""
    if f == 0:
        return state == 0, 1
    if f == 1:
        return state == 1, 0
    return False, state
