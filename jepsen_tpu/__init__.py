"""jepsen_tpu — a TPU-native distributed-systems correctness-testing framework.

A ground-up rebuild of the capabilities of Jepsen (reference:
m1l4n54v1c/jepsen, Clojure/JVM): it sets up a real distributed system over an
SSH control plane, drives randomized concurrent operations from a
pure-functional generator while a nemesis injects faults, records a complete
operation history, and verifies that history against consistency models.

The defining difference: the compute-bound checking stage — Knossos-class
linearizability search and Elle-class transactional-cycle detection — runs as
JAX/XLA kernels on TPU. Histories are encoded as structure-of-arrays device
tensors; the linearizability search is a breadth-first frontier over
fixed-width configurations (`lax.while_loop` + sort-dedup), vmapped over
independent keys and sharded across a `jax.sharding.Mesh` with psum-OR
verdict reduction.

Layer map (mirrors reference SURVEY.md §1):
  L0 control/       — remote execution (SSH/docker/k8s), shell escaping
  L1 os*/db         — environment automation protocols
  L2 nemesis*/net   — fault injection
  L3 generator/     — pure-functional op scheduler + combinators
  L4 core/client    — orchestrator runtime
  L5 checker/       — analysis (the TPU compute core)
  L6 store/web/cli  — persistence, reporting, UI
  L7 workloads + suites — per-database test bundles
"""

__version__ = "0.1.0"
