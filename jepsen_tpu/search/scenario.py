"""Genome -> runnable scenario: generator, synthetic executor, model.

`build(genome, bug=...)` turns a typed genome into the three things a
simulated run needs: a Context sized to the genome's concurrency, a
generator (client ops under `gen.clients`, nemesis fault-window
boundary ops on the nemesis thread), and a fault-aware *executor* — a
`complete(ctx, invoke)` function for `generator.simulate`.

The executor is a tiny in-memory register service that linearizes
every op at its invoke point (simulate() calls complete() at dispatch,
so invoke order IS linearization order): healthy runs are linearizable
by construction and tier-1 screens stay silent on them. It also
watches the nemesis boundary ops flow past and tracks which fault
kinds are active — which is what planted *bugs* key on. A bug from
BUGS gives the executor one precise defect, e.g.
'lost-write-kill-partition': a write is acknowledged ok but silently
dropped iff kill AND partition are both active at its invoke — a
conjunction-fault window bug that only a schedule overlapping both
kinds with the write phase can surface (later reads of the stale value
trip the screen's stale-read invariant).

Scenarios (the genome's `workload` field):

  register          uniform read/write mix over the whole horizon
  phased-register   long read phase, a NARROW write phase (the only
                    mutation window), then reads again — the planted-
                    bug demo target: the violation exists only when
                    fault windows overlap the write phase
"""

from __future__ import annotations

import random

from .. import models
from ..generator import clients as gen_clients
from ..generator import context as gen_context
from ..generator import delay as gen_delay
from ..generator import limit as gen_limit
from ..generator import sleep as gen_sleep
from ..generator import stagger as gen_stagger
from ..generator import time_limit as gen_time_limit
from ..generator import rng as gen_rng
from .coverage import START_F, STOP_F
from .mutate import Genome

# fault kind -> (window-start f, window-stop f); the f names are the
# nemesis/combined.py package op vocabulary, and tests pin this table
# against both the packages and coverage.START_F/STOP_F
KIND_OPS = {
    "partition": ("start-partition", "stop-partition"),
    "kill": ("kill", "start"),
    "pause": ("pause", "resume"),
    "clock": ("strobe-clock", "reset-clock"),
}

NEMESIS_LATENCY_NS = 1_000          # boundary ops are near-instant
VALUE_SPACE = 1_000_000_000

# phased-register shape: writes exist ONLY in [WRITE_AT_S,
# WRITE_AT_S + WRITES * WRITE_SPACING_S] — about 0.1s of a 60s run
PHASED_HORIZON_S = 60.0
WRITE_AT_S = 45.0
PHASED_WRITES = 5
WRITE_SPACING_S = 0.02
READ_STAGGER_S = 0.25


class Bug:
    """A planted executor defect: drop semantics gated on a
    conjunction of active fault kinds."""

    def __init__(self, name: str, trigger: frozenset, effect: str):
        self.name = name
        self.trigger = trigger
        self.effect = effect


BUGS = {
    # acked-but-lost write iff kill AND partition are simultaneously
    # active at the write's invoke
    "lost-write-kill-partition": Bug(
        "lost-write-kill-partition",
        frozenset({"kill", "partition"}), "lose-write"),
    # single-kind variant, for tests that need an easy target
    "lost-write-pause": Bug(
        "lost-write-pause", frozenset({"pause"}), "lose-write"),
}


class RegisterExecutor:
    """In-memory register `complete` fn. Ops linearize at invoke;
    completion latency comes from an executor-private stream seeded
    off the genome so it never touches the generator's pinned RNG."""

    def __init__(self, genome: Genome, bug: Bug | None = None):
        self.bug = bug
        # the register starts at 0, not None: the model treats a read
        # of None as a wildcard (knossos nil-read convention), so a
        # bug that strands the INITIAL value must strand a real one or
        # the full checkers would call the stale reads linearizable
        self.state = 0
        self.active: set = set()
        self.lost_writes = 0
        self._lat = random.Random(genome.seed ^ 0x5EED_CAFE)

    def _latency_ns(self) -> int:
        base = 2_000_000 if "pause" in self.active else 200_000
        return self._lat.randrange(base, base * 4)

    def complete(self, ctx, invoke: dict) -> dict:
        out = dict(invoke)
        if invoke.get("process") == "nemesis":
            f = invoke.get("f")
            if f in START_F:
                self.active.add(START_F[f])
            elif f in STOP_F:
                self.active.discard(STOP_F[f])
            out["time"] = invoke["time"] + NEMESIS_LATENCY_NS
            return out
        f = invoke.get("f")
        if f == "write":
            dropped = (self.bug is not None
                       and self.bug.effect == "lose-write"
                       and self.bug.trigger <= self.active)
            if dropped:
                self.lost_writes += 1
            else:
                self.state = invoke.get("value")
        elif f == "read":
            out["value"] = self.state
        out["type"] = "ok"
        out["time"] = invoke["time"] + self._latency_ns()
        return out


def _nemesis_gen(genome: Genome):
    """Fault-window boundaries as absolute-time nemesis info ops:
    sleeps between consecutive boundary events, windows free to
    overlap across kinds."""
    events = []
    for w in genome.faults:
        start_f, stop_f = KIND_OPS[w.kind]
        events.append((w.start_s, start_f))
        events.append((w.start_s + w.duration_s, stop_f))
    events.sort(key=lambda e: e[0])
    seq: list = []
    now = 0.0
    for at_s, f in events:
        if at_s > now:
            seq.append(gen_sleep(at_s - now))
            now = at_s
        seq.append({"type": "info", "f": f, "value": None})
    return seq


def _register_client(genome: Genome):
    def rw(test, ctx):
        if gen_rng.random() < 0.5:
            return {"f": "read", "value": None}
        return {"f": "write",
                "value": gen_rng.randrange(VALUE_SPACE)}
    horizon = _horizon_s(genome)
    return gen_time_limit(horizon, gen_stagger(0.1, rw))


def _phased_register_client(genome: Genome):
    def read(test, ctx):
        return {"f": "read", "value": None}

    writes = iter({"f": "write", "value": v + 1}
                  for v in range(PHASED_WRITES))

    def write(test, ctx):
        return next(writes, None)

    return [gen_time_limit(WRITE_AT_S,
                           gen_stagger(READ_STAGGER_S, read)),
            gen_limit(PHASED_WRITES,
                      gen_delay(WRITE_SPACING_S, write)),
            gen_time_limit(PHASED_HORIZON_S - WRITE_AT_S
                           - PHASED_WRITES * WRITE_SPACING_S,
                           gen_stagger(READ_STAGGER_S, read))]


SCENARIOS = {
    "register": {"client": _register_client, "horizon-s": 30.0,
                 "max-ops": 400},
    "phased-register": {"client": _phased_register_client,
                        "horizon-s": PHASED_HORIZON_S,
                        "max-ops": 600},
}


def _horizon_s(genome: Genome) -> float:
    spec = SCENARIOS[genome.workload]
    return float(genome.opts.get("horizon-s", spec["horizon-s"]))


def default_horizon_s(workload: str) -> float:
    return float(SCENARIOS[workload]["horizon-s"])


def default_max_ops(workload: str) -> int:
    return int(SCENARIOS[workload]["max-ops"])


def build(genome: Genome, bug: Bug | str | None = None):
    """(ctx, gen, executor, model) for one genome. `bug` is a BUGS
    name, a Bug, or None for a healthy executor."""
    if isinstance(bug, str):
        bug = BUGS[bug]
    spec = SCENARIOS.get(genome.workload)
    if spec is None:
        raise ValueError(
            f"unknown search workload {genome.workload!r}; "
            f"have {sorted(SCENARIOS)}")
    ctx = gen_context({"concurrency": genome.concurrency})
    g = gen_clients(spec["client"](genome), _nemesis_gen(genome))
    return ctx, g, RegisterExecutor(genome, bug), models.register(0)
