"""The scenario genome and its seeded mutation engine.

A *genome* is the typed, serializable description of one scenario:
generator seed, client concurrency, the workload (a scenario builder
name from scenario.SCENARIOS plus opts), and a nemesis schedule — a
list of fault *windows*, each a (kind, start_s, duration_s) triple
over the fault-kind vocabulary of nemesis/combined.py's packages
(partition / kill / pause / clock). Genomes are plain data: to_dict /
from_dict round-trip through JSON for corpus artifacts and repro
files.

Mutators are deterministic under an explicit `random.Random` — the
driver owns the rng, so a whole search replays from one seed:

  perturb    nudge one window's start or duration
  widen      grow one window
  narrow     shrink one window
  swap-kind  change one window's fault kind
  stack-kind add a DIFFERENT kind over an existing window's span —
             the direct constructor of conjunction faults (pairwise
             overlap is its own coverage dimension in coverage.py)
  add-window / drop-window
  reseed     new generator seed (same schedule, new interleaving)
  concurrency  bump client thread count
  splice     cross two corpus genomes: windows drawn from both parents
             (the conjunction-fault maker: a kill-overlapping parent
             spliced with a partition-overlapping one yields a
             schedule with both)

shrink_reductions() yields the candidate *reductions* of a genome in
decreasing-aggressiveness order; the driver's shrinker greedily
re-simulates them to a minimal reproducing scenario.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Iterator, Optional

FAULT_KINDS = ("partition", "kill", "pause", "clock")

# genome sampling ranges (the "seed universe"): both the guided search
# and the pure-random baseline draw from exactly this space, so an A/B
# at a fixed simulation budget compares search strategies, not spaces
MAX_WINDOWS = 3
MIN_DURATION_S = 0.2
MAX_DURATION_S = 2.0
MIN_CONCURRENCY = 2
MAX_CONCURRENCY = 5
SEED_SPACE = 2 ** 32


@dataclasses.dataclass(frozen=True)
class FaultWindow:
    kind: str
    start_s: float
    duration_s: float

    def to_dict(self) -> dict:
        return {"kind": self.kind, "start-s": round(self.start_s, 6),
                "duration-s": round(self.duration_s, 6)}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultWindow":
        return cls(kind=d["kind"], start_s=float(d["start-s"]),
                   duration_s=float(d["duration-s"]))


@dataclasses.dataclass(frozen=True)
class Genome:
    seed: int
    concurrency: int
    workload: str
    faults: tuple
    opts: dict = dataclasses.field(default_factory=dict)
    max_ops: Optional[int] = None

    def to_dict(self) -> dict:
        return {"seed": self.seed, "concurrency": self.concurrency,
                "workload": self.workload,
                "faults": [w.to_dict() for w in self.faults],
                "opts": dict(self.opts), "max-ops": self.max_ops}

    @classmethod
    def from_dict(cls, d: dict) -> "Genome":
        return cls(seed=int(d["seed"]),
                   concurrency=int(d["concurrency"]),
                   workload=d["workload"],
                   faults=tuple(FaultWindow.from_dict(w)
                                for w in d.get("faults", [])),
                   opts=dict(d.get("opts") or {}),
                   max_ops=d.get("max-ops"))

    def key(self) -> tuple:
        """Canonical identity for corpus dedup."""
        return (self.seed, self.concurrency, self.workload,
                tuple(sorted((w.kind, round(w.start_s, 6),
                              round(w.duration_s, 6))
                             for w in self.faults)),
                tuple(sorted(self.opts.items())), self.max_ops)


def _clamp(x: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, x))


def sample_window(rng: random.Random, horizon_s: float) -> FaultWindow:
    return FaultWindow(
        kind=rng.choice(FAULT_KINDS),
        start_s=round(rng.uniform(0.0, horizon_s), 3),
        duration_s=round(rng.uniform(MIN_DURATION_S, MAX_DURATION_S),
                         3))


def sample_genome(rng: random.Random, workload: str,
                  horizon_s: float, opts: dict | None = None,
                  max_ops: Optional[int] = None) -> Genome:
    """One uniform draw from the seed universe."""
    n = rng.randint(1, MAX_WINDOWS)
    return Genome(
        seed=rng.randrange(SEED_SPACE),
        concurrency=rng.randint(MIN_CONCURRENCY, MAX_CONCURRENCY),
        workload=workload,
        faults=tuple(sample_window(rng, horizon_s) for _ in range(n)),
        opts=dict(opts or {}),
        max_ops=max_ops)


# -- mutators ---------------------------------------------------------------

def _with_window(g: Genome, i: int, w: FaultWindow) -> Genome:
    faults = list(g.faults)
    faults[i] = w
    return dataclasses.replace(g, faults=tuple(faults))


def _perturb(g: Genome, rng: random.Random, horizon_s: float) -> Genome:
    if not g.faults:
        return _add_window(g, rng, horizon_s)
    i = rng.randrange(len(g.faults))
    w = g.faults[i]
    if rng.random() < 0.7:
        # timing nudges are the workhorse: small sigma keeps a
        # coverage-novel window's mutants exploring its neighborhood
        sigma = max(0.05, 0.05 * horizon_s * rng.random())
        w = dataclasses.replace(
            w, start_s=round(
                _clamp(w.start_s + rng.gauss(0.0, sigma), 0.0,
                       horizon_s), 3))
    else:
        w = dataclasses.replace(
            w, duration_s=round(
                _clamp(w.duration_s * rng.uniform(0.5, 2.0),
                       MIN_DURATION_S, MAX_DURATION_S), 3))
    return _with_window(g, i, w)


def _widen(g: Genome, rng: random.Random, horizon_s: float) -> Genome:
    if not g.faults:
        return _add_window(g, rng, horizon_s)
    i = rng.randrange(len(g.faults))
    w = g.faults[i]
    return _with_window(g, i, dataclasses.replace(
        w, duration_s=round(_clamp(w.duration_s * 1.5, MIN_DURATION_S,
                                   MAX_DURATION_S), 3)))


def _narrow(g: Genome, rng: random.Random, horizon_s: float) -> Genome:
    if not g.faults:
        return _add_window(g, rng, horizon_s)
    i = rng.randrange(len(g.faults))
    w = g.faults[i]
    return _with_window(g, i, dataclasses.replace(
        w, duration_s=round(_clamp(w.duration_s * 0.5, MIN_DURATION_S,
                                   MAX_DURATION_S), 3)))


def _swap_kind(g: Genome, rng: random.Random,
               horizon_s: float) -> Genome:
    if not g.faults:
        return _add_window(g, rng, horizon_s)
    i = rng.randrange(len(g.faults))
    w = g.faults[i]
    others = [k for k in FAULT_KINDS if k != w.kind]
    return _with_window(g, i, dataclasses.replace(
        w, kind=rng.choice(others)))


def _stack_kind(g: Genome, rng: random.Random,
                horizon_s: float) -> Genome:
    if not g.faults or len(g.faults) >= MAX_WINDOWS:
        return _perturb(g, rng, horizon_s)
    w = g.faults[rng.randrange(len(g.faults))]
    others = [k for k in FAULT_KINDS if k != w.kind]
    jitter = rng.uniform(-0.25, 0.25) * w.duration_s
    stacked = FaultWindow(
        kind=rng.choice(others),
        start_s=round(_clamp(w.start_s + jitter, 0.0, horizon_s), 3),
        duration_s=w.duration_s)
    return dataclasses.replace(g, faults=g.faults + (stacked,))


def _add_window(g: Genome, rng: random.Random,
                horizon_s: float) -> Genome:
    if len(g.faults) >= MAX_WINDOWS:
        return _perturb(g, rng, horizon_s)
    return dataclasses.replace(
        g, faults=g.faults + (sample_window(rng, horizon_s),))


def _drop_window(g: Genome, rng: random.Random,
                 horizon_s: float) -> Genome:
    if len(g.faults) <= 1:
        return _perturb(g, rng, horizon_s)
    i = rng.randrange(len(g.faults))
    return dataclasses.replace(
        g, faults=g.faults[:i] + g.faults[i + 1:])


def _reseed(g: Genome, rng: random.Random, horizon_s: float) -> Genome:
    return dataclasses.replace(g, seed=rng.randrange(SEED_SPACE))


def _concurrency(g: Genome, rng: random.Random,
                 horizon_s: float) -> Genome:
    c = _clamp(g.concurrency + rng.choice((-1, 1)), MIN_CONCURRENCY,
               MAX_CONCURRENCY)
    return dataclasses.replace(g, concurrency=int(c))


MUTATORS = (
    (_perturb, 5), (_widen, 1), (_narrow, 1), (_swap_kind, 2),
    (_stack_kind, 3), (_add_window, 1), (_drop_window, 1),
    (_reseed, 2), (_concurrency, 1),
)


def splice(a: Genome, b: Genome, rng: random.Random) -> Genome:
    """Cross two genomes: each parent contributes a nonempty subset of
    its windows (capped at MAX_WINDOWS total), scalar fields drawn
    from either parent."""
    pool: list = []
    for parent in (a, b):
        ws = list(parent.faults)
        if ws:
            rng.shuffle(ws)
            pool.extend(ws[:max(1, rng.randint(1, len(ws)))])
    rng.shuffle(pool)
    return Genome(
        seed=(a if rng.random() < 0.5 else b).seed,
        concurrency=(a if rng.random() < 0.5 else b).concurrency,
        workload=a.workload,
        faults=tuple(pool[:MAX_WINDOWS]),
        opts=dict(a.opts),
        max_ops=a.max_ops)


def mutate(g: Genome, rng: random.Random, horizon_s: float,
           corpus: list | None = None) -> Genome:
    """One mutation step. With a corpus of >= 2 genomes, splice fires
    with probability 0.25 (crossing this genome with a random corpus
    mate); otherwise a weighted point mutator."""
    if corpus and len(corpus) >= 2 and rng.random() < 0.25:
        mate = corpus[rng.randrange(len(corpus))]
        out = splice(g, mate, rng)
        if out.key() != g.key():
            return out
    total = sum(w for _, w in MUTATORS)
    pick = rng.random() * total
    for fn, w in MUTATORS:
        pick -= w
        if pick <= 0:
            return fn(g, rng, horizon_s)
    return _perturb(g, rng, horizon_s)


# -- shrinking --------------------------------------------------------------

def shrink_reductions(g: Genome) -> Iterator[Genome]:
    """Candidate reductions, most aggressive first: drop whole
    windows, then halve durations, then coarsen start times, then
    lower concurrency, then trim the op budget. Every candidate is
    strictly 'smaller'; the driver keeps one only if the violation
    still reproduces."""
    if len(g.faults) > 1:
        for i in range(len(g.faults)):
            yield dataclasses.replace(
                g, faults=g.faults[:i] + g.faults[i + 1:])
    for i, w in enumerate(g.faults):
        if w.duration_s > 2 * MIN_DURATION_S:
            yield _with_window(g, i, dataclasses.replace(
                w, duration_s=round(max(MIN_DURATION_S,
                                        w.duration_s / 2), 3)))
    for i, w in enumerate(g.faults):
        coarse = round(w.start_s, 1)
        if coarse != w.start_s:
            yield _with_window(g, i, dataclasses.replace(
                w, start_s=coarse))
        whole = float(int(w.start_s))
        if whole not in (w.start_s, coarse):
            yield _with_window(g, i, dataclasses.replace(
                w, start_s=whole))
    if g.concurrency > MIN_CONCURRENCY:
        yield dataclasses.replace(g, concurrency=MIN_CONCURRENCY)
        if g.concurrency - 1 > MIN_CONCURRENCY:
            yield dataclasses.replace(g, concurrency=g.concurrency - 1)
    if g.max_ops and g.max_ops > 50:
        yield dataclasses.replace(g, max_ops=max(50, g.max_ops // 2))


def genome_size(g: Genome) -> tuple:
    """The (lexicographic) size a shrink minimizes: window count, total
    fault seconds, concurrency, op budget."""
    return (len(g.faults),
            round(sum(w.duration_s for w in g.faults), 6),
            g.concurrency, g.max_ops or 0)
