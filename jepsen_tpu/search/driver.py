"""The generational search loop: simulate, screen, cover, mutate.

One search run is `run_search(SearchConfig)`: per generation, a
population of genomes (random draws, or — guided — mutants of corpus
members) is simulated across a worker pool; every history goes through
the tier-1 screen (`checker/screen.py`) and coverage extraction
(`coverage.py`). Genomes that reach novel coverage bits or raise
screen suspicion enter the corpus; suspicious histories escalate to
the full checker (host mirror, a batched `analysis_tpu_batch` call per
generation, or a live VerificationService); confirmed violations are
shrunk to a minimal reproducing genome by greedily re-simulating
`mutate.shrink_reductions`.

Determinism: the search rng (sampling + mutation) lives on the main
thread and is seeded from the config; each simulation pins its own
thread-local generator stream from the genome's seed; worker results
are consumed in submission order. Same config -> same search,
regardless of worker count.

Every `simulate()` call — including escalation confirms and shrink
steps — counts against the one simulation budget (`max_sims`), so a
guided-vs-random A/B at a fixed budget is an honest comparison.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import os
import random
import time as _time
from typing import Optional

from .. import telemetry
from ..checker.screen import screen_history, should_escalate
from ..generator.simulate import simulate
from . import mutate as mutate_mod
from . import scenario as scenario_mod
from .coverage import CoverageMap, extract_coverage
from .mutate import Genome, genome_size, mutate, sample_genome

_M_SIMS = telemetry.counter(
    "jepsen_tpu_search_simulations_total",
    "Simulated scenario runs, by search strategy",
    ("strategy",))
_M_NEW_BITS = telemetry.counter(
    "jepsen_tpu_search_new_bits_total",
    "Novel coverage bits admitted to the corpus map")
_M_COV = telemetry.gauge(
    "jepsen_tpu_search_coverage_bits",
    "Accumulated corpus coverage bits")
_M_CORPUS = telemetry.gauge(
    "jepsen_tpu_search_corpus_genomes",
    "Genomes in the search corpus")
_M_ESC = telemetry.counter(
    "jepsen_tpu_search_escalations_total",
    "Histories escalated from the tier-1 screen to a full check",
    ("mode",))
_M_VIOL = telemetry.counter(
    "jepsen_tpu_search_violations_total",
    "Confirmed violations found by search")
_M_SHRINK = telemetry.counter(
    "jepsen_tpu_search_shrink_steps_total",
    "Shrink candidate re-simulations")
_M_GEN_S = telemetry.histogram(
    "jepsen_tpu_search_generation_seconds",
    "Wall-clock seconds per search generation")

# guided-mode fresh-blood fraction: even with a corpus, this share of
# each generation is uniform random draws so the search never inbreeds
FRESH_FRACTION = 0.2
# share of each guided generation spent bursting mutants of the
# PREVIOUS generation's admissions (the AFL energy idea): a genome
# that just reached novel coverage is one mutation from its neighbors,
# and spreading its mutants over later generations dissipates that
BURST_FRACTION = 0.5


@dataclasses.dataclass
class SearchConfig:
    workload: str = "register"
    generations: int = 10
    population: int = 50
    seed: int = 45100
    workers: int = 4
    strategy: str = "guided"          # guided | random
    escalate: str = "none"            # none | host | batch | service
    bug: Optional[str] = None         # a scenario.BUGS name, or None
    max_sims: Optional[int] = None    # total simulate() budget
    max_ops: Optional[int] = None     # per-run history bound
    horizon_s: Optional[float] = None
    sample: float = 0.0               # clean-history audit fraction
    host_budget_s: float = 2.0
    stop_on_violation: bool = True
    store_dir: Optional[str] = None
    resume_dir: Optional[str] = None  # prior store_dir to continue from

    def resolved_horizon_s(self) -> float:
        if self.horizon_s is not None:
            return float(self.horizon_s)
        return scenario_mod.default_horizon_s(self.workload)

    def resolved_max_ops(self) -> int:
        if self.max_ops is not None:
            return int(self.max_ops)
        return scenario_mod.default_max_ops(self.workload)


def evaluate_genome(genome: Genome, bug=None):
    """Simulate one genome and screen its history. Returns
    (history, Coverage, screen-verdict, model)."""
    ctx, g, ex, model = scenario_mod.build(genome, bug)
    hist = simulate(ctx, g, ex.complete, seed=genome.seed,
                    max_ops=genome.max_ops)
    return hist, extract_coverage(hist), \
        screen_history(model, hist), model


class _Search:
    def __init__(self, cfg: SearchConfig):
        self.cfg = cfg
        self.rng = random.Random(cfg.seed)
        self.cmap = CoverageMap()
        # (genome, novel-bit-count) — admission order
        self.corpus: list = []
        self._keys: set = set()
        self.curve: list = []
        self.sims = 0
        self.escalations = 0
        self.shrink_steps = 0
        self.violations: list = []
        self.generations_run = 0
        self._service = None
        # genomes admitted during the previous generation (burst pool)
        self._fresh: list = []
        if cfg.resume_dir:
            self._resume(cfg.resume_dir)

    def _resume(self, d: str) -> None:
        """Reload a prior run's artifacts (search.json + coverage.bin)
        and continue: the corpus, coverage map, and counters pick up
        where the stored run left off, and the restored simulation
        count keeps charging against max_sims — so a resumed search
        spends only the REMAINING budget, not a fresh one.

        The mutation rng restarts from cfg.seed (its walk position is
        not persisted): a resumed search is deterministic given
        (artifact, config), not a replay of the unsplit run."""
        with open(os.path.join(d, "search.json")) as f:
            art = json.load(f)
        stored = (art.get("config") or {}).get("workload")
        if stored is not None and stored != self.cfg.workload:
            raise ValueError(
                f"resume workload mismatch: {d} was searched with "
                f"workload {stored!r}, config says "
                f"{self.cfg.workload!r}")
        cov_bin = os.path.join(d, "coverage.bin")
        if os.path.exists(cov_bin):
            with open(cov_bin, "rb") as f:
                self.cmap = CoverageMap.decode(f.read())
        for entry in art.get("corpus") or []:
            g = Genome.from_dict(entry["genome"])
            if g.key() in self._keys:
                continue
            self._keys.add(g.key())
            self.corpus.append(
                (g, int(entry.get("new-bits", 0) or 0)))
        self.sims = int(art.get("simulations", 0) or 0)
        self.escalations = int(art.get("escalations", 0) or 0)
        self.shrink_steps = int(art.get("shrink-steps", 0) or 0)
        self.generations_run = int(art.get("generations-run", 0)
                                   or 0)
        self.curve = list(art.get("coverage-curve") or [])
        self.violations = list(art.get("violations") or [])

    # -- budget ------------------------------------------------------------

    def budget_left(self) -> bool:
        return self.cfg.max_sims is None \
            or self.sims < self.cfg.max_sims

    def _count_sim(self) -> None:
        self.sims += 1
        _M_SIMS.labels(strategy=self.cfg.strategy).inc()

    # -- population --------------------------------------------------------

    def _prepare(self, genome: Genome) -> Genome:
        if genome.max_ops is None:
            genome = dataclasses.replace(
                genome, max_ops=self.cfg.resolved_max_ops())
        return genome

    def _next_batch(self) -> list:
        cfg, horizon = self.cfg, self.cfg.resolved_horizon_s()
        out = []
        for _ in range(cfg.population):
            r = self.rng.random()
            if cfg.strategy == "random" or not self.corpus \
                    or r < FRESH_FRACTION:
                g = sample_genome(self.rng, cfg.workload, horizon,
                                  max_ops=cfg.resolved_max_ops())
            else:
                if self._fresh \
                        and r < FRESH_FRACTION + BURST_FRACTION:
                    parent = self._fresh[
                        self.rng.randrange(len(self._fresh))]
                else:
                    # recency-weighted draw over the whole corpus: a
                    # genome admitted late earned bits the earlier
                    # corpus never reached — uniform selection would
                    # let the first (bit-rich but generic) admissions
                    # dominate the mutation budget
                    n = len(self.corpus)
                    i = self.rng.choices(range(n),
                                         weights=range(1, n + 1))[0]
                    parent = self.corpus[i][0]
                mates = [c[0] for c in self.corpus]
                g = mutate(parent, self.rng, horizon, mates)
            out.append(self._prepare(g))
        return out

    # -- escalation --------------------------------------------------------

    def _confirm_host(self, model, hist) -> dict:
        from ..checker.linear import analysis_host
        return analysis_host(model, hist,
                             budget_s=self.cfg.host_budget_s)

    def _confirm_batch(self, model, hists: list) -> list:
        from ..checker.wgl import analysis_tpu_batch
        return analysis_tpu_batch(model, hists,
                                  budget_s=self.cfg.host_budget_s)

    def _confirm_service(self, model, hist, tag: str) -> dict:
        """Round-trip one history through an in-process verification
        service stream (the online path a live cluster would use)."""
        from ..service import (VerificationService, model_spec,
                               targets_spec)
        from ..checker.linear import Linearizable
        if self._service is None:
            self._service = VerificationService()
        spec = targets_spec({
            "checker": Linearizable(model),
            "tier": "screen"})
        if not spec:
            spec = {"screen-linear": {"kind": "screen",
                                      "model": model_spec(model)}}
        name = f"search-{tag}"
        self._service.admit(name, spec)
        for op in hist:
            self._service.offer(name, op)
        self._service.seal(name)
        res = self._service.result(name, timeout_s=60.0)
        for sub in res.values():
            if isinstance(sub, dict) and sub.get("valid?") is False:
                return sub
        for sub in res.values():
            if isinstance(sub, dict) and "valid?" in sub:
                return sub
        return {"valid?": "unknown", "analyzer": "service"}

    def _escalate(self, model, hist, tag: str) -> dict | None:
        """Inline escalation for host/service modes; batch defers to
        generation end. None when mode is none/batch."""
        mode = self.cfg.escalate
        if mode == "host":
            return self._confirm_host(model, hist)
        if mode == "service":
            return self._confirm_service(model, hist, tag)
        return None

    # -- shrinking ---------------------------------------------------------

    def _reproduces(self, genome: Genome) -> bool:
        self._count_sim()
        _M_SHRINK.inc()
        self.shrink_steps += 1
        _, _, screen, _ = evaluate_genome(genome, self.cfg.bug)
        return screen["violation-count"] > 0

    def _shrink(self, genome: Genome) -> Genome:
        """Greedy minimization: accept any reduction that still
        reproduces and is no larger; restart the reduction walk from
        each accepted genome. The screen verdict is the reproduction
        oracle — it is sound (flags only definite violations), and at
        shrink sizes it is orders cheaper than the full search."""
        cur = genome
        improved = True
        while improved and self.budget_left():
            improved = False
            for cand in mutate_mod.shrink_reductions(cur):
                if not self.budget_left():
                    break
                if cand.key() == cur.key() \
                        or genome_size(cand) > genome_size(cur):
                    continue
                if self._reproduces(cand):
                    cur = cand
                    improved = True
                    break
        return cur

    # -- violations --------------------------------------------------------

    def _record_violation(self, genome: Genome, screen: dict,
                          confirm: dict | None) -> None:
        _M_VIOL.inc()
        found_at = self.sims
        minimized = self._shrink(genome)
        self.violations.append({
            "genome": genome.to_dict(),
            "minimized": minimized.to_dict(),
            "screen-violations": screen.get("violations", []),
            "confirmed-by": (confirm or {}).get("analyzer",
                                                "tier1-screen"),
            "found-at-sim": found_at,
            "shrink-steps": self.shrink_steps,
        })

    # -- the loop ----------------------------------------------------------

    def _evaluate_batch(self, batch: list) -> list:
        bug = self.cfg.bug
        if self.cfg.workers <= 1:
            return [evaluate_genome(g, bug) for g in batch]
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.cfg.workers) as pool:
            futs = [pool.submit(evaluate_genome, g, bug)
                    for g in batch]
            return [f.result() for f in futs]

    def run(self) -> dict:
        cfg = self.cfg
        t_start = _time.monotonic()
        try:
            # cumulative cap: a resumed search (resume_dir) has its
            # prior generations restored, so it runs only the
            # remainder of the configured budget
            while self.generations_run < cfg.generations:
                if not self.budget_left():
                    break
                with _M_GEN_S.time():
                    done = self._generation()
                self.generations_run += 1
                self.curve.append(len(self.cmap))
                _M_COV.set(len(self.cmap))
                _M_CORPUS.set(len(self.corpus))
                if done:
                    break
        finally:
            if self._service is not None:
                try:
                    self._service.drain()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    pass
        result = {
            "workload": cfg.workload,
            "strategy": cfg.strategy,
            "seed": cfg.seed,
            "bug": cfg.bug,
            "generations-run": self.generations_run,
            "simulations": self.sims,
            "coverage-bits": len(self.cmap),
            "coverage-curve": self.curve,
            "coverage-digest": self.cmap.digest(),
            "corpus-size": len(self.corpus),
            "escalations": self.escalations,
            "shrink-steps": self.shrink_steps,
            "violations": self.violations,
            "found": bool(self.violations),
            "wall-s": round(_time.monotonic() - t_start, 3),
        }
        if cfg.store_dir:
            self._store(result)
        return result

    def _generation(self) -> bool:
        """One generation. True when the search should stop (first
        violation confirmed and stop_on_violation)."""
        cfg = self.cfg
        batch = self._next_batch()
        if cfg.max_sims is not None:
            batch = batch[:max(0, cfg.max_sims - self.sims)]
        if not batch:
            return False
        results = self._evaluate_batch(batch)
        fresh: list = []
        deferred: list = []     # (genome, screen, hist) for batch mode
        for genome, (hist, cov, screen, model) in zip(batch, results):
            self._count_sim()
            novel = self.cmap.add(cov)
            if novel:
                _M_NEW_BITS.inc(len(novel))
            suspicious = screen["suspicion"] > 0
            if (novel or suspicious) \
                    and genome.key() not in self._keys:
                self._keys.add(genome.key())
                self.corpus.append((genome, len(novel)))
                fresh.append(genome)
            if screen["violation-count"] > 0:
                # the screen's verdict is definite; escalation (if
                # configured) corroborates with the full checker
                confirm = None
                if cfg.escalate in ("host", "service"):
                    self.escalations += 1
                    _M_ESC.labels(mode=cfg.escalate).inc()
                    confirm = self._escalate(model, hist,
                                             f"v{self.sims}")
                self._record_violation(genome, screen, confirm)
                if cfg.stop_on_violation:
                    return True
                continue
            esc, _why = should_escalate(screen, sample=cfg.sample,
                                        key=genome.seed)
            if esc and cfg.escalate != "none":
                self.escalations += 1
                _M_ESC.labels(mode=cfg.escalate).inc()
                if cfg.escalate == "batch":
                    deferred.append((genome, screen, hist, model))
                else:
                    confirm = self._escalate(model, hist,
                                             f"e{self.sims}")
                    if confirm is not None \
                            and confirm.get("valid?") is False:
                        self._record_violation(genome, screen,
                                               confirm)
                        if cfg.stop_on_violation:
                            return True
        self._fresh = fresh
        if deferred:
            model = deferred[0][3]
            verdicts = self._confirm_batch(
                model, [d[2] for d in deferred])
            for (genome, screen, _h, _m), verdict in zip(deferred,
                                                         verdicts):
                if verdict.get("valid?") is False:
                    self._record_violation(genome, screen, verdict)
                    if cfg.stop_on_violation:
                        return True
        return False

    # -- artifacts ---------------------------------------------------------

    def _store(self, result: dict) -> None:
        d = self.cfg.store_dir
        os.makedirs(d, exist_ok=True)
        artifact = dict(result)
        artifact["config"] = {
            f.name: getattr(self.cfg, f.name)
            for f in dataclasses.fields(self.cfg)}
        artifact["corpus"] = [
            {"genome": g.to_dict(), "new-bits": n}
            for g, n in self.corpus]
        with open(os.path.join(d, "search.json"), "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
        with open(os.path.join(d, "coverage.bin"), "wb") as f:
            f.write(self.cmap.encode())


def run_search(cfg: SearchConfig) -> dict:
    """Run one coverage-guided (or pure-random) scenario search to its
    generation/simulation budget. Returns the result summary (the
    store-dir artifact carries the full corpus)."""
    return _Search(cfg).run()
