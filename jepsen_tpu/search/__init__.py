"""Coverage-guided scenario search: fuzzing over generator/nemesis
schedules.

The suite menus are a static catalog; this subsystem treats scenario
generation as a feedback loop (ROADMAP "Coverage-guided scenario
search", PAPERS.md: *AccelSync*, arXiv 2605.07881): simulate a typed
scenario *genome* (seed, concurrency, nemesis fault windows, workload
opts) on the deterministic simulator, extract *schedule-coverage*
signals from the history, and mutate genomes that reach novel
synchronization patterns toward the still-uncovered ones. Tier-1
screens triage every simulated history; suspicion escalates to the
full WGL search (host mirror, a batched device call, or a live
verification service); found violations are shrunk to a minimal
reproducing scenario by re-simulating genome reductions.

Layout:

  coverage.py   schedule-coverage signals + corpus-wide coverage map
  mutate.py     the scenario genome, seeded mutators, shrink reductions
  scenario.py   genome -> generator + synthetic fault-aware executor
  driver.py     the generational search loop, worker pool, escalation,
                shrinking, artifacts; CLI `jepsen-tpu search`

See doc/search.md for the genome grammar, the coverage-signal
definitions, and the novelty/corpus semantics.
"""

from .coverage import Coverage, CoverageMap, extract_coverage  # noqa: F401
from .driver import SearchConfig, run_search  # noqa: F401
# NB: mutate() itself is not re-exported — the bare name would shadow
# the jepsen_tpu.search.mutate submodule attribute
from .mutate import FaultWindow, Genome, sample_genome  # noqa: F401

__all__ = ["Coverage", "CoverageMap", "extract_coverage",
           "FaultWindow", "Genome", "sample_genome",
           "SearchConfig", "run_search"]
