"""Schedule-coverage signals over simulated histories.

What "coverage" means for a distributed-systems scenario is *which
synchronization patterns the schedule actually exercised* (AccelSync's
synchronization-coverage insight, arXiv 2605.07881), not which code
ran. Three signal families, each reduced to a set of stable 64-bit
coverage *bits*:

  overlap   fault-window x operation-phase bitmap: for every client op
            that completed, which nemesis fault kinds were active over
            its in-flight interval, classified per kind as
            'throughout' (active at invoke and completion),
            'ended-during', 'began-during', or 'within' (the window
            opened AND closed while the op was in flight). Ops in
            flight while >= 2 kinds were simultaneously active also
            set a pairwise (kind, kind, f) bit — conjunction faults
            are their own coverage dimension. Per (kind, f) the COUNT
            of overlapped ops also sets cumulative log2-bucket bits,
            so a schedule overlapping more of a rare op phase is
            coverage-novel over one that grazed it — the gradient the
            search climbs toward narrow phases.
  kgram     interleaving digests: hashed k-grams (k=3) of each
            process's (f, type) op ordering, bucketed into a bounded
            space. Process ids never enter the hash, so digests are
            stable under op-id renumbering.
  adj       nemesis/op adjacency: for each nemesis event, the f of the
            last client event before it and the first after it.

Bits are BLAKE2b-64 hashes of canonical key tuples — no registry, no
ordering dependence, stable across runs, processes, and platforms. A
corpus-wide CoverageMap accumulates bits; novelty is a set difference,
and the whole map has a stable binary encoding (sorted u64 big-endian)
so two encodings are byte-comparable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import Iterable

# nemesis f -> (fault kind, activates?) — the fault-kind vocabulary of
# nemesis/combined.py's packages (partition / kill / pause / clock);
# tests/test_search.py pins this table against the packages' perf sets
# so a new package can't silently fall out of coverage.
START_F = {"kill": "kill", "start-partition": "partition",
           "pause": "pause", "bump-clock": "clock",
           "strobe-clock": "clock"}
STOP_F = {"start": "kill", "stop-partition": "partition",
          "resume": "pause", "reset-clock": "clock"}

KGRAM_K = 3
KGRAM_SPACE = 4096  # k-gram buckets; bounded so digests stay compact

_SEP = b"\x1f"


def _bit(*parts) -> int:
    """One stable 64-bit coverage bit from a canonical key tuple."""
    payload = _SEP.join(str(p).encode("utf-8") for p in parts)
    return int.from_bytes(
        hashlib.blake2b(payload, digest_size=8).digest(), "big")


def _stable_bucket(parts: tuple, space: int) -> int:
    return _bit(*parts) % space


@dataclasses.dataclass(frozen=True)
class Coverage:
    """The coverage a single history reached: the bit set plus
    per-family counts (for reporting — the bits alone are what the
    corpus accumulates)."""
    bits: frozenset
    overlap_bits: int
    kgram_bits: int
    adjacency_bits: int

    def __len__(self):
        return len(self.bits)


def _overlap_class(at_invoke: bool, at_complete: bool) -> str:
    if at_invoke and at_complete:
        return "throughout"
    if at_invoke:
        return "ended-during"
    if at_complete:
        return "began-during"
    return "within"


def extract_coverage(history: Iterable[dict]) -> Coverage:
    """One pass over a simulated history (journal order: invokes and
    completions interleaved, nemesis info ops included) -> Coverage.

    Fault activity is derived from the history itself — the nemesis
    ops' f names (START_F/STOP_F) — so coverage needs no side channel
    from the scenario that produced the history."""
    bits: set = set()
    n_overlap = n_kgram = n_adj = 0

    active: set = set()            # fault kinds active right now
    # process -> (active-at-invoke snapshot, kinds seen active while
    # in flight, f)
    open_ops: dict = {}
    # per-process (f, type) orderings for the k-gram digests
    per_process: dict = {}
    last_client_f: str | None = None
    # nemesis events waiting for their first following client op
    pending_after: list = []
    # (kind, opf) -> overlapped-op count, for the cumulative buckets
    ov_counts: dict = {}

    for op in history:
        proc = op.get("process")
        f = op.get("f")
        typ = op.get("type")
        if proc == "nemesis":
            kind = START_F.get(f)
            if kind is not None:
                if kind not in active:
                    active.add(kind)
                    for st in open_ops.values():
                        st[1].add(kind)
            elif f in STOP_F:
                active.discard(STOP_F[f])
            # adjacency: client op just before, and (deferred) the
            # first client op after this nemesis event
            if typ == "invoke" or typ == "info":
                if last_client_f is not None:
                    b = _bit("adj", f, last_client_f, "before")
                    if b not in bits:
                        bits.add(b)
                        n_adj += 1
                pending_after.append(f)
            continue
        if not isinstance(proc, int):
            continue
        # client op
        if f is not None:
            last_client_f = f
            for nf in pending_after:
                b = _bit("adj", nf, f, "after")
                if b not in bits:
                    bits.add(b)
                    n_adj += 1
            pending_after = []
        seq = per_process.setdefault(proc, [])
        seq.append((f, typ))
        if len(seq) >= KGRAM_K:
            gram = tuple(seq[-KGRAM_K:])
            b = _bit("kg", _stable_bucket(("kg",) + gram, KGRAM_SPACE))
            if b not in bits:
                bits.add(b)
                n_kgram += 1
        if typ == "invoke":
            open_ops[proc] = (frozenset(active), set(active), f)
        elif typ in ("ok", "fail", "info"):
            st = open_ops.pop(proc, None)
            if st is None:
                continue
            at_invoke, seen, inv_f = st
            opf = inv_f if inv_f is not None else f
            for kind in seen:
                klass = _overlap_class(kind in at_invoke,
                                       kind in active)
                b = _bit("ov", kind, opf, klass)
                if b not in bits:
                    bits.add(b)
                    n_overlap += 1
                ov_counts[(kind, opf)] = \
                    ov_counts.get((kind, opf), 0) + 1
            if len(seen) >= 2:
                kinds = sorted(seen)
                for i, k1 in enumerate(kinds):
                    for k2 in kinds[i + 1:]:
                        b = _bit("ov2", k1, k2, opf)
                        if b not in bits:
                            bits.add(b)
                            n_overlap += 1
    # cumulative count buckets: n overlapped ops of (kind, f) sets
    # every bucket up to floor(log2 n) — a deeper overlap of the same
    # phase strictly adds bits
    for (kind, opf), n in ov_counts.items():
        for bucket in range(n.bit_length()):
            b = _bit("ovn", kind, opf, bucket)
            if b not in bits:
                bits.add(b)
                n_overlap += 1
    return Coverage(bits=frozenset(bits), overlap_bits=n_overlap,
                    kgram_bits=n_kgram, adjacency_bits=n_adj)


def _site_class(site) -> str:
    """'stream-chunk/w0' -> 'stream-chunk': coverage is over the site
    *kind*, not the per-stream instance name."""
    return str(site).split("/", 1)[0]


def _site_stream(site) -> str | None:
    s = str(site)
    return s.split("/", 1)[1] if "/" in s else None


def extract_chaos_coverage(probes: Iterable[dict],
                           actions: Iterable[str] = ()) -> Coverage:
    """Chaos-run coverage: one pass over the pipeline's probe stream
    (``_platform.probe``) plus the genome's scripted lifecycle actions
    -> Coverage, reusing the search corpus machinery. Families:

      cx    (fault kind x fault-site class x stream-lifecycle-state)
            transitions — WHERE in the stream's life each fault
            landed, the tentpole's recovery-path gradient
      cx2   fault-during-replay conjunction: a fault/inject probe
            inside an open replay-begin..replay-end window on the
            same site — the path single-fault tests never reach
      cxn   recovery-depth log2 buckets per site class (retry k sets
            every bucket up to floor(log2 k): deeper ladders strictly
            add bits)
      ck    k-gram digests of the probe event sequence (bounded
            buckets, same scheme as the history k-grams)
      ca    scripted-action structure: each lifecycle action and each
            adjacent action pair in schedule order

    Probes are emitted synchronously from the worker thread that runs
    the stream, so their order — and therefore the bit set — is
    deterministic for a fixed genome."""
    bits: set = set()
    n_transition = n_kgram = n_action = 0

    states: dict = {}        # stream name -> last lifecycle state
    replay_open: dict = {}   # site class -> replay window open?
    retries: dict = {}       # site class -> deepest retry seen
    seq: list = []           # (event, detail) ordering for k-grams

    def _add(b, fam):
        nonlocal n_transition, n_kgram, n_action
        if b not in bits:
            bits.add(b)
            if fam == "k":
                n_kgram += 1
            elif fam == "a":
                n_action += 1
            else:
                n_transition += 1

    for p in probes:
        ev = p.get("event")
        site = p.get("site", "")
        sc = _site_class(site)
        if ev == "lifecycle":
            states[p.get("stream")] = p.get("state")
            seq.append((ev, p.get("state")))
        elif ev == "replay-begin":
            replay_open[sc] = True
            seq.append((ev, sc))
        elif ev == "replay-end":
            replay_open[sc] = False
            seq.append((ev, sc))
        elif ev in ("fault", "inject", "corrupt"):
            kind = p.get("kind") or ("bitflip" if ev == "corrupt"
                                     else None)
            state = states.get(_site_stream(site), "admitted")
            _add(_bit("cx", kind, sc, state), "t")
            if replay_open.get(sc):
                _add(_bit("cx2", kind, sc), "t")
            if ev == "fault":
                try:
                    r = int(p.get("retry") or 0)
                except (TypeError, ValueError):
                    r = 0
                retries[sc] = max(retries.get(sc, 0), r)
            seq.append((ev, kind))
        else:
            seq.append((ev, sc))
        if len(seq) >= KGRAM_K:
            gram = tuple(seq[-KGRAM_K:])
            _add(_bit("ck", _stable_bucket(("ck",) + gram,
                                           KGRAM_SPACE)), "k")
    for sc, deepest in retries.items():
        for bucket in range(int(deepest).bit_length()):
            _add(_bit("cxn", sc, bucket), "t")
    prev = None
    for a in actions:
        _add(_bit("ca", a), "a")
        if prev is not None:
            _add(_bit("ca", prev, a), "a")
        prev = a
    return Coverage(bits=frozenset(bits), overlap_bits=n_transition,
                    kgram_bits=n_kgram, adjacency_bits=n_action)


class CoverageMap:
    """Corpus-wide accumulated coverage. add() returns the NOVEL bits
    (set difference against everything accumulated so far); encode()
    is a stable binary form (sorted u64, big-endian) so two maps — or
    the same map across runs/platforms — compare byte-for-byte."""

    def __init__(self, bits: Iterable[int] = ()):
        self._bits: set = set(bits)

    def __len__(self) -> int:
        return len(self._bits)

    def __contains__(self, bit: int) -> bool:
        return bit in self._bits

    @property
    def bits(self) -> frozenset:
        return frozenset(self._bits)

    def novel(self, cov: Coverage | Iterable[int]) -> frozenset:
        bits = cov.bits if isinstance(cov, Coverage) else frozenset(cov)
        return bits - self._bits

    def add(self, cov: Coverage | Iterable[int]) -> frozenset:
        new = self.novel(cov)
        self._bits |= new
        return new

    def encode(self) -> bytes:
        return b"".join(struct.pack(">Q", b)
                        for b in sorted(self._bits))

    @classmethod
    def decode(cls, blob: bytes) -> "CoverageMap":
        if len(blob) % 8:
            raise ValueError(f"coverage encoding length {len(blob)} "
                             "is not a multiple of 8")
        return cls(struct.unpack(">Q", blob[i:i + 8])[0]
                   for i in range(0, len(blob), 8))

    def digest(self) -> str:
        """Hex digest of the stable encoding — the one-line identity
        of a whole corpus's coverage (artifacts, logs, tests)."""
        return hashlib.blake2b(self.encode(),
                               digest_size=16).hexdigest()
