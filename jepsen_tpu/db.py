"""DB protocols: database lifecycle on remote nodes.

Reference: `jepsen/src/jepsen/db.clj` — the `DB` setup/teardown protocol
(:11-13) and optional capability protocols `Process` start/kill (:18-24),
`Pause` (:26-29), `Primary` (:31-38), `LogFiles` (:40-41); the `tcpdump`
wrapper DB (:49-115); and `cycle!` — concurrent teardown+setup across
nodes with 3 retries on setup failure (:117-158).

Capabilities are optional-protocol style: a DB advertises a capability by
implementing its methods; `supports(db, "pause")` reflects on that, the
way the reference uses `(satisfies? Pause db)`.
"""

from __future__ import annotations

import logging
import time as _time

from . import control
from .control import util as cu

log = logging.getLogger(__name__)


class DB:
    def setup(self, test: dict, node: str) -> None:
        """Set up the database on this node."""

    def teardown(self, test: dict, node: str) -> None:
        """Tear down the database on this node."""


class Process:
    """Optional: starting and killing a DB's processes (`db.clj:18-24`)."""

    def start(self, test: dict, node: str):
        raise NotImplementedError

    def kill(self, test: dict, node: str):
        raise NotImplementedError


class Pause:
    """Optional: pausing/resuming a DB's processes (`db.clj:26-29`)."""

    def pause(self, test: dict, node: str):
        raise NotImplementedError

    def resume(self, test: dict, node: str):
        raise NotImplementedError


class Primary:
    """Optional: databases with a notion of primary nodes
    (`db.clj:31-38`)."""

    def primaries(self, test: dict) -> list[str]:
        """Nodes that currently think they're primaries (best-effort)."""
        raise NotImplementedError

    def setup_primary(self, test: dict, node: str) -> None:
        """One-time setup on a single node."""


class LogFiles:
    """Optional: per-node log files to snarf (`db.clj:40-41`)."""

    def log_files(self, test: dict, node: str) -> list[str]:
        return []


_CAPABILITIES = {
    "process": ("start", "kill"),
    "pause": ("pause", "resume"),
    "primary": ("primaries",),
    "log-files": ("log_files",),
}


def supports(db, capability: str) -> bool:
    """Does this DB implement an optional capability protocol? The
    reference's `(satisfies? Pause db)` reflection (`db.clj:121-158`,
    `nemesis/combined.clj:141-160` use it to pick nemesis menus)."""
    return all(callable(getattr(db, m, None))
               for m in _CAPABILITIES[capability])


class Noop(DB):
    """Does nothing (`db.clj:43-47`)."""


noop = Noop()


class SetupFailed(Exception):
    """Raise from DB.setup to request a teardown+retry cycle
    (`db.clj:125-126` :type ::setup-failed)."""


class Tcpdump(DB, LogFiles):
    """Runs a tcpdump capture from setup to teardown (`db.clj:49-115`).

    Options: ports (list of ints), filter (extra pcap filter string),
    clients_only (restrict to control-node traffic; needs control_ip).
    """

    DIR = "/tmp/jepsen/tcpdump"

    def __init__(self, ports=(), filter: str | None = None,
                 clients_only: bool = False,
                 control_ip: str | None = None):
        self.ports = list(ports)
        self.filter = filter
        self.clients_only = clients_only
        self.control_ip = control_ip
        self.logfile = f"{self.DIR}/log"
        self.capfile = f"{self.DIR}/tcpdump"
        self.pidfile = f"{self.DIR}/pid"

    def _filter_str(self) -> str:
        parts = []
        if self.ports:
            parts.append(" and ".join(f"port {p}" for p in self.ports))
        if self.clients_only and self.control_ip:
            parts.append(f"host {self.control_ip}")
        if self.filter:
            parts.append(self.filter)
        return " and ".join(parts)

    def setup(self, test, node):
        with control.su():
            control.exec_("mkdir", "-p", self.DIR)
            cu.start_daemon(
                {"logfile": self.logfile, "pidfile": self.pidfile,
                 "chdir": self.DIR},
                "/usr/sbin/tcpdump",
                "-w", self.capfile, "-s", "65535", "-B", "16384",
                # SIGINT should flush the capture, but in practice leaves
                # it half-finished — so don't buffer at all (`db.clj:87-92`)
                "-U", self._filter_str())

    def teardown(self, test, node):
        with control.su():
            pid = cu.meh(lambda: control.exec_("cat", self.pidfile))
            if pid:
                cu.meh(lambda: control.exec_("kill", "-s", "INT", pid))
                while cu.meh(lambda: control.exec_("ps", "-p", pid)) \
                        is not None:
                    log.info("Waiting for tcpdump %s to exit", pid)
                    _time.sleep(0.05)
            cu.stop_daemon(self.pidfile, cmd="tcpdump")
            control.exec_("rm", "-rf", self.DIR)

    def log_files(self, test, node):
        return [self.logfile, self.capfile]


def tcpdump(opts: dict | None = None) -> Tcpdump:
    return Tcpdump(**(opts or {}))


CYCLE_TRIES = 3


def cycle(test: dict) -> None:
    """Tear down then set up the DB on all nodes concurrently; on
    SetupFailed, tear down and retry up to CYCLE_TRIES times
    (`db.clj:117-158`)."""
    db = test["db"]
    tries = CYCLE_TRIES
    while True:
        log.info("Tearing down DB")
        control.on_nodes(test, db.teardown)
        log.info("Setting up DB")
        try:
            control.on_nodes(test, db.setup)
            if supports(db, "primary"):
                primary = test["nodes"][0]
                log.info("Setting up primary %s", primary)
                control.on_nodes(test, db.setup_primary, nodes=[primary])
            return
        except SetupFailed as e:
            tries -= 1
            if tries <= 0:
                raise
            log.warning("Unable to set up database; retrying... (%s)", e)
