#!/usr/bin/env python
"""Headline benchmark: linearizability verification throughput on TPU.

The reference's CPU Knossos checker needs a 32 GB JVM heap
(`jepsen/project.clj:38`) and times out (~1 h) on 10k-op histories
(BASELINE.md north-star). This benchmark checks a 10k-op concurrent CAS
register history with the TPU WGL kernel and reports verified ops/sec.

vs_baseline is the speedup over the CPU-Knossos north-star baseline of
10_000 ops / 3600 s (the 1 h timeout).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N}
"""

import json
import sys
import time

N_OPS = 10_000
CONCURRENCY = 5
BASELINE_OPS_PER_SEC = N_OPS / 3600.0  # CPU knossos: 1 h timeout on 10k ops


def main() -> int:
    from jepsen_tpu import models
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.wgl import analysis_tpu

    hist = synth.register_history(N_OPS, concurrency=CONCURRENCY, values=5,
                                  crash_rate=0.0005, seed=45100)
    model = models.cas_register()

    # First call compiles (~20-40 s on TPU); benchmark the steady state.
    a = analysis_tpu(model, hist, budget_s=420)
    assert a["valid?"] is True, f"benchmark history must verify: {a}"

    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        a = analysis_tpu(model, hist)
        best = min(best, time.monotonic() - t0)
    assert a["valid?"] is True

    value = N_OPS / best
    print(json.dumps({
        "metric": ("linearizability verification throughput, 10k-op "
                   "concurrent CAS-register history (WGL frontier search)"),
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(value / BASELINE_OPS_PER_SEC, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
