#!/usr/bin/env python
"""Headline benchmark: history verification throughput on TPU.

Covers every BASELINE.md config plus the adversarial headline proof:

  * headline metric (round-over-round comparable): WGL linearizability
    throughput on the 10k-op concurrent CAS-register history.
  * extra.adversarial_10k: a 10k-op history with front-loaded crashed
    writes (the shape the reference calls out at `checker.clj:213-216`
    — ":info ops hold slots forever", hours/32 GB on CPU knossos).
    The host oracle is *measured* against a budget on this exact
    history; when it blows the budget, its total runtime is projected
    linearly from the ops it processed (a lower bound: per-op cost is
    nondecreasing in this shape), capped at the 1 h north star. The
    reported speedup is projected-host-time / device-time — derived
    from measurement, never an assumed timeout.
  * extra.configs: BASELINE configs 1-5 —
      1 tutorial-scale 200-op register (CPU parity),
      2 zookeeper-shape 2k-op WGL register,
      3 cockroach-shape 10k-txn elle rw-register,
      4 hazelcast-shape 50k ops sharded over the device mesh,
      5 tidb-shape 100k-txn elle list-append (north star < 300 s).

Resilience: the TPU backend is reached through a relay that can wedge
mid-session, so the orchestrator (default mode) runs every section in
its OWN short-lived subprocess (`--section NAME`), with a preflight
probe first and a shared persistent compilation cache.  Per-section
budgets are SOFT deadlines for the round: a section that hangs is
terminated and marked {"ok": false, "timeout": true} in
extra.sections, the run continues, and over-budget-only rounds still
exit 0 (a whole-run soft budget additionally guarantees the final
line lands before any driver-level kill) — the driver always gets one
parseable JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N,
   "extra": {...}}
"""

import json
import os
import subprocess
import sys
import time

from jepsen_tpu._platform import honor_platform_env

honor_platform_env()


def _note(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# ---- backend preflight -------------------------------------------------
#
# The axon TPU backend reaches the chip through a loopback relay that can
# wedge (init hangs forever, r04 shipped no TPU number because of exactly
# this).  Before touching jax in-process, probe device init in a SHORT
# subprocess with a timeout — killing a probe at init stage is safe; what
# must never be killed is a process mid-device-op.  Bounded retries with
# backoff; on persistent failure emit one diagnosable JSON line instead
# of a stack trace.

PREFLIGHT_ATTEMPTS = int(os.environ.get("BENCH_PREFLIGHT_ATTEMPTS", "4"))
PREFLIGHT_TIMEOUT_S = float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT_S", "75"))
PREFLIGHT_BACKOFF_S = float(os.environ.get("BENCH_PREFLIGHT_BACKOFF_S", "45"))

_PROBE_SRC = (
    # sitecustomize may pre-bake the axon platform over any caller-set
    # JAX_PLATFORMS (config beats env once the plugin registers);
    # re-assert the env choice (same dance as
    # _platform.honor_platform_env) so CPU probes stay hermetic and an
    # invalid platform genuinely fails instead of reaching the chip.
    # The probe must DISPATCH, not just init: the relay can wedge at the
    # dispatch level while init still succeeds (r05: an elle compile
    # hung while jax.devices() answered), so an init-only probe would
    # green-light a backend that swallows real work.
    "import os, jax; "
    "env = os.environ.get('JAX_PLATFORMS'); "
    "env and jax.config.update('jax_platforms', env); "
    "ds = jax.devices(); "
    "import jax.numpy as jnp; "
    "y = (jnp.ones((8, 128)) @ jnp.ones((128, 128))).block_until_ready(); "
    "assert float(y[0, 0]) == 128.0; "
    "print(ds[0].platform, len(ds), getattr(ds[0], 'device_kind', '?'))"
)


def preflight_backend():
    """Probe jax backend init in a subprocess; retry with backoff.

    Returns (ok, info-dict).  info carries per-attempt outcomes so a
    failure artifact is diagnosable (which attempt, timeout vs error,
    last stderr tail).
    """
    attempts = []
    for i in range(PREFLIGHT_ATTEMPTS):
        t0 = time.monotonic()
        timed_out = False
        try:
            p = subprocess.run(
                [sys.executable, "-u", "-c", _PROBE_SRC],
                capture_output=True, text=True,
                timeout=PREFLIGHT_TIMEOUT_S)
            dt = round(time.monotonic() - t0, 1)
            try:
                if p.returncode == 0 and p.stdout.strip():
                    # device_kind may contain spaces ("TPU v4"): split
                    # from the front, at most twice
                    platform, n, kind = (
                        p.stdout.strip().splitlines()[-1].split(None, 2))
                    attempts.append({"attempt": i + 1, "ok": True,
                                     "seconds": dt})
                    return True, {"platform": platform,
                                  "n_devices": int(n),
                                  "device_kind": kind,
                                  "attempts": attempts}
            except ValueError:
                # unexpected probe output must become a recorded failed
                # attempt, never an uncaught stack trace
                pass
            attempts.append({
                "attempt": i + 1, "ok": False, "seconds": dt,
                "rc": p.returncode,
                "stdout_tail": p.stdout.strip()[-200:],
                "stderr_tail": p.stderr.strip().splitlines()[-1][:200]
                if p.stderr.strip() else ""})
        except subprocess.TimeoutExpired:
            timed_out = True
            attempts.append({"attempt": i + 1, "ok": False,
                             "seconds": round(time.monotonic() - t0, 1),
                             "timeout": True})
        if i + 1 < PREFLIGHT_ATTEMPTS:
            if timed_out:
                # the wedged-relay signature: give the relay a quiet
                # recovery window before reconnecting
                _note(f"preflight attempt {i + 1} timed out; retrying "
                      f"in {PREFLIGHT_BACKOFF_S:.0f}s")
                time.sleep(PREFLIGHT_BACKOFF_S)
            else:
                # deterministic immediate failure: retrying after a
                # backoff would just reproduce it slower
                _note(f"preflight attempt {i + 1} failed fast; "
                      f"retrying immediately")
    return False, {"attempts": attempts}


def _env_int(name: str, default: int) -> int:
    """Parse an int env override; a malformed value falls back to the
    default with a stderr note — module import must never traceback,
    or the one-parseable-JSON-line contract dies before main()."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        _note(f"ignoring malformed {name}={raw!r}; using {default}")
        return default


# benchmark scales; env-overridable so orchestrator tests and smoke
# runs stay fast (the driver's real runs never set these).  Overridden
# scales are stamped into the output JSON (see main()) so a leaked
# smoke-env artifact can never pass for a real 10k/100k run.
DEFAULT_N_OPS, DEFAULT_N_TXNS = 10_000, 100_000
N_OPS = _env_int("BENCH_N_OPS", DEFAULT_N_OPS)
CONCURRENCY = 5
BASELINE_OPS_PER_SEC = N_OPS / 3600.0  # CPU knossos: 1 h timeout on 10k ops
N_TXNS = _env_int("BENCH_N_TXNS", DEFAULT_N_TXNS)
BASELINE_TXNS_PER_SEC = N_TXNS / 300.0  # north star: solved < 300 s
# Host budget for the adversarial blowout measurement.  The north star
# is "CPU knossos times out at 1 h" (checker.clj:213-216); a short
# budget artificially floors the provable speedup at budget/tpu_time,
# so give the host long enough that the ops-processed projection can
# document a >=30x floor.  Env-overridable so smoke runs stay quick.
HOST_BUDGET_S = float(os.environ.get("BENCH_HOST_BUDGET_S", "300"))
# Whole-run soft budget.  Per-section budgets bound one wedged relay;
# this bounds the SUM, so a round where several sections crawl still
# emits its final JSON line well before any driver-level kill (the r05
# failure mode: one hung config -> whole round rc=1/timeout, zero
# numbers recorded).  0 = derive from the section table.
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "0"))


def _best_of(fn, n=3):
    best = float("inf")
    out = None
    for _ in range(n):
        t0 = time.monotonic()
        out = fn()
        best = min(best, time.monotonic() - t0)
    return best, out


# ---- sections ----------------------------------------------------------
#
# Each section is one short-lived device process (never kill a process
# mid-device-op: a kill can wedge the relay for the whole session; the
# orchestrator only ever times out whole sections and then stops
# scheduling device work).

def _model():
    from jepsen_tpu import models
    return models.cas_register()


def section_headline():
    """Easy 10k-op history (comparable to r01/r02)."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    hist = synth.register_history(N_OPS, concurrency=CONCURRENCY, values=5,
                                  crash_rate=0.0005, seed=45100)
    a = analysis_tpu(model, hist, budget_s=420)   # compile + first run
    assert a["valid?"] is True, f"benchmark history must verify: {a}"
    best, a = _best_of(lambda: analysis_tpu(model, hist))
    assert a["valid?"] is True
    return {"value": round(N_OPS / best, 1),
            "wgl_best_s": round(best, 3),
            "wgl_engine": a["analyzer"],
            "wgl_dedup": a.get("dedup")}


def section_adversarial():
    """Measured host blowout vs exact device on the front-loaded
    crashed-writes shape."""
    from jepsen_tpu.checker import UNKNOWN, synth
    from jepsen_tpu.checker.linear import analysis_host
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    # 8 crashed writes (r03/r04 used 7): each front-loaded crash
    # permanently doubles the host's per-completion configuration set,
    # so k=8 pushes the measured host projection past the 1 h north
    # star's evidence bar (>= 600 s) while the dense device table only
    # doubles (S * 2^P ~ 82k entries, far under DENSE_TABLE_CAP).
    adv = synth.adversarial_register_history(
        N_OPS, concurrency=6, crashed_writes=8, front_load=True,
        seed=45100)
    analysis_tpu(model, adv, budget_s=420)   # warm: compile this shape
    t0 = time.monotonic()
    ta = analysis_tpu(model, adv, budget_s=420)
    adv_tpu_s = time.monotonic() - t0

    t0 = time.monotonic()
    host = analysis_host(model, adv, budget_s=HOST_BUDGET_S)
    adv_host_s = time.monotonic() - t0
    # Honest speedup: when the host blows its budget, extrapolate its
    # total runtime linearly from the ops it processed. That is a
    # LOWER bound — per-op cost in this front-loaded shape is
    # nondecreasing (the crashed writes pend forever, so the closure
    # per event never shrinks) — so the reported speedup is what we
    # can actually prove, not an assumed timeout.
    host_decided = host["valid?"] != UNKNOWN
    host_info = {"budget_s": HOST_BUDGET_S,
                 "completed_in_budget": host_decided,
                 "seconds": round(adv_host_s, 1),
                 "verdict": str(host["valid?"])}
    speedup = None
    if host_decided:
        # both engines decided: a verdict disagreement is a checker
        # bug, not a benchmark win — surface it instead of a speedup
        if str(host["valid?"]) == str(ta["valid?"]):
            speedup = round(adv_host_s / adv_tpu_s, 1)
        else:
            host_info["verdict_divergence"] = True
    elif ta["valid?"] is True and host.get("ops-processed"):
        done_ops = host["ops-processed"]
        projected = adv_host_s * N_OPS / done_ops
        host_info["ops_processed"] = done_ops
        host_info["projected_seconds_lower_bound"] = round(
            min(projected, 3600.0), 1)
        host_info["projection"] = (
            "measured_seconds * total_ops / ops_processed; linear in "
            "ops, a lower bound because per-op cost is nondecreasing "
            "here")
        speedup = round(min(projected, 3600.0) / adv_tpu_s, 1)
    return {"adversarial_10k": {
        "shape": "concurrency 6, 8 crashed writes front-loaded",
        "tpu": {"seconds": round(adv_tpu_s, 2),
                "verdict": str(ta["valid?"]),
                "engine": ta["analyzer"],
                "dedup": ta.get("dedup"),
                "ops_per_s": round(N_OPS / adv_tpu_s, 1),
                "configs_tracked": ta.get("max-frontier")},
        "host": host_info,
        "speedup_lower_bound": speedup,
    }}


def section_streaming():
    """Online verification tail latency vs offline full-check on the
    10k adversarial shape, plus the early-abort demonstration on an
    injected-violation history (checker/streaming.py).

    Offline, analyze pays the FULL check after the run; online, the
    device search advances while ops arrive and finalize() only pays
    the unchecked tail — the number that matters is stream_tail_s
    against offline_s. The feed loop here pushes ops as fast as the
    pipeline accepts them (a worst case: a real run's op arrival is
    slower, hiding even more of the device time)."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.streaming import WglStream
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    adv = synth.adversarial_register_history(
        N_OPS, concurrency=6, crashed_writes=8, front_load=True,
        seed=45100)
    analysis_tpu(model, adv, budget_s=420)   # compile
    t0 = time.monotonic()
    off = analysis_tpu(model, adv, budget_s=420)
    offline_s = time.monotonic() - t0
    assert off["valid?"] is True, f"adversarial must verify: {off}"

    # chunk size scales with the history so smoke-scale runs still
    # exercise multi-chunk pipelining (~8 chunks); real 10k runs use
    # the default 1024
    chunk = max(64, min(1024, N_OPS // 8))

    # dense streaming: the register's state range is declared up front
    # (initial NIL=-1, written values 0..4) so the exact reachable-set
    # table exists before the first op arrives
    def stream_once():
        s = WglStream(model, chunk_entries=chunk, engine="dense",
                      state_range=(-1, 4), concurrency_hint=12)
        t_feed = time.monotonic()
        for op in adv.ops:
            s.feed(op)
        feed_s = time.monotonic() - t_feed
        t_tail = time.monotonic()
        r = s.finish()
        return r, feed_s, time.monotonic() - t_tail

    stream_once()                            # compile
    r, feed_s, tail_s = stream_once()
    assert r["valid?"] is True, f"stream verdict diverged: {r}"

    # early abort: a violation injected mid-history is detected while
    # ops are still arriving; the remaining run time would be saved
    plain = synth.register_history(N_OPS, concurrency=CONCURRENCY,
                                   values=5, crash_rate=0.0, seed=45100)
    bad = synth.corrupt(plain, seed=11)
    bad_at = next(i for i, (a, b) in enumerate(zip(plain.ops, bad.ops))
                  if a != b)
    s = WglStream(model, chunk_entries=chunk,
                  concurrency_hint=CONCURRENCY)
    fed = 0
    for op in bad.ops:
        s.feed(op)
        fed += 1
        if s.violation:
            break
    rb = s.finish()
    assert rb["valid?"] is False, f"violation must be caught: {rb}"
    return {"streaming": {
        "shape": "adversarial 10k (conc 6, 8 crashed writes, "
                 "front-loaded), dense engine",
        "dedup": r.get("dedup"),
        "offline_s": round(offline_s, 3),
        "stream_feed_s": round(feed_s, 3),
        "stream_tail_s": round(tail_s, 3),
        "tail_vs_offline_speedup": round(offline_s / max(tail_s, 1e-4),
                                         1),
        "chunks": r["chunks"],
        "verdict": str(r["valid?"]),
        "early_abort": {
            "violation_injected_at_op": bad_at,
            "detected_after_ops_fed": fed,
            "total_history_ops": len(bad.ops),
            "run_fraction_saved": round(1 - fed / len(bad.ops), 3),
            "verdict": str(rb["valid?"]),
        }}}


def section_recovery():
    """Checker fault tolerance: checkpoint-cadence overhead (K sweep)
    and recovery latency vs a cold re-check, on the adversarial 10k
    history (checker/streaming.py carry checkpoints + the recovery
    ladder; doc/robustness.md).

    Two numbers matter: what the periodic carry round-trip costs an
    UNFAULTED stream (cadence_sweep: K=0 disables checkpointing), and
    what a mid-stream device-lost fault costs to heal — resuming from
    the last checkpoint (replays ≤K chunks) vs replaying the whole
    steps log cold (K=0) vs abandoning the stream for a full offline
    re-check, the pre-recovery behavior."""
    from jepsen_tpu import _platform as plat
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.streaming import WglStream
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    adv = synth.adversarial_register_history(
        N_OPS, concurrency=6, crashed_writes=8, front_load=True,
        seed=45100)
    chunk = max(64, min(1024, N_OPS // 8))

    def stream_once(checkpoint_every, hook=None):
        plat.fault_hook = hook
        plat.reset_fault_injection()
        try:
            s = WglStream(model, chunk_entries=chunk, engine="dense",
                          state_range=(-1, 4), concurrency_hint=12,
                          checkpoint_every=checkpoint_every)
            t0 = time.monotonic()
            for op in adv.ops:
                s.feed(op)
            r = s.finish()
            return s, r, time.monotonic() - t0
        finally:
            plat.fault_hook = None

    def one_shot(kind, at):
        state = {"n": 0}

        def hook(site):
            if site == "stream-chunk":
                state["n"] += 1
                if state["n"] == at:
                    raise plat.InjectedFault(kind, site, at)
        return hook

    stream_once(0)                           # compile
    sweep, base_s = {}, None
    for k in (0, 8, 4, 2, 1):
        s, r, dt = stream_once(k)
        assert r["valid?"] is True, f"verdict diverged at K={k}: {r}"
        if k == 0:
            base_s = dt
        sweep[str(k)] = {
            "seconds": round(dt, 3),
            "overhead_vs_uncheckpointed": round(dt / base_s - 1, 4)}
    total_chunks = s._chunks

    # heal a device-lost fault at the stream's midpoint three ways
    fault_at = max(2, total_chunks // 2)
    _, r2, ckpt_s = stream_once(2, one_shot("device-lost", fault_at))
    assert r2["valid?"] is True and r2["recovered"]["retries"] == 1, \
        f"checkpointed recovery diverged: {r2}"
    _, r0, cold_s = stream_once(0, one_shot("device-lost", fault_at))
    assert r0["valid?"] is True \
        and r0["recovered"]["resumed-from-chunk"] == 0, \
        f"cold recovery diverged: {r0}"
    t0 = time.monotonic()
    off = analysis_tpu(model, adv, budget_s=420)
    offline_s = time.monotonic() - t0
    assert off["valid?"] is True

    return {"recovery": {
        "shape": "adversarial 10k (conc 6, 8 crashed writes, "
                 "front-loaded), dense engine",
        "chunks": total_chunks,
        "cadence_sweep": sweep,
        "fault_at_chunk": fault_at,
        "recover_from_checkpoint_s": round(ckpt_s, 3),
        "recover_cold_replay_s": round(cold_s, 3),
        "offline_recheck_s": round(offline_s, 3),
        "recovery_vs_recheck_speedup": round(
            (base_s + offline_s) / max(ckpt_s, 1e-4), 1),
        "resumed_from_chunk": r2["recovered"]["resumed-from-chunk"],
    }}


def section_tiered():
    """Tiered always-on verification (checker/screen.py + ABFT
    attestation): tier-1 screening throughput on clean vs anomalous
    histories, escalation rates over a labeled matrix (with the
    no-false-negative check at the screen boundary: the screen must
    escalate every history the full checker rejects), and the ABFT
    checksum overhead vs unguarded kernels."""
    import os as _os

    from jepsen_tpu.checker import screen, synth
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()

    # -- labeled matrix: clean + anomalous registers ------------------
    # smoke-scale runs (orchestrator tests, BENCH_N_OPS overridden
    # down) keep this section DEVICE-FREE: screen throughput and
    # escalation rates only — the full-checker cross-validation and
    # the ABFT A/B each cost cold kernel compiles that would dominate
    # a smoke round, and both are pinned directly in tier-1
    # (tests/test_screen.py's no-false-negative matrix,
    # tests/test_attest.py's bitflip matrix)
    smoke = N_OPS < DEFAULT_N_OPS // 4
    n = max(N_OPS // 10, 300)
    seeds = (13, 21) if smoke else (13, 21, 7, 45100)
    clean = [synth.register_history(n, concurrency=CONCURRENCY,
                                    values=5, seed=s)
             for s in seeds]
    anomalous = [synth.corrupt(h, seed=i + 3)
                 for i, h in enumerate(clean)]

    # -- tier-1 screening throughput ----------------------------------
    # same shape as the headline section (crash_rate matters: the
    # default 2% pins ~N/50 slots forever, forcing the P=64 sort
    # family — the adversarial section's job, not this one's)
    big = synth.register_history(N_OPS, concurrency=CONCURRENCY,
                                 values=5, crash_rate=0.0005,
                                 seed=45100)
    best_clean, sc_big = _best_of(
        lambda: screen.screen_history(model, big))
    big_bad = synth.corrupt(big, seed=5)
    best_bad, sc_bad = _best_of(
        lambda: screen.screen_history(model, big_bad))
    assert sc_big["valid?"] is True and sc_bad["valid?"] is False

    # -- escalation rate + screen-boundary soundness ------------------
    matrix = [(h, True) for h in clean] + [(h, False) for h in anomalous]
    escalations = {"clean": 0, "anomalous": 0}
    false_negatives: int | None = 0 if not smoke else None
    for h, is_clean in matrix:
        sc = screen.screen_history(model, h)
        price = screen.price_escalation(model, h)
        esc, _why = screen.should_escalate(
            sc, sample=screen.DEFAULT_SAMPLE,
            cost=price["cost"] if price else None)
        escalations["clean" if is_clean else "anomalous"] += bool(esc)
        if smoke:
            continue
        # explain=False: the matrix needs verdicts, not blame
        # certificates — the host explain re-search on each anomalous
        # member would dominate the section
        full = analysis_tpu(model, h, budget_s=120, explain=False)
        if full["valid?"] is False and not esc:
            false_negatives += 1
    assert not false_negatives, \
        f"screen passed {false_negatives} histories the full checker " \
        f"rejects"

    # -- ABFT checksum overhead vs unguarded kernels ------------------
    # flip the env gate (resolved outside the kernel caches) and use a
    # chunked run so the carry-digest boundary cost is included
    abft: dict = {"skipped": "smoke scale"}
    if not smoke:
        prev = _os.environ.get("JEPSEN_TPU_ATTEST")
        try:
            _os.environ["JEPSEN_TPU_ATTEST"] = "1"
            analysis_tpu(model, big, chunk_entries=1024)   # warm
            best_on, a_on = _best_of(
                lambda: analysis_tpu(model, big, chunk_entries=1024))
            assert a_on.get("attested"), "guarded run must attest"
            _os.environ["JEPSEN_TPU_ATTEST"] = "0"
            analysis_tpu(model, big, chunk_entries=1024)   # warm
            best_off, a_off = _best_of(
                lambda: analysis_tpu(model, big, chunk_entries=1024))
            assert a_on["valid?"] == a_off["valid?"] is True
            abft = {
                "guarded_s": round(best_on, 3),
                "unguarded_s": round(best_off, 3),
                "overhead_pct": round(
                    100.0 * (best_on - best_off)
                    / max(best_off, 1e-6), 2),
                "attested": a_on.get("attested"),
                "engine": a_on["analyzer"],
            }
        finally:
            if prev is None:
                _os.environ.pop("JEPSEN_TPU_ATTEST", None)
            else:
                _os.environ["JEPSEN_TPU_ATTEST"] = prev

    return {"tiered": {
        "screen_ops_per_s_clean": round(N_OPS / max(best_clean, 1e-6),
                                        1),
        "screen_ops_per_s_anomalous": round(
            N_OPS / max(best_bad, 1e-6), 1),
        "matrix": {"clean": len(clean), "anomalous": len(anomalous),
                   "ops_each": n},
        "escalation_rate_clean": round(
            escalations["clean"] / len(clean), 3),
        "escalation_rate_anomalous": round(
            escalations["anomalous"] / len(anomalous), 3),
        "screen_false_negatives": false_negatives,
        "sample_fraction": screen.DEFAULT_SAMPLE,
        "abft": abft}}


def section_config1():
    """Tutorial-scale 200-op register (CPU parity target)."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.linear import analysis_host
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    h1 = synth.register_history(200, concurrency=5, values=5,
                                crash_rate=0.01, seed=45100)
    analysis_tpu(model, h1, budget_s=420)   # compile
    t1_host, r1h = _best_of(lambda: analysis_host(model, h1))
    t1_tpu, r1t = _best_of(lambda: analysis_tpu(model, h1))
    assert r1h["valid?"] is True and r1t["valid?"] is True
    return {"1_register_200": {
        "host_s": round(t1_host, 4), "tpu_s": round(t1_tpu, 4),
        "target": "parity", "tpu_over_host": round(t1_host / t1_tpu, 2)}}


def section_config2():
    """zookeeper-shape 2k-op WGL register."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.linear import analysis_host
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    h2 = synth.register_history(2000, concurrency=5, values=5,
                                crash_rate=0.005, seed=45100)
    analysis_tpu(model, h2, budget_s=420)   # compile
    t2_host, r2h = _best_of(lambda: analysis_host(model, h2), 1)
    t2_tpu, r2t = _best_of(lambda: analysis_tpu(model, h2))
    assert r2h["valid?"] is True and r2t["valid?"] is True
    return {"2_register_wgl_2k": {
        "host_s": round(t2_host, 3), "tpu_s": round(t2_tpu, 3),
        "ops_per_s": round(2000 / t2_tpu, 1),
        "speedup_vs_host": round(t2_host / t2_tpu, 2)}}


def section_config3():
    """cockroach-shape 10k-txn elle rw-register."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.elle import wr

    h3 = synth.wr_history(10_000, seed=45100)
    wr.check(h3)   # compile
    t3, r3 = _best_of(lambda: wr.check(h3))
    assert r3["valid?"] is True, f"wr bench history must verify: {r3}"
    return {"3_elle_wr_10k": {
        "seconds": round(t3, 2), "txns_per_s": round(10_000 / t3, 1)}}


def section_addgraphs():
    """config3's 10k-txn elle rw-register re-checked with the realtime
    + process precedence graphs unioned in (checker/elle/graphs.py) —
    the additional-graphs tax on the perf trajectory.  The history is
    strict-serializable by construction, so the union graph condenses
    to trivial SCCs host-side and the section stays meaningful without
    the chip (anomalous SCCs would take the stacked-level device
    path)."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.elle import wr

    graphs = ("realtime", "process")
    h = synth.wr_history(10_000, seed=45100)
    wr.check(h, additional_graphs=graphs)   # compile / warm caches
    t, r = _best_of(lambda: wr.check(h, additional_graphs=graphs))
    assert r["valid?"] is True, \
        f"addgraphs bench history must verify: {r}"
    return {"addgraphs_wr_10k": {
        "seconds": round(t, 2), "txns_per_s": round(10_000 / t, 1),
        "graphs": list(graphs)}}


def section_config4():
    """hazelcast-shape 50k ops sharded over the device mesh."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.wgl import check_batch_sharded

    model = _model()
    keys = 100
    per_key = [synth.register_history(500, concurrency=4, values=5,
                                      crash_rate=0.005, seed=1000 + i)
               for i in range(keys)]
    check_batch_sharded(model, per_key, slots=16)   # compile
    t0 = time.monotonic()
    all_ok, per_ok, info = check_batch_sharded(model, per_key, slots=16,
                                               return_info=True)
    t4 = time.monotonic() - t0
    assert all_ok and per_ok.all()
    return {"4_sharded_50k": {
        "keys": keys, "seconds": round(t4, 2),
        "ops_per_s": round(keys * 500 / t4, 1),
        # which engine each slot-bucketed dispatch group actually ran
        # (family + dedup variant) — the tunable the dedup cost model
        # controls on this headline shape
        "engine_groups": info["groups"],
        "dedup_engines": sorted({g["dedup"] for g in info["groups"]})}}


def section_config5():
    """tidb-shape 100k-txn elle list-append (best-of damps the ±10%
    run-to-run variance that read as a "regression" in r03 — the
    checker was byte-identical across those rounds).

    A valid history's elle check is host-only (the sparse SCC
    condensation short-circuits before any device work), so the
    throughput number never depends on the relay.  The injected-cycle
    run is this bench's ONE elle device dispatch — and in r05 it was
    the dispatch a wedged relay swallowed, hanging the whole section
    for its full 900 s budget.  It therefore runs in a nested
    TERM-on-timeout subprocess; on timeout the anomaly verdict is
    recomputed with the exact host classifier
    (`JEPSEN_TPU_ELLE_HOST=1`) so the section always completes."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.elle import list_append

    eh = synth.append_history(N_TXNS, seed=45100)
    list_append.check(eh)   # warm host caches
    elle_s, er = _best_of(lambda: list_append.check(eh))
    assert er["valid?"] is True, f"elle bench history must verify: {er}"
    elle_rate = N_TXNS / elle_s

    classify_path = "device"
    elle_bad_s = None
    # degraded host-only run (orchestrator preflight failed): don't
    # even spawn the device child
    forced_host = os.environ.get("JEPSEN_TPU_ELLE_HOST") == "1"
    child = info = None
    if not forced_host:
        child, info = _run_section_child("config5bad", timeout_s=240)
    if child is not None:
        elle_bad_s = child["seconds"]
    else:
        if forced_host:
            classify_path = "host (forced by JEPSEN_TPU_ELLE_HOST)"
        else:
            # a wedged relay (timeout, or an UNAVAILABLE init error)
            # falls back to the exact host classifier; a genuine child
            # failure — the anomaly assertion tripping means the DEVICE
            # CLASSIFIER REGRESSED — must fail the section loudly, not
            # be papered over with a host verdict
            if (not info["timed_out"]
                    and "AssertionError" in info["stderr_tail"]):
                raise RuntimeError(
                    f"config5bad device classifier failed its anomaly "
                    f"assertion: {info['stderr_tail']}")
            classify_path = ("host-fallback (device dispatch lost/timed "
                             "out)" if info["timed_out"] else
                             f"host-fallback (device init failed: "
                             f"{info['stderr_tail'][:120]})")
            os.environ["JEPSEN_TPU_ELLE_HOST"] = "1"
        bad = synth.inject_append_cycles(eh, 64, "G1c")
        t0 = time.monotonic()
        br = list_append.check(bad)
        elle_bad_s = round(time.monotonic() - t0, 2)
        assert br["valid?"] is False and "G1c" in br["anomaly-types"]
    return {"5_elle_append_100k": {
        "seconds": round(elle_s, 2), "txns_per_s": round(elle_rate, 1),
        "vs_baseline": round(elle_rate / BASELINE_TXNS_PER_SEC, 1),
        "with_64_injected_cycles_s": elle_bad_s,
        "injected_cycle_classify": classify_path}}


def section_config5bad():
    """The injected-cycle leg of config5: 64 G1c cycles over the 100k
    history, anomaly SCCs classified on device (the bench's only elle
    device dispatch — isolated so a lost dispatch costs a bounded
    timeout, not the section)."""
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.elle import list_append

    eh = synth.append_history(N_TXNS, seed=45100)
    bad = synth.inject_append_cycles(eh, 64, "G1c")
    list_append.check(bad)   # compile the classifier
    t0 = time.monotonic()
    br = list_append.check(bad)
    dt = time.monotonic() - t0
    assert br["valid?"] is False and "G1c" in br["anomaly-types"]
    return {"seconds": round(dt, 2)}


def section_service():
    """The persistent verification service (jepsen_tpu/service.py):
    aggregate checking throughput vs concurrent stream count, the
    isolation overhead of serving a stream next to siblings vs a solo
    OnlineChecker-style stream, and drain-and-resume latency vs an
    uninterrupted run.

    Device-light by design: the per-stream kernels are the streaming
    section's; what this section measures is the SERVING layer —
    queueing, the cost-model budget, checkpoint/manifest round-trips."""
    import json as _json
    import shutil as _shutil
    import tempfile as _tempfile
    import threading as _threading

    from jepsen_tpu import service as _service, store as _store
    from jepsen_tpu.checker import streaming as _streaming, synth

    model = _model()
    n = max(N_OPS // 20, 400)
    chunk = 64
    slots = 8
    frontier = 128

    def jops(h):
        return [_json.loads(_json.dumps(op,
                                        default=_store._json_default))
                for op in h.ops]

    def spec():
        return {"linear": {
            "kind": "wgl", "model": _service.model_spec(model),
            "chunk-entries": chunk, "slots": slots, "engine": "sort",
            "frontier": frontier, "checkpoint-every": 2}}

    def solo(ops):
        s = _streaming.WglStream(model, chunk_entries=chunk,
                                 slots=slots, frontier=frontier,
                                 checkpoint_every=2)
        t0 = time.monotonic()
        for op in ops:
            s.feed(op)
        r = s.finish()
        assert r["valid?"] is True, r
        return time.monotonic() - t0

    smoke = N_OPS < DEFAULT_N_OPS // 4
    counts = (1, 2, 4) if smoke else (1, 2, 4, 8)
    hists = {i: jops(synth.register_history(
        n, concurrency=3, values=5, seed=300 + i))
        for i in range(max(counts))}
    solo(hists[0])                   # warm every kernel shape
    solo_s = solo(hists[0])

    # -- aggregate throughput vs stream count -------------------------
    scaling = {}
    iso_overhead = None
    for m in counts:
        svc = _service.VerificationService()
        for i in range(m):
            svc.admit(f"s{i}", spec())

        per_stream: dict = {}

        def feed(i):
            t0 = time.monotonic()
            for op in hists[i]:
                svc.offer(f"s{i}", op)
            svc.seal(f"s{i}")
            r = svc.result(f"s{i}", timeout_s=600)
            # a shed/quarantined stream returns fast with no verdict
            # and would fake great throughput numbers
            assert r.get("linear", {}).get("valid?") is True, \
                f"stream s{i} lost its verdict: {r}"
            per_stream[i] = time.monotonic() - t0

        t0 = time.monotonic()
        ths = [_threading.Thread(target=feed, args=(i,))
               for i in range(m)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        wall = time.monotonic() - t0
        total_ops = sum(len(hists[i]) for i in range(m))
        scaling[m] = {"wall_s": round(wall, 3),
                      "agg_ops_per_s": round(total_ops / wall, 1)}
        if m == max(counts):
            # isolation overhead: one stream's latency served among
            # (m-1) siblings vs the solo OnlineChecker-style stream
            iso_overhead = round(per_stream[0] / max(solo_s, 1e-4), 2)

    # -- drain-and-resume latency -------------------------------------
    tmp = _tempfile.mkdtemp(prefix="bench-service-")
    try:
        run_dir = os.path.join(tmp, "bench", "t0")
        os.makedirs(run_dir)
        with open(os.path.join(run_dir, "journal.jsonl"), "w") as fh:
            for op in hists[0]:
                fh.write(_json.dumps(
                    op, default=_store._json_default) + "\n")
        import gzip as _gzip
        with _gzip.open(os.path.join(run_dir, "history.jsonl.gz"),
                        "wt") as fh:
            for op in hists[0]:
                fh.write(_json.dumps(
                    op, default=_store._json_default) + "\n")
        svc = _service.VerificationService()
        svc.admit("t0", spec(), store_dir=run_dir)
        for op in hists[0][:len(hists[0]) // 2]:
            svc.offer("t0", op)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            w = svc.workers["t0"]
            if w.targets["linear"]._ckpt is not None and w.q.empty():
                break
            time.sleep(0.01)
        t0 = time.monotonic()
        svc.drain()
        drain_s = time.monotonic() - t0
        t0 = time.monotonic()
        svc2 = _service.VerificationService()
        name = svc2.resume(run_dir)
        r = svc2.result(name, timeout_s=600)
        resume_s = time.monotonic() - t0
        assert r["linear"]["valid?"] is True, r
        svc2.stop()
    finally:
        _shutil.rmtree(tmp, ignore_errors=True)

    return {"service": {
        "shape": f"{n}-op register streams (conc 3, chunk {chunk}, "
                 f"F {frontier})",
        "solo_stream_s": round(solo_s, 3),
        "scaling": scaling,
        "isolation_overhead_x": iso_overhead,
        "drain_s": round(drain_s, 3),
        "resume_to_verdict_s": round(resume_s, 3),
        "uninterrupted_s": round(solo_s, 3),
    }}


def section_failover():
    """Crash-consistency latency (jepsen_tpu/service.py): the
    detect -> fence -> promote -> first-verdict path of a Standby
    taking over a dead primary's store, and the session protocol's
    reconnect-storm throughput (forced socket drops mid-stream) vs an
    undisturbed connection.

    Device-light like the service section: the kernels are the
    streaming section's; what this measures is the failover control
    plane (health probes, epoch fencing, checkpoint recovery) and the
    wire protocol's replay cost."""
    import json as _json
    import shutil as _shutil
    import socket as _socket
    import tempfile as _tempfile
    import threading as _threading

    from jepsen_tpu import service as _service, store as _store
    from jepsen_tpu.checker import synth

    model = _model()
    n = max(N_OPS // 20, 400)
    chunk = 64
    slots = 8
    frontier = 128

    def jops(h):
        return [_json.loads(_json.dumps(op,
                                        default=_store._json_default))
                for op in h.ops]

    def spec():
        return {"linear": {
            "kind": "wgl", "model": _service.model_spec(model),
            "chunk-entries": chunk, "slots": slots, "engine": "sort",
            "frontier": frontier, "checkpoint-every": 2}}

    ops = jops(synth.register_history(n, concurrency=3, values=5,
                                      seed=412))
    tmp = _tempfile.mkdtemp(prefix="bench-failover-")
    out: dict = {"shape": f"{n}-op register stream (conc 3, "
                          f"chunk {chunk}, F {frontier})"}
    try:
        # -- standby promotion: detect -> fence -> promote -> verdict
        root = os.path.join(tmp, "store")
        run_dir = os.path.join(root, "bench", "t0")
        os.makedirs(run_dir)
        with open(os.path.join(run_dir, "journal.jsonl"), "w") as fh:
            for op in ops:
                fh.write(_json.dumps(
                    op, default=_store._json_default) + "\n")
        import gzip as _gzip
        with _gzip.open(os.path.join(run_dir, "history.jsonl.gz"),
                        "wt") as fh:
            for op in ops:
                fh.write(_json.dumps(
                    op, default=_store._json_default) + "\n")
        primary = _service.VerificationService()
        primary.claim_store(root)
        addr = primary.serve("127.0.0.1:0")
        primary.admit("bench/t0", spec(), store_dir=run_dir)
        for op in ops[:3 * len(ops) // 4]:
            primary.offer("bench/t0", op)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            m = _store.load_service_resume(run_dir)
            if m and any("carry" in c
                         for c in m.get("checkpoints", {}).values()):
                break
            time.sleep(0.02)
        else:
            raise RuntimeError("no durable checkpoint before kill")
        standby_svc = _service.VerificationService()
        sb = _service.Standby(standby_svc, addr, root,
                              bind="127.0.0.1:0", poll_s=0.05,
                              failures=2)
        th = _threading.Thread(target=sb.run, daemon=True)
        th.start()
        t_kill = time.monotonic()
        primary.stop()           # the "SIGKILL": acceptor + workers die
        assert sb.promoted.wait(180.0), "standby never promoted"
        promote_s = time.monotonic() - t_kill
        res_path = os.path.join(run_dir, _store.STREAMED_RESULTS_FILE)
        while time.monotonic() - t_kill < 300:
            if os.path.exists(res_path):
                try:
                    with open(res_path) as fh:
                        r = _json.load(fh)
                    break
                except ValueError:
                    pass             # mid-write
            time.sleep(0.02)
        else:
            raise RuntimeError("no verdict after promotion")
        verdict_s = time.monotonic() - t_kill
        assert r["linear"]["valid?"] is True, r
        out["standby"] = {
            "detect_fence_promote_s": round(promote_s, 3),
            "kill_to_verdict_s": round(verdict_s, 3),
            "recovered_streams": standby_svc.recovered_total,
            "standby_epoch": standby_svc.epoch,
        }
        sb.stop()
        standby_svc.stop()

        # -- reconnect storm vs steady-state client throughput -------
        def feed(name, drops):
            svc = _service.VerificationService()
            a = svc.serve("127.0.0.1:0")
            test = {"name": name, "start-time": "0",
                    "store-dir": os.path.join(tmp, name)}
            c = _service.ServiceClient(a, test, spec=spec())
            marks = {len(ops) * k // (drops + 1)
                     for k in range(1, drops + 1)} if drops else set()
            t0 = time.monotonic()
            for i, op in enumerate(ops):
                if i in marks:
                    # cut the live connection under the client; the
                    # next offer reconnects and replays unacked ops
                    try:
                        c._wrap.conn().sock.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass     # already mid-reconnect

                c.offer(op)
            r = c.finalize()
            wall = time.monotonic() - t0
            assert r["linear"]["valid?"] is True, r
            st = svc.status()
            svc.stop()
            return {"wall_s": round(wall, 3),
                    "ops_per_s": round(len(ops) / wall, 1),
                    "reconnects": c.reconnects,
                    "replays": st["sessions"]["replays"]}
        steady = feed("steady", 0)
        storm = feed("storm", 8)
        out["client"] = {
            "steady": steady, "storm_8_drops": storm,
            "storm_overhead_x": round(
                storm["wall_s"] / max(steady["wall_s"], 1e-4), 2)}
    finally:
        _shutil.rmtree(tmp, ignore_errors=True)
    return {"failover": out}


def section_adaptive():
    """Static vs adaptive budget under a 16-stream overload mix (the
    ISSUE-12 control plane, doc/robustness.md `Adaptive overload
    control`): a deliberately tight device-seconds budget serves a
    half-cheap / half-expensive stream mix, once with the AIMD
    controller + degradation ladder on and once frozen
    (`adaptive=False` — the `--static-budget` posture).

    What the A/B shows: with the ladder on, the service stays live
    (bounded status-verb latency while saturated) by deferring clean
    expensive streams' device verdicts to offline; frozen, every
    stream grinds through the same contended budget. Verdict
    accounting (full vs deferred vs shed) keeps the comparison honest
    — a deferred verdict is cheaper because it did less, and the
    numbers say so out loud."""
    import json as _json
    import threading as _threading

    from jepsen_tpu import service as _service, store as _store
    from jepsen_tpu.checker import synth

    model = _model()
    smoke = N_OPS < DEFAULT_N_OPS // 4
    n_streams = 8 if smoke else 16
    n = max(N_OPS // 25, 400)

    def jops(h):
        return [_json.loads(_json.dumps(op,
                                        default=_store._json_default))
                for op in h.ops]

    def spec(expensive):
        # the expensive half: 4x chunk and 2 extra slot doublings
        return {
            "linear": {"kind": "wgl",
                       "model": _service.model_spec(model),
                       "chunk-entries": 256 if expensive else 64,
                       "slots": 10 if expensive else 8,
                       "engine": "sort", "frontier": 128,
                       "checkpoint-every": 4},
            "screen-linear": {"kind": "screen",
                              "model": _service.model_spec(model)},
        }

    hists = [jops(synth.register_history(n, concurrency=3, values=5,
                                         seed=900 + i))
             for i in range(n_streams)]

    def drive(adaptive):
        svc = _service.VerificationService(
            max_streams=n_streams + 4,
            budget_elementops=2e7,   # tight: sustained contention
            adaptive=adaptive,
            ladder_tick_s=0.05,
            ladder_climb_hold_s=0.3,
            ladder_descend_hold_s=0.9)
        for i in range(n_streams):
            svc.admit(f"s{i}", spec(i % 2 == 0))
        verb_lat: list = []
        stop = _threading.Event()

        def probe():
            # the liveness probe: /healthz-shaped status() under load
            while not stop.is_set():
                t0 = time.monotonic()
                svc.status()
                verb_lat.append(time.monotonic() - t0)
                stop.wait(0.05)

        results: dict = {}

        def feed(i):
            for op in hists[i]:
                svc.offer(f"s{i}", op)
            svc.seal(f"s{i}")
            results[i] = svc.result(f"s{i}", timeout_s=600)

        prober = _threading.Thread(target=probe, daemon=True)
        prober.start()
        t0 = time.monotonic()
        ths = [_threading.Thread(target=feed, args=(i,))
               for i in range(n_streams)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        wall = time.monotonic() - t0
        stop.set()
        prober.join(timeout=5)
        st = svc.status()
        svc.stop()
        full = sum(1 for r in results.values()
                   if r.get("linear", {}).get("valid?") is True)
        deferred = sum(1 for r in results.values()
                       if r.get("linear", {}).get("deferred"))
        shed = n_streams - len([r for r in results.values() if r])
        return {
            "wall_s": round(wall, 3),
            "full_verdicts": full,
            "deferred_verdicts": deferred,
            "shed_or_lost": shed,
            "ladder_transitions":
                st.get("ladder", {}).get("transitions", 0),
            "budget_cuts": st.get("budget", {}).get("cuts", 0),
            "budget_capacity_fraction": round(
                st["budget"]["capacity"] / st["budget"]["initial"], 3),
            "status_p_max_ms": round(max(verb_lat) * 1e3, 1)
            if verb_lat else None,
            "calibration":
                st.get("calibration", {}).get("coefficients", {}),
        }

    # warm both kernel shapes outside the timed A/B (whichever mode
    # ran first would otherwise pay every compile)
    warm = _service.VerificationService(max_streams=4)
    for i in (0, 1):
        warm.admit(f"warm{i}", spec(i % 2 == 0))
        for op in hists[i][:120]:
            warm.offer(f"warm{i}", op)
        warm.seal(f"warm{i}")
        warm.result(f"warm{i}", timeout_s=300)
    warm.stop()

    static = drive(False)
    adaptive = drive(True)
    return {"adaptive": {
        "shape": f"{n_streams} streams ({n_streams // 2} cheap chunk-"
                 f"64 + {n_streams // 2} expensive chunk-256) x {n} "
                 f"ops, budget 2e7 elementops",
        "static": static,
        "adaptive": adaptive,
    }}


def section_telemetry():
    """Instrumentation overhead: the chunked 10k-op WGL path with the
    metrics registry on vs off, pinned to the CPU backend (the
    overhead contract is host-side bookkeeping — per-chunk histogram
    observes, engine-decision counters — and must stay under 2% of
    the checking path it instruments; doc/observability.md documents
    the budget). Also reports the registry's primitive micro-costs."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu import telemetry
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = _model()
    # the headline shape (near-zero crash rate — a crashed-write pileup
    # would measure the adversarial search, not the bookkeeping)
    hist = synth.register_history(N_OPS, concurrency=CONCURRENCY,
                                  values=5, crash_rate=0.0005,
                                  seed=45100)
    # small chunks -> many instrumented chunk boundaries: the shape
    # that maximizes per-chunk bookkeeping relative to device work
    kw = dict(chunk_entries=256)
    a = analysis_tpu(model, hist, budget_s=420, **kw)  # warm compile
    assert a["valid?"] is True, f"benchmark history must verify: {a}"
    # Interleaved min-floor estimator: per-run wall time on a shared
    # host is ~5%-sigma noisy, but the FLOOR (best observed run) is
    # stable to well under 1% — so compare min-of-N on vs min-of-N
    # off, sampled alternately so drift hits both arms. When the
    # first round still reads over threshold, a second round folds in
    # (legitimate for a floor estimator: more samples only sharpen
    # the min, they cannot manufacture a pass).
    prev = telemetry.set_enabled(True)
    on_s = off_s = float("inf")

    def sample_pairs(n):
        # each timed sample is 3 back-to-back analyses (~0.9 s): a
        # ~10 ms scheduler/GC spike then costs ~1% of a sample
        # instead of ~4%, which is what makes the floor sharp enough
        # for a 2% assertion on a shared host
        nonlocal on_s, off_s
        for _ in range(n):
            telemetry.set_enabled(True)
            t0 = time.monotonic()
            for _i in range(3):
                analysis_tpu(model, hist, **kw)
            on_s = min(on_s, time.monotonic() - t0)
            telemetry.set_enabled(False)
            t0 = time.monotonic()
            for _i in range(3):
                analysis_tpu(model, hist, **kw)
            off_s = min(off_s, time.monotonic() - t0)

    try:
        sample_pairs(15)
        if (on_s - off_s) / off_s * 100.0 >= 2.0:
            sample_pairs(15)
    finally:
        # restore what the operator configured (JEPSEN_TPU_METRICS=0
        # must survive this section), not a hardcoded True
        telemetry.set_enabled(prev)
    overhead_pct = round((on_s - off_s) / off_s * 100.0, 2)

    # registry primitive costs (ns/op), for the doc catalog —
    # measured with the registry ON regardless of what the section
    # restored above (with JEPSEN_TPU_METRICS=0 these loops would
    # otherwise time the no-op path and misreport it as the real
    # locked-increment cost), and against a PRIVATE registry so 200k
    # synthetic samples never pollute the real wgl series this
    # section snapshots into the BENCH artifact
    prev_prim = telemetry.set_enabled(True)
    reg = telemetry.Registry()
    c = reg.register(telemetry.Counter,
                     "jepsen_tpu_run_prim_total", "micro-bench",
                     ("site",)).labels(site="bench")
    h = reg.register(telemetry.Histogram,
                     "jepsen_tpu_run_prim_seconds", "micro-bench",
                     ("site", "family")) \
        .labels(site="bench", family="sort")
    n_prim = 200_000
    t0 = time.monotonic()
    for _ in range(n_prim):
        c.inc()
    counter_ns = (time.monotonic() - t0) / n_prim * 1e9
    t0 = time.monotonic()
    for _ in range(n_prim):
        h.observe(0.001)
    observe_ns = (time.monotonic() - t0) / n_prim * 1e9
    telemetry.set_enabled(prev_prim)

    assert overhead_pct < 2.0, \
        f"telemetry overhead {overhead_pct}% >= 2% on the CPU path"
    return {"telemetry_overhead": {
        "on_s": round(on_s, 4), "off_s": round(off_s, 4),
        "overhead_pct": overhead_pct,
        "chunk_entries": kw["chunk_entries"],
        "counter_inc_ns": round(counter_ns, 1),
        "histogram_observe_ns": round(observe_ns, 1),
    }}


def section_generator():
    """Generator throughput, host-only (reference: >20k ops/s
    single-thread, generator.clj:66-70)."""
    import random as _random

    from jepsen_tpu import generator as gen
    from jepsen_tpu.generator import simulate

    rng = _random.Random(45100)
    n_gen = 50_000
    g = gen.clients(gen.limit(n_gen, gen.mix([
        lambda: {"f": "read"},
        lambda: {"f": "write", "value": rng.randint(0, 4)},
    ])))
    t0 = time.monotonic()
    simulate.quick(gen.context({"concurrency": 10}), g)
    return {"generator_ops_per_s": round(
        n_gen / (time.monotonic() - t0), 1)}


def section_search():
    """Coverage-guided vs pure-random scenario search, CPU-pinned
    (doc/search.md): same planted conjunction bug, same seed universe,
    same fixed simulation budget — the A/B the subsystem exists for.
    Reports whether each strategy found the violation, sims-to-find,
    and corpus coverage."""
    from jepsen_tpu.search.driver import SearchConfig, run_search

    out: dict = {}
    for strategy in ("guided", "random"):
        t0 = time.monotonic()
        r = run_search(SearchConfig(
            workload="phased-register", strategy=strategy,
            bug="lost-write-kill-partition",
            generations=16, population=25, seed=2,
            max_sims=400, workers=4, escalate="none"))
        v = r["violations"][0] if r["violations"] else None
        out[strategy] = {
            "found": r["found"],
            "simulations": r["simulations"],
            "found_at_sim": v["found-at-sim"] if v else None,
            "shrink_steps": r["shrink-steps"],
            "coverage_bits": r["coverage-bits"],
            "corpus_genomes": r["corpus-size"],
            "seconds": round(time.monotonic() - t0, 3),
        }
        sims = max(1, r["simulations"])
        out[strategy]["sims_per_s"] = round(
            sims / max(1e-9, out[strategy]["seconds"]), 1)
    out["separation"] = bool(out["guided"]["found"]
                             and not out["random"]["found"])
    return out


def section_chaos():
    """Self-chaos A/B, CPU-pinned (doc/robustness.md, "Self-chaos"):
    coverage-guided vs pure-random fault-schedule fuzzing of the
    verification pipeline — same seed universe, same schedule budget.
    The prize is the fault-DURING-recovery-replay conjunction (a
    second fault landing inside the replay window of the first):
    reports conjunction hits per strategy, corpus coverage, schedule
    throughput, and that every oracle stayed green on the clean
    tree."""
    from jepsen_tpu.chaos import ChaosConfig, run_chaos

    out: dict = {}
    for strategy in ("guided", "random"):
        t0 = time.monotonic()
        r = run_chaos(ChaosConfig(
            strategy=strategy, workload="register",
            budget=40, ops=128, seed=23))
        out[strategy] = {
            "schedules": r["schedules"],
            "conjunction_hits": r["conjunction-hits"],
            "coverage_bits": r["coverage-bits"],
            "corpus_genomes": r["corpus-size"],
            "oracle_failures": len(r["failures"]),
            "seconds": round(time.monotonic() - t0, 3),
        }
        out[strategy]["schedules_per_s"] = round(
            r["schedules"] / max(1e-9, out[strategy]["seconds"]), 1)
    out["separation"] = bool(
        out["guided"]["conjunction_hits"] > 0
        and out["random"]["conjunction_hits"] == 0)
    out["oracles_green"] = (out["guided"]["oracle_failures"] == 0
                            and out["random"]["oracle_failures"] == 0)
    return out


# (name, fn, timeout_s, touches_device).  Budgets are generous: they
# exist to bound a wedged relay, not to race healthy runs.
SECTIONS = [
    ("headline", section_headline, 900, True),
    ("adversarial", section_adversarial, 600 + HOST_BUDGET_S, True),
    ("streaming", section_streaming, 900, True),
    ("recovery", section_recovery, 900, True),
    ("tiered", section_tiered, 600, True),
    ("config1", section_config1, 420, True),
    ("config2", section_config2, 480, True),
    ("config3", section_config3, 600, True),
    ("addgraphs", section_addgraphs, 600, True),
    ("config4", section_config4, 900, True),
    ("config5", section_config5, 1200, True),
    ("service", section_service, 600, True),
    ("failover", section_failover, 600, True),
    ("adaptive", section_adaptive, 600, True),
    ("telemetry", section_telemetry, 420, False),
    ("generator", section_generator, 180, False),
    ("search", section_search, 420, False),
    ("chaos", section_chaos, 420, False),
]

# nested-only sections (invoked by other sections, never scheduled by
# the orchestrator directly)
NESTED_SECTIONS = {"config5bad": section_config5bad}


def run_section(name: str) -> int:
    table = {n: f for n, f, _t, _d in SECTIONS}
    table.update(NESTED_SECTIONS)
    out = table[name]()
    # every section's JSON rides a telemetry snapshot of its own
    # process — engine decisions, recovery rungs, chunk histograms —
    # which the orchestrator files under extra.sections[name].telemetry
    # so BENCH_*.json rounds carry the decision counts alongside the
    # throughput numbers
    try:
        from jepsen_tpu import telemetry
        out.setdefault("telemetry", telemetry.snapshot(compact=True))
    except Exception as e:  # noqa: BLE001 — meta must not sink a section
        _note(f"telemetry snapshot failed: {e}")
    print(json.dumps(out), flush=True)
    return 0


def _spawn_section(name: str, timeout_s: float, env=None):
    """Run `--section name` in a child; on timeout TERM it (escalating
    to KILL).  A blocked child must NOT be left alive: the axon client
    holds the chip grant until process exit, so an abandoned child
    starves every later device process of the chip (r05: one blocked
    section pinned the grant and every subsequent `jax.devices()` hung
    at init until the holder was terminated).  Returns
    (rc|None, stdout, stderr, timed_out, seconds)."""
    # pid-scoped paths: two orchestrators on one box (the live bench
    # and the orchestrator e2e tests, say) must not truncate or read
    # each other's section pipes
    out_f = open(f"/tmp/bench_section_{os.getpid()}_{name}.out", "w+")
    err_f = open(f"/tmp/bench_section_{os.getpid()}_{name}.err", "w+")
    t0 = time.monotonic()
    child = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__),
         "--section", name],
        stdout=out_f, stderr=err_f, text=True,
        env=env if env is not None else dict(os.environ))
    timed_out = False
    try:
        rc = child.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        rc = None
        child.terminate()
        try:
            child.wait(timeout=15)
        except subprocess.TimeoutExpired:
            child.kill()
            try:
                child.wait(timeout=15)
            except subprocess.TimeoutExpired:
                pass
    out_f.seek(0), err_f.seek(0)
    stdout, stderr = out_f.read(), err_f.read()
    out_f.close(), err_f.close()
    return rc, stdout, stderr, timed_out, round(time.monotonic() - t0, 1)


def _discard_section_files(name: str) -> None:
    """Remove a section's pid-scoped pipes once its stdout has PARSED.
    Success is only knowable after the parse, so cleanup lives with the
    callers; failed/wedged/unparseable sections keep their files as the
    postmortem artifact (the JSON carries only a 300-char tail)."""
    for ext in ("out", "err"):
        try:
            os.unlink(f"/tmp/bench_section_{os.getpid()}_{name}.{ext}")
        except OSError:
            pass


def _run_section_child(name: str, timeout_s: float):
    """Nested section helper.  Returns (payload | None, info) where
    info carries {'timed_out': bool, 'rc', 'stderr_tail'} so callers
    can tell a lost/wedged dispatch (fall back) from a genuine child
    failure like an assertion (propagate, don't paper over)."""
    rc, stdout, stderr, timed_out, _dt = _spawn_section(name, timeout_s)
    tail = (stderr.strip().splitlines()[-1][:300]
            if stderr.strip() else "")
    info = {"timed_out": timed_out, "rc": rc, "stderr_tail": tail}
    if rc != 0 or not stdout.strip():
        if timed_out:
            _note(f"nested section {name} timed out after {timeout_s}s")
        else:
            _note(f"nested section {name} failed rc={rc}: {tail}")
        return None, info
    try:
        payload = json.loads(stdout.strip().splitlines()[-1])
    except ValueError:
        _note(f"nested section {name}: unparseable stdout tail "
              f"{stdout.strip()[-200:]!r}")
        return None, info
    _discard_section_files(name)
    return payload, info


def _last_known_good():
    """Most recent committed real-TPU bench artifact (doc/perf/), for
    degraded runs: a wedged relay at round end must not erase hardware
    evidence this tree already produced.  The embedded copy carries its
    own provenance so it can never be mistaken for tonight's run."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    # filename sort, not mtime: git checkouts don't preserve mtimes,
    # and round-stamped names (bench_r05_..., bench_r06_...) order
    # correctly by name
    cands = sorted(glob.glob(os.path.join(here, "doc", "perf",
                                          "bench_*tpu*.json")))
    for path in reversed(cands):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        # a partial-failure artifact ("error": "partial: ...") is not
        # "known good" — only embed fully-clean runs
        if d.get("value") and "error" not in d:
            return {"source": os.path.relpath(path, here),
                    "note": ("prior healthy on-hardware run of this "
                             "tree, committed in doc/perf — NOT "
                             "tonight's measurement"),
                    "value": d["value"], "unit": d.get("unit"),
                    "vs_baseline": d.get("vs_baseline"),
                    "configs": d.get("extra", {}).get("configs"),
                    "adversarial_10k": d.get("extra", {}).get(
                        "adversarial_10k")}
    return None


def _staticcheck_summary(env):
    """The staticcheck findings-count summary for the artifact (the
    CI gate's `--summary-json` line: files / findings / baselined /
    suppressed / by_code). AST-only analyzers — no module imports, so
    it is safe and cheap even against a wedged backend. None when the
    tool itself fails; the gate lives in `make check`, this is just
    provenance for the round."""
    try:
        p = subprocess.run(
            [sys.executable, "-m", "tools.staticcheck",
             "--only", "style,device-sync,locks,retrace",
             "--summary-json"],
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
            capture_output=True, text=True, timeout=120)
        out = json.loads(p.stdout.strip().splitlines()[-1])
        out["ok"] = p.returncode == 0
        return out
    except Exception as e:  # noqa: BLE001 — meta must not sink the run
        _note(f"staticcheck summary unavailable: {e}")
        return None


def main() -> int:
    ok, backend = preflight_backend()
    degraded = not ok
    if degraded:
        # Degraded mode: the WGL sections need the chip, but the elle
        # checks on valid histories and the generator are host-only by
        # construction — run those (with JEPSEN_TPU_ELLE_HOST=1 so the
        # injected-anomaly classification cannot touch the wedged
        # backend either) and attach them to the diagnosable error
        # line, so a wedged relay costs the round its WGL numbers, not
        # every number.
        _note("backend unavailable; degraded host-only run")
    else:
        _note(f"backend up: {backend['platform']} x{backend['n_devices']} "
              f"({backend['device_kind']})")

    # one persistent compilation cache across the per-section processes,
    # so each section only pays its own first-ever compile
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".jax_cache"))

    # sections that stay meaningful without the chip: elle checks on
    # valid histories short-circuit before any device work, and the
    # injected-anomaly leg is forced host-side by JEPSEN_TPU_ELLE_HOST
    host_capable = {"config3", "addgraphs", "config5", "generator"}
    if degraded:
        env["JEPSEN_TPU_ELLE_HOST"] = "1"

    # preserve the documented output shapes: healthy runs carry
    # extra.backend = {platform, n_devices, ...}; degraded runs carry
    # extra.preflight = {attempts: [...]} (the pre-existing contract)
    extra = {"preflight" if degraded else "backend": backend}
    if degraded:
        lkg = _last_known_good()
        if lkg is not None:
            extra["last_known_good_tpu_run"] = lkg
    sc = _staticcheck_summary(env)
    if sc is not None:
        extra["staticcheck"] = sc
    configs = {}
    sections_meta = {}
    headline = None
    device_dead = False
    t_start = time.monotonic()
    # soft whole-run deadline: generous (sum of section budgets +
    # orchestration slack), but FINITE — the final JSON line must land
    # before any driver-level kill
    total_budget = TOTAL_BUDGET_S or (
        sum(t for _n, _f, t, _d in SECTIONS) + 300)
    for name, _fn, timeout_s, touches_device in SECTIONS:
        if degraded:
            if name not in host_capable:
                sections_meta[name] = {"skipped": "backend unavailable"}
                continue
        elif device_dead and touches_device:
            sections_meta[name] = {"skipped": "backend wedged earlier"}
            continue
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining <= 30:
            # out of run budget: report, don't dispatch — partial
            # results with every section accounted for beat a dead
            # round
            sections_meta[name] = {
                "ok": False, "timeout": True,
                "skipped": "total bench budget exhausted"}
            continue
        budget_s = min(timeout_s, remaining)
        _note(f"section {name} (budget {budget_s:.0f}s)")
        # A timed-out child is TERMINATED, not abandoned: the axon
        # client holds the chip grant until process exit, so a blocked
        # child left alive starves every later device process (r05).
        # After a timeout the relay may still be wedged, so a short
        # probe decides whether to keep scheduling device sections.
        rc, stdout, stderr, timed_out, dt = _spawn_section(
            name, budget_s, env=env)
        if timed_out:
            # soft deadline: the section is marked over-budget and the
            # run CONTINUES — one hung config costs its own numbers,
            # not the round's
            sections_meta[name] = {"ok": False, "timeout": True,
                                   "seconds": dt,
                                   "budget_s": round(budget_s, 1)}
            # in degraded mode nothing touches the device, so a timeout
            # is just a slow host — never re-probe a backend already
            # known down, never skip the remaining host sections
            if touches_device and not degraded:
                ok, _info = preflight_backend()
                if not ok:
                    device_dead = True
            continue
        if rc != 0 or not stdout.strip():
            sections_meta[name] = {
                "ok": False,
                "error": f"rc {rc}",
                "seconds": dt,
                "stderr_tail": stderr.strip().splitlines()[-1][:300]
                if stderr.strip() else ""}
            continue
        try:
            payload = json.loads(stdout.strip().splitlines()[-1])
        except ValueError:
            sections_meta[name] = {
                "ok": False,
                "error": "unparseable section output",
                "stdout_tail": stdout.strip()[-300:]}
            continue
        _discard_section_files(name)
        sections_meta[name] = {"seconds": dt}
        tele = payload.pop("telemetry", None)
        if tele:
            sections_meta[name]["telemetry"] = tele
        if name == "headline":
            headline = payload
            extra["wgl_best_s"] = payload["wgl_best_s"]
            extra["wgl_engine"] = payload["wgl_engine"]
            extra["wgl_dedup"] = payload.get("wgl_dedup")
        elif name in ("adversarial", "streaming", "recovery",
                      "telemetry"):
            extra.update(payload)
        elif name.startswith("config") or name == "addgraphs":
            configs.update(payload)
        elif name == "generator":
            extra.update(payload)

    extra["configs"] = configs
    extra["sections"] = sections_meta
    if (N_OPS, N_TXNS) != (DEFAULT_N_OPS, DEFAULT_N_TXNS):
        extra["scale_override"] = {"n_ops": N_OPS, "n_txns": N_TXNS}
    value = headline["value"] if headline else None
    out = {
        "metric": ("linearizability verification throughput, 10k-op "
                   "concurrent CAS-register history (WGL search)"),
        "value": value,
        "unit": "ops/s",
        "vs_baseline": round(value / BASELINE_OPS_PER_SEC, 1)
        if value else None,
        "extra": extra,
    }
    over_budget = [n for n, m in sections_meta.items()
                   if m.get("timeout")]
    # sections never attempted because the backend wedged mid-run are a
    # HARD partial (their numbers are missing because the relay died,
    # not because a config was slow) — the soft-budget rc-0 contract
    # covers over-budget-only rounds, not a dead backend
    hard_errors = [n for n, m in sections_meta.items()
                   if ("error" in m and not m.get("timeout"))
                   or m.get("skipped") == "backend wedged earlier"]
    if degraded:
        out["error"] = "tpu-backend-unavailable"
    elif hard_errors:
        out["error"] = "partial: " + ", ".join(hard_errors + over_budget)
    elif over_budget:
        # over-budget sections are a SOFT failure: their meta rows say
        # {"ok": false, "timeout": true} and the line below is the
        # round's complete parseable result — rc stays 0 so drivers
        # keep the partial numbers (r05's rc:1 made them discard a
        # round that had nine healthy sections)
        out["error"] = "sections-over-budget: " + ", ".join(over_budget)
    print(json.dumps(out))
    # A missing backend is an environment condition, not a bench
    # failure: the host-only JSON line above is the complete, parseable
    # result for such a round (BENCH_r05 recorded rc 1 + parsed null
    # because drivers treat nonzero exit as "no result"). Exit 0 so the
    # host numbers land; the "error" field still says the WGL numbers
    # are absent. Genuinely partial healthy-backend runs stay rc 1.
    if degraded:
        return 0
    return 0 if not hard_errors else 1


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--section":
        sys.exit(run_section(sys.argv[2]))
    sys.exit(main())
