#!/usr/bin/env python
"""Headline benchmark: history verification throughput on TPU.

Two north-star configs (BASELINE.md):
  * WGL linearizability on a 10k-op concurrent CAS-register history
    (the reference's CPU Knossos needs a 32 GB heap, `jepsen/
    project.clj:38`, and times out ~1 h on 10k ops — that timeout is the
    vs_baseline denominator). We also report the *measured* host-oracle
    result on the same history under a 60 s budget, so the baseline
    framing is checked against a real run, not only the assumed timeout.
  * Elle list-append cycle analysis on a 100k-txn history (config 5).
    The north-star grading is "max history length solved < 300 s", so
    vs_baseline is speedup over 100k txns / 300 s.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N,
   "extra": {...}}
"""

import json
import sys
import time

N_OPS = 10_000
CONCURRENCY = 5
BASELINE_OPS_PER_SEC = N_OPS / 3600.0  # CPU knossos: 1 h timeout on 10k ops
N_TXNS = 100_000
BASELINE_TXNS_PER_SEC = N_TXNS / 300.0  # north star: solved < 300 s


def main() -> int:
    from jepsen_tpu import models
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.elle import list_append
    from jepsen_tpu.checker.linear import analysis_host
    from jepsen_tpu.checker.wgl import analysis_tpu

    hist = synth.register_history(N_OPS, concurrency=CONCURRENCY, values=5,
                                  crash_rate=0.0005, seed=45100)
    model = models.cas_register()

    # First call compiles (~20-40 s on TPU); benchmark the steady state.
    a = analysis_tpu(model, hist, budget_s=420)
    assert a["valid?"] is True, f"benchmark history must verify: {a}"

    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        a = analysis_tpu(model, hist)
        best = min(best, time.monotonic() - t0)
    assert a["valid?"] is True
    value = N_OPS / best

    # measured host oracle on the same history, 60 s budget
    t0 = time.monotonic()
    host = analysis_host(model, hist, budget_s=60)
    host_s = time.monotonic() - t0
    host_done = host["valid?"] is True

    # elle list-append at config-5 scale (100k txns), end-to-end
    eh = synth.append_history(N_TXNS, seed=45100)
    t0 = time.monotonic()
    er = list_append.check(eh)
    elle_s = time.monotonic() - t0
    assert er["valid?"] is True, f"elle bench history must verify: {er}"
    elle_rate = N_TXNS / elle_s
    # and an anomalous variant must still classify (exercises the MXU path)
    bad = synth.inject_append_cycles(eh, 64, "G1c")
    t0 = time.monotonic()
    br = list_append.check(bad)
    elle_bad_s = time.monotonic() - t0
    assert br["valid?"] is False and "G1c" in br["anomaly-types"]

    print(json.dumps({
        "metric": ("linearizability verification throughput, 10k-op "
                   "concurrent CAS-register history (WGL frontier search)"),
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(value / BASELINE_OPS_PER_SEC, 1),
        "extra": {
            "wgl_best_s": round(best, 3),
            "host_oracle_10k": {
                "completed_in_60s": host_done,
                "seconds": round(host_s, 1),
                "verdict": str(host["valid?"])},
            "elle_append_100k": {
                "value": round(elle_rate, 1),
                "unit": "txns/s",
                "seconds": round(elle_s, 2),
                "vs_baseline": round(elle_rate / BASELINE_TXNS_PER_SEC, 1)},
            "elle_append_100k_with_64_cycles_s": round(elle_bad_s, 2),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
