#!/usr/bin/env python
"""Back-compat shim: the metric-name lint now lives in
tools/staticcheck (the metrics analyzer, JTS01x) — one naming pass
over the live registry against the ``jepsen_tpu_<layer>_<name>_<unit>``
convention from doc/observability.md. This entry point keeps the
historical CLI and output (``name: message`` lines, exit 1 when
dirty).

Prefer ``python -m tools.staticcheck`` (or ``make lint``), which runs
the whole suite. See doc/static_analysis.md."""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.staticcheck.metrics import lint_registry

    problems, n = lint_registry(repo)
    for _code, name, msg in problems:
        print(f"{name}: {msg}")
    print(f"lint-metrics: {n} metrics, {len(problems)} problem(s)",
          file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
