#!/usr/bin/env python
"""Metric-name lint: one naming pass over the live registry.

Imports every instrumented module (which registers its metrics at
import time) and asserts the ``jepsen_tpu_<layer>_<name>_<unit>``
convention from doc/observability.md over each registered metric:

  * prefix ``jepsen_tpu_``, layer in telemetry.LAYERS, final token
    (the unit) in telemetry.UNITS, all-lowercase snake_case;
  * counters end in ``_total``; nothing else may;
  * histograms end in a measurable unit (``_seconds``, ``_rows``,
    ``_bytes``, ``_ops``, ``_elementops``) — the Prometheus
    ``_bucket``/``_sum``/``_count`` suffixes hang off that base.

Run by ``make check`` (the reference gates pushes on lint,
`.travis.yml:1-11`); exit 0 when clean, 1 with one `name: message`
line per finding otherwise.
"""

from __future__ import annotations

import os
import re
import sys

HISTOGRAM_UNITS = ("seconds", "rows", "bytes", "ops", "elementops")

# the instrumented modules — importing them registers their metrics
MODULES = (
    "jepsen_tpu.telemetry",
    "jepsen_tpu.trace",
    "jepsen_tpu.checker.wgl",
    "jepsen_tpu.checker.streaming",
    "jepsen_tpu.checker.screen",
    "jepsen_tpu.checker.abft",
    "jepsen_tpu.service",
    "jepsen_tpu.web",
)


def lint_registry() -> list[str]:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runnable as `python tools/lint_metrics.py` from the repo root
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import importlib
    for m in MODULES:
        importlib.import_module(m)
    from jepsen_tpu import telemetry

    pat = re.compile(
        r"^jepsen_tpu_(%s)_[a-z0-9_]+_(%s)$"
        % ("|".join(telemetry.LAYERS), "|".join(telemetry.UNITS)))
    problems: list[str] = []
    metrics = telemetry.REGISTRY.metrics()
    if not metrics:
        return ["registry is empty — instrumented modules did not "
                "register their metrics at import time"]
    for m in metrics:
        if not pat.match(m.name):
            problems.append(
                f"{m.name}: does not match "
                f"jepsen_tpu_<layer>_<name>_<unit> "
                f"(layers {telemetry.LAYERS}, units "
                f"{telemetry.UNITS})")
            continue
        if m.kind == "counter" and not m.name.endswith("_total"):
            problems.append(f"{m.name}: counters must end in _total")
        if m.kind != "counter" and m.name.endswith("_total"):
            problems.append(
                f"{m.name}: _total is reserved for counters "
                f"({m.kind})")
        if m.kind == "histogram" and \
                not m.name.endswith(HISTOGRAM_UNITS):
            problems.append(
                f"{m.name}: histograms must end in a measurable "
                f"unit {HISTOGRAM_UNITS}")
    return problems


def main() -> int:
    problems = lint_registry()
    for p in problems:
        print(p)
    from jepsen_tpu import telemetry
    print(f"lint-metrics: {len(telemetry.REGISTRY.names())} metrics, "
          f"{len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
