#!/usr/bin/env python
"""In-repo lint gate (the reference gates every push on `lein eastwood`,
`.travis.yml:1-11`; no third-party linter is available in this image,
so the checks that matter are implemented here directly).

Checks, per Python file:

  * syntax (ast.parse)
  * unused imports — an imported name never referenced in the module
    (`# noqa` on the import line exempts deliberate re-exports)
  * duplicate imports of the same name
  * tabs in indentation, trailing whitespace
  * lines longer than MAX_LINE columns

Exit 0 when clean; prints one `path:line: message` per finding
otherwise and exits 1.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 100
ROOTS = ["jepsen_tpu", "tests", "tools", "bench.py", "__graft_entry__.py"]


def _imported_names(tree: ast.AST):
    """Yield (lineno, bound-name, is-future, is-toplevel) for every
    import binding.  Function-local imports are idiomatic in this
    codebase (they defer jax init), so duplicate detection only looks
    at the is-toplevel subset."""
    toplevel = set()
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            toplevel.add(id(node))
    for node in ast.walk(tree):
        top = id(node) in toplevel
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                # dedup on the full dotted path: `import urllib.error`
                # and `import urllib.request` both bind `urllib` but
                # are distinct imports
                yield node.lineno, bound, a.asname or a.name, False, top
        elif isinstance(node, ast.ImportFrom):
            future = node.module == "__future__"
            prefix = f"{node.module}." if node.module else ""
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                yield (node.lineno, bound, prefix + a.name, future, top)


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # names exported via __all__ count as used
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    used.add(c.value)
    return used


def lint_file(path: Path) -> list[str]:
    problems: list[str] = []
    text = path.read_text()
    lines = text.splitlines()

    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    noqa = {i + 1 for i, line in enumerate(lines) if "# noqa" in line}

    used = _used_names(tree)
    seen: dict[str, int] = {}
    for lineno, name, dotted, future, top in _imported_names(tree):
        if lineno in noqa or future:
            continue
        if top:
            key = f"{dotted} as {name}"
            if key in seen and seen[key] != lineno:
                problems.append(
                    f"{path}:{lineno}: duplicate import of {dotted!r} "
                    f"(first at line {seen[key]})")
            seen.setdefault(key, lineno)
        if name not in used and not name.startswith("_"):
            problems.append(f"{path}:{lineno}: unused import {name!r}")

    for i, line in enumerate(lines, 1):
        if i in noqa:
            continue
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        body = line[:len(line) - len(line.lstrip())]
        if "\t" in body:
            problems.append(f"{path}:{i}: tab in indentation")
        if len(line) > MAX_LINE:
            problems.append(
                f"{path}:{i}: line too long ({len(line)} > {MAX_LINE})")
    return problems


def main(argv: list[str]) -> int:
    repo = Path(__file__).resolve().parent.parent
    targets = argv or ROOTS
    files: list[Path] = []
    for t in targets:
        p = repo / t
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    problems: list[str] = []
    for f in files:
        problems.extend(lint_file(f))
    for msg in problems:
        print(msg)
    print(f"lint: {len(files)} files, {len(problems)} problem(s)",
          file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
