#!/usr/bin/env python
"""Back-compat shim: the in-repo lint gate now lives in
tools/staticcheck (the style analyzer, JTS00x — syntax, unused /
duplicate imports, whitespace, line length). This entry point keeps
the historical CLI: ``python tools/lint.py [targets...]``, one
``path:line: ...`` per finding, exit 1 when dirty.

Prefer ``python -m tools.staticcheck`` (or ``make lint``), which runs
the whole suite: style + metric naming + device-sync + lock
discipline + retrace hazards. See doc/static_analysis.md."""

from __future__ import annotations

import sys
from pathlib import Path

if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from tools.staticcheck.driver import run

    res = run(sys.argv[1:], only={"style"})
    for f in res["_live"]:
        print(f.render())
    print(f"lint: {res['files']} files, {res['findings']} problem(s)",
          file=sys.stderr)
    sys.exit(1 if res["findings"] else 0)
