"""Max-history-length probe: how large a register history the WGL
dense engine solves within a wall-clock budget on the current backend.

BASELINE.md's metric line is "ops verified/sec; max history length
solved < 300 s" — this tool produces that datapoint (the bench proper
stays at 10k/50k/100k so its runtime remains bounded).

Usage: python tools/scale_probe.py [--n 1000000] [--budget 280]
Prints one JSON line. Crash-free shape by construction: every crashed
mutating op permanently doubles the configuration space (the same
exponential wall the reference's knossos hits), so "max length" is
only well-defined on the crash-free workload.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jepsen_tpu._platform import honor_platform_env  # noqa: E402

honor_platform_env()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--budget", type=float, default=280.0)
    ap.add_argument("--concurrency", type=int, default=6)
    args = ap.parse_args()

    from jepsen_tpu import models
    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.wgl import analysis_tpu

    model = models.cas_register()
    t0 = time.monotonic()
    h = synth.register_history(args.n, concurrency=args.concurrency,
                               values=5, crash_rate=0.0, seed=45100)
    synth_s = time.monotonic() - t0

    import jax
    backend = jax.devices()[0]
    t0 = time.monotonic()
    a = analysis_tpu(model, h, budget_s=args.budget)
    check_s = time.monotonic() - t0
    print(json.dumps({
        "n_ops": args.n,
        "platform": backend.platform,
        "device_kind": backend.device_kind,
        "synth_s": round(synth_s, 1),
        "check_s": round(check_s, 1),
        "ops_per_s": round(args.n / check_s, 1),
        "valid": a["valid?"] is True,
        "analyzer": a["analyzer"],
        "solved_in_budget": a["valid?"] is True and check_s <= args.budget,
    }))
    return 0 if a["valid?"] is True else 1


if __name__ == "__main__":
    raise SystemExit(main())
