"""`jepsen-tpu staticcheck` — the repo's whole-program static-analysis
suite (doc/static_analysis.md).

Five analyzers on one driver, mirroring the reference's `lein
eastwood` CI gate (`.travis.yml:1-11`) but specialised to what this
codebase's correctness actually hinges on:

  style        JTS00x  syntax / imports / whitespace (ex tools/lint.py)
  metrics      JTS01x  metric naming (ex tools/lint_metrics.py)
  device-sync  JTS10x  every device fetch rides guarded_device_get
  locks        JTS20x  `# guarded-by:` / `# holds:` lock discipline
  retrace      JTS30x  stable jit trace signatures

Run: ``python -m tools.staticcheck`` (or ``make lint`` /
``make staticcheck``). Suppress: ``# noqa: JTS###``. Pre-existing
debt: ``tools/staticcheck/baseline.txt``."""

from .base import Analyzer, Finding, SourceFile  # noqa: F401 — public API
from .driver import main, run  # noqa: F401 — public API
