"""Style/import analyzer (JTS00x) — the old tools/lint.py checks,
migrated onto the shared driver (the reference gates every push on
`lein eastwood`, `.travis.yml:1-11`; no third-party linter exists in
this image, so the checks that matter are implemented here).

Per file:

  JTS001  syntax error (ast.parse)
  JTS002  unused import — an imported name never referenced in the
          module. Names used only inside *string annotations*
          (``x: "Optional[int]"``, forward refs nested in real
          annotations) count as used: the old pass missed them and
          forced ``# noqa`` noise on typing-only imports.
  JTS003  duplicate toplevel import of the same dotted name
  JTS004  trailing whitespace
  JTS005  tab in indentation
  JTS006  line longer than MAX_LINE columns

Keeps tools/lint.py's legacy suppression rule: any ``# noqa`` mention
on the line exempts it (so existing ``# noqa: F401``-style re-export
exemptions keep working)."""

from __future__ import annotations

import ast

from .base import Analyzer, Finding, SourceFile

MAX_LINE = 100


def _imported_names(tree: ast.AST):
    """Yield (lineno, bound-name, dotted, is-future, is-toplevel) for
    every import binding. Function-local imports are idiomatic in this
    codebase (they defer jax init), so duplicate detection only looks
    at the is-toplevel subset."""
    toplevel = set()
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            toplevel.add(id(node))
    for node in ast.walk(tree):
        top = id(node) in toplevel
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                # dedup on the full dotted path: `import urllib.error`
                # and `import urllib.request` both bind `urllib` but
                # are distinct imports
                yield node.lineno, bound, a.asname or a.name, False, top
        elif isinstance(node, ast.ImportFrom):
            future = node.module == "__future__"
            prefix = f"{node.module}." if node.module else ""
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                yield (node.lineno, bound, prefix + a.name, future, top)


def _annotation_exprs(tree: ast.AST):
    """Every annotation expression position in the module."""
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            yield node.annotation
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.returns is not None:
                yield node.returns
            args = node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                if a.annotation is not None:
                    yield a.annotation


def _names_in_string_annotations(tree: ast.AST) -> set[str]:
    """Names referenced only inside string annotations ("Optional[X]"
    as a quoted forward reference, or quoted pieces nested inside a
    real annotation expression). The old unused-import pass could not
    see these — the false-positive class this fixes."""
    used: set[str] = set()
    pending = list(_annotation_exprs(tree))
    while pending:
        expr = pending.pop()
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                used.add(n.id)
            elif isinstance(n, ast.Attribute):
                v = n
                while isinstance(v, ast.Attribute):
                    v = v.value
                if isinstance(v, ast.Name):
                    used.add(v.id)
            elif (isinstance(n, ast.Constant)
                    and isinstance(n.value, str)):
                try:
                    pending.append(ast.parse(n.value, mode="eval").body)
                except SyntaxError:
                    pass   # a plain string (Literal["a"], doc text)
    return used


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # names exported via __all__ count as used
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    used.add(c.value)
    used |= _names_in_string_annotations(tree)
    return used


class StyleAnalyzer(Analyzer):
    name = "style"
    codes = ("JTS001", "JTS002", "JTS003", "JTS004", "JTS005",
             "JTS006")
    legacy_noqa = True

    def check_file(self, sf: SourceFile) -> list[Finding]:
        if sf.syntax_error is not None:
            e = sf.syntax_error
            return [Finding(sf.rel, e.lineno or 1, "JTS001",
                            f"syntax error: {e.msg}")]
        out: list[Finding] = []
        tree = sf.tree
        used = _used_names(tree)
        seen: dict[str, int] = {}
        for lineno, name, dotted, future, top in _imported_names(tree):
            if future:
                continue
            if top:
                key = f"{dotted} as {name}"
                if key in seen and seen[key] != lineno:
                    out.append(Finding(
                        sf.rel, lineno, "JTS003",
                        f"duplicate import of {dotted!r} "
                        f"(first at line {seen[key]})"))
                seen.setdefault(key, lineno)
            if name not in used and not name.startswith("_"):
                out.append(Finding(sf.rel, lineno, "JTS002",
                                   f"unused import {name!r}"))
        for i, line in enumerate(sf.lines, 1):
            if line != line.rstrip():
                out.append(Finding(sf.rel, i, "JTS004",
                                   "trailing whitespace"))
            body = line[:len(line) - len(line.lstrip())]
            if "\t" in body:
                out.append(Finding(sf.rel, i, "JTS005",
                                   "tab in indentation"))
            if len(line) > MAX_LINE:
                out.append(Finding(
                    sf.rel, i, "JTS006",
                    f"line too long ({len(line)} > {MAX_LINE})"))
        return out
