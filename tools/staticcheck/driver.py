"""The staticcheck driver: collect files, run analyzers, apply
suppressions and the committed baseline, report.

Usage (also via ``make lint`` / ``make staticcheck``)::

    python -m tools.staticcheck [targets...]
        [--only style,metrics,device-sync,locks,retrace]
        [--baseline PATH] [--write-baseline] [--summary-json]

Exit 0 when the tree is clean (or every finding is baselined);
exit 1 with one ``path:line: CODE message`` per finding otherwise —
the same contract as the old tools/lint.py, which this subsumes."""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .base import Finding, SourceFile
from .devicesync import DeviceSyncAnalyzer
from .lockcheck import LockAnalyzer
from .metrics import MetricsAnalyzer
from .retrace import RetraceAnalyzer
from .style import StyleAnalyzer

ROOTS = ["jepsen_tpu", "tests", "tools", "bench.py",
         "__graft_entry__.py"]
ANALYZER_ORDER = ("style", "metrics", "device-sync", "locks",
                  "retrace")


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


def default_baseline() -> Path:
    return Path(__file__).resolve().parent / "baseline.txt"


def make_analyzers(only: set[str] | None = None,
                   repo: Path | None = None) -> list:
    repo = str(repo or repo_root())
    byname = {
        "style": StyleAnalyzer(),
        "metrics": MetricsAnalyzer(repo),
        "device-sync": DeviceSyncAnalyzer(),
        "locks": LockAnalyzer(),
        "retrace": RetraceAnalyzer(),
    }
    names = [n for n in ANALYZER_ORDER
             if only is None or n in only]
    unknown = (only or set()) - set(byname)
    if unknown:
        raise SystemExit(f"unknown analyzer(s): {sorted(unknown)} "
                         f"(choose from {list(ANALYZER_ORDER)})")
    return [byname[n] for n in names]


def collect_files(targets: list[str], repo: Path) -> list[SourceFile]:
    files: list[Path] = []
    for t in targets or ROOTS:
        p = (repo / t) if not Path(t).is_absolute() else Path(t)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return [SourceFile.load(f, repo) for f in files]


def load_baseline(path: Path) -> dict[str, int]:
    """Baseline entries as a multiset of `path: CODE message` keys."""
    out: dict[str, int] = {}
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        out[line] = out.get(line, 0) + 1
    return out


def write_baseline(path: Path, findings: list[Finding]) -> None:
    lines = [
        "# staticcheck baseline — pre-existing findings that do not",
        "# fail the gate. One `path: CODE message` per line (no line",
        "# numbers, so unrelated edits don't churn this file).",
        "# Regenerate: python -m tools.staticcheck --write-baseline",
    ]
    lines += sorted(f.baseline_key() for f in findings)
    path.write_text("\n".join(lines) + "\n")


def run(targets: list[str], only: set[str] | None = None,
        baseline_path: Path | None = None,
        repo: Path | None = None) -> dict:
    """Run the suite; returns the summary dict (see --summary-json).
    `repo` overrides the tree root (tests point it at a fixture
    tree)."""
    repo = repo or repo_root()
    analyzers = make_analyzers(only, repo=repo)
    files = collect_files(targets, repo)
    sf_by_rel = {sf.rel: sf for sf in files}

    findings: list[Finding] = []
    suppressed = 0
    for az in analyzers:
        scoped = [sf for sf in files if az.scope(sf)]
        raw: list[Finding] = []
        for sf in scoped:
            raw.extend(az.check_file(sf))
        raw.extend(az.check_program(files))
        for f in raw:
            sf = sf_by_rel.get(f.path)
            if sf is not None and sf.suppressed(
                    f, legacy=az.legacy_noqa):
                suppressed += 1
                continue
            findings.append(f)

    baseline = load_baseline(baseline_path or default_baseline())
    live: list[Finding] = []
    baselined = 0
    remaining = dict(baseline)
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.code)):
        key = f.baseline_key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            baselined += 1
        else:
            live.append(f)

    by_code: dict[str, int] = {}
    for f in live:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    return {
        "files": len(files),
        "analyzers": [az.name for az in analyzers],
        "findings": len(live),
        "baselined": baselined,
        "suppressed": suppressed,
        "by_code": dict(sorted(by_code.items())),
        "_live": live,
        "_all": findings,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.staticcheck",
        description="repo-specific static-analysis gate "
                    "(doc/static_analysis.md)")
    ap.add_argument("targets", nargs="*",
                    help=f"files/dirs to check (default: {ROOTS})")
    ap.add_argument("--only",
                    help="comma-separated analyzer subset "
                         f"(default: all of {list(ANALYZER_ORDER)})")
    ap.add_argument("--baseline", type=Path,
                    help="baseline file (default: "
                         "tools/staticcheck/baseline.txt)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--summary-json", action="store_true",
                    help="emit one machine-readable JSON summary "
                         "line on stdout (bench.py embeds it)")
    args = ap.parse_args(argv)

    only = ({t.strip() for t in args.only.split(",") if t.strip()}
            if args.only else None)
    res = run(args.targets, only=only, baseline_path=args.baseline)

    if args.write_baseline:
        if args.only or args.targets:
            # a filtered run sees only a subset of findings; writing
            # it out would silently erase every baseline entry
            # belonging to the analyzers/files that did not run
            print("staticcheck: --write-baseline requires a full run "
                  "(no --only, no explicit targets)", file=sys.stderr)
            return 2
        path = args.baseline or default_baseline()
        write_baseline(path, res["_all"])
        print(f"staticcheck: wrote {len(res['_all'])} baseline "
              f"entr{'y' if len(res['_all']) == 1 else 'ies'} to "
              f"{path}", file=sys.stderr)
        return 0

    for f in res["_live"]:
        print(f.render())
    summary = (f"staticcheck: {res['files']} files, "
               f"{len(res['analyzers'])} analyzers, "
               f"{res['findings']} finding(s) "
               f"({res['baselined']} baselined, "
               f"{res['suppressed']} suppressed)")
    print(summary, file=sys.stderr)
    if args.summary_json:
        out = {k: v for k, v in res.items()
               if not k.startswith("_")}
        print(json.dumps(out))
    return 1 if res["findings"] else 0
