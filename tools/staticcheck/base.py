"""Shared plumbing for the repo's static-analysis suite.

A *finding* is one `path:line: CODE message` diagnostic. An *analyzer*
is a named pass producing findings, either per-file (handed the parsed
AST, shared across analyzers so each file is read and parsed once) or
whole-program (the metrics-registry pass). The driver
(`tools/staticcheck/driver.py`) owns file collection, suppression, the
committed baseline, and exit-code semantics.

Suppression grammar (doc/static_analysis.md):

  * ``# noqa: JTS123`` on the offending line suppresses that code
    there (comma-separated lists allowed; anything after an ``em``
    dash or the code list is free-text rationale).
  * A bare ``# noqa`` suppresses *every* code on the line.
  * Analyzers migrated from the old tools/lint.py keep its looser
    legacy rule — any ``# noqa`` mention exempts the line — so
    pre-existing ``# noqa: F401``-style exemptions keep working.

Baseline: `tools/staticcheck/baseline.txt` holds pre-existing debt as
``path: CODE message`` lines (no line numbers, so unrelated edits
don't churn it). Findings matching a baseline entry don't fail the
gate; regenerate with ``--write-baseline`` after deliberate changes.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

NOQA_RE = re.compile(r"#\s*noqa(?!\w)(?::\s*(?P<codes>[A-Z0-9, ]+))?",
                     re.IGNORECASE)
CODE_RE = re.compile(r"[A-Z]+[0-9]+")


@dataclass(frozen=True)
class Finding:
    path: str          # repo-relative, forward slashes
    line: int
    code: str          # e.g. "JTS101"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def baseline_key(self) -> str:
        return f"{self.path}: {self.code} {self.message}"


@dataclass
class SourceFile:
    """One parsed target, shared by every per-file analyzer."""

    path: Path              # absolute
    rel: str                # repo-relative, forward slashes
    text: str
    lines: list[str] = field(default_factory=list)
    tree: ast.AST | None = None
    syntax_error: SyntaxError | None = None

    @classmethod
    def load(cls, path: Path, repo: Path) -> "SourceFile":
        text = path.read_text()
        try:
            rel = path.relative_to(repo).as_posix()
        except ValueError:      # explicit target outside the repo
            rel = path.as_posix()
        return cls.from_text(rel, text, path=path)

    @classmethod
    def from_text(cls, rel: str, text: str,
                  path: Path | None = None) -> "SourceFile":
        """Build from source text directly (test fixtures)."""
        sf = cls(path=path or Path(rel), rel=rel, text=text,
                 lines=text.splitlines())
        try:
            sf.tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            sf.syntax_error = e
        return sf

    def noqa_codes(self, line: int) -> set[str] | None:
        """Codes suppressed on `line`: a set of codes, the sentinel
        {"*"} for a bare noqa, or None when the line has no noqa."""
        if not (1 <= line <= len(self.lines)):
            return None
        m = NOQA_RE.search(self.lines[line - 1])
        if m is None:
            return None
        codes = m.group("codes")
        if not codes:
            return {"*"}
        found = set(CODE_RE.findall(codes.upper()))
        return found or {"*"}

    def suppressed(self, finding: Finding, legacy: bool = False) -> bool:
        codes = self.noqa_codes(finding.line)
        if codes is None:
            return False
        if legacy:      # old tools/lint.py rule: any noqa exempts
            return True
        return "*" in codes or finding.code in codes


class Analyzer:
    """Base class. Per-file analyzers override check_file; whole-
    program analyzers override check_program (called once, after the
    per-file sweep, with every collected SourceFile)."""

    name = "base"
    codes: tuple[str, ...] = ()
    #: legacy=True keeps the old tools/lint.py bare-noqa semantics
    legacy_noqa = False

    def scope(self, sf: SourceFile) -> bool:
        """Is this file in the analyzer's scope?"""
        return sf.rel.endswith(".py")

    def check_file(self, sf: SourceFile) -> list[Finding]:
        return []

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        return []


# -- small AST helpers shared by the analyzers --------------------------------

def call_root(node: ast.AST) -> str | None:
    """The leftmost Name of a (possibly dotted) callee expression:
    `jax.device_get` -> 'jax', `np.asarray` -> 'np', `foo(...)` ->
    'foo'. None for anything fancier."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def attr_name(call: ast.Call) -> str | None:
    """The attribute name of an attribute call (`k.check(...)` ->
    'check'), or the bare Name (`fn(...)` -> 'fn')."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def contains_call_to(node: ast.AST, names: set[str]) -> bool:
    for c in ast.walk(node):
        if isinstance(c, ast.Call) and attr_name(c) in names:
            return True
    return False
