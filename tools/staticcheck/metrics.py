"""Metric-name analyzer (JTS01x) — the old tools/lint_metrics.py,
migrated onto the shared driver as a *whole-program* pass.

Imports every instrumented module (which registers its metrics at
import time) and asserts the ``jepsen_tpu_<layer>_<name>_<unit>``
convention from doc/observability.md over the live registry:

  JTS010  registry unavailable / empty (import failure)
  JTS011  name does not match jepsen_tpu_<layer>_<name>_<unit>
  JTS012  counter not ending in _total
  JTS013  _total on a non-counter
  JTS014  histogram not ending in a measurable unit

Findings carry the pseudo-path ``<metrics-registry>`` (a registered
metric has no single source line)."""

from __future__ import annotations

import os
import re
import sys

from .base import Analyzer, Finding, SourceFile

HISTOGRAM_UNITS = ("seconds", "rows", "bytes", "ops", "elementops")

# the instrumented modules — importing them registers their metrics
MODULES = (
    "jepsen_tpu.telemetry",
    "jepsen_tpu.trace",
    "jepsen_tpu.checker.wgl",
    "jepsen_tpu.checker.streaming",
    "jepsen_tpu.checker.screen",
    "jepsen_tpu.checker.abft",
    "jepsen_tpu.service",
    "jepsen_tpu.web",
    "jepsen_tpu.search.driver",
    "jepsen_tpu.chaos.driver",
)

REGISTRY_PATH = "<metrics-registry>"


def lint_registry(repo: str) -> tuple[list[tuple[str, str, str]], int]:
    """[(code, metric-name, message)], metric count. Runs against the
    live process-wide registry after importing MODULES."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import importlib
    try:
        for m in MODULES:
            importlib.import_module(m)
        from jepsen_tpu import telemetry
    except Exception as e:  # noqa: BLE001 — report, don't crash the gate
        return [("JTS010", "registry",
                 f"could not import the instrumented modules: {e}")], 0

    pat = re.compile(
        r"^jepsen_tpu_(%s)_[a-z0-9_]+_(%s)$"
        % ("|".join(telemetry.LAYERS), "|".join(telemetry.UNITS)))
    problems: list[tuple[str, str, str]] = []
    metrics = telemetry.REGISTRY.metrics()
    if not metrics:
        return [("JTS010", "registry",
                 "registry is empty — instrumented modules did not "
                 "register their metrics at import time")], 0
    for m in metrics:
        if not pat.match(m.name):
            problems.append((
                "JTS011", m.name,
                f"does not match jepsen_tpu_<layer>_<name>_<unit> "
                f"(layers {telemetry.LAYERS}, units "
                f"{telemetry.UNITS})"))
            continue
        if m.kind == "counter" and not m.name.endswith("_total"):
            problems.append(("JTS012", m.name,
                             "counters must end in _total"))
        if m.kind != "counter" and m.name.endswith("_total"):
            problems.append((
                "JTS013", m.name,
                f"_total is reserved for counters ({m.kind})"))
        if m.kind == "histogram" and \
                not m.name.endswith(HISTOGRAM_UNITS):
            problems.append((
                "JTS014", m.name,
                f"histograms must end in a measurable unit "
                f"{HISTOGRAM_UNITS}"))
    return problems, len(metrics)


class MetricsAnalyzer(Analyzer):
    name = "metrics"
    codes = ("JTS010", "JTS011", "JTS012", "JTS013", "JTS014")

    def __init__(self, repo: str):
        self.repo = repo
        self.metric_count = 0

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        # only meaningful when the instrumented package is a target
        if not any(sf.rel.startswith("jepsen_tpu/") for sf in files):
            return []
        problems, n = lint_registry(self.repo)
        self.metric_count = n
        return [Finding(REGISTRY_PATH, 0, code, f"{name}: {msg}")
                for code, name, msg in problems]
