"""Device-sync analyzer (JTS10x): every blocking device fetch in the
checking pipeline must ride ``_platform.guarded_device_get``.

Why: `guarded_device_get` is where the JEPSEN_TPU_SYNC_DEADLINE_S
watchdog and the fault classifier live — a raw `jax.device_get`, a
`.block_until_ready()`, or an implicit sync (`np.asarray` /
`int()`/`bool()`/`float()` over a device value) bypasses both, so a
wedged TPU hangs the calling stream forever instead of raising
`WedgedDeviceSync` and climbing the recovery ladder.

Scope: ``jepsen_tpu/checker/`` and ``jepsen_tpu/service.py`` (the
long-lived daemon paths; `_platform.py` itself hosts the wrapper).

  JTS101  raw jax.device_get call
  JTS102  .block_until_ready() call
  JTS103  implicit sync: np.asarray/np.array or int/float/bool over a
          device-value expression

Device values are tracked *function-locally*: results of known jitted
kernel entries (``k.check`` / ``check_stream_chunk`` / ``summarize``
/ ...), of callables bound from kernel factories (``fn =
_flags_batch_fn(...)``), of `jnp.*` / `jax.device_put` / `jax.vmap`
calls — propagated through assignments, tuple unpacking, subscripts,
and comprehension targets. `guarded_device_get(...)` launders taint
(its result is host data). Attribute state (``self._carry``) and
cross-function flows are out of scope — keep device values local to
the dispatch function, which every current call site does."""

from __future__ import annotations

import ast

from .base import Analyzer, Finding, SourceFile, attr_name, call_root

# jitted kernel-entry attribute names (the Kernel namedtuple surface
# plus the abft digest entries)
ENTRY_NAMES = {
    "check", "check_batch", "check_chunk", "check_chunk_batch",
    "check_stream_chunk", "init_carry", "summarize", "digest",
    "digest_device",
}

# factories whose return value is a jitted callable (calling it yields
# a device value)
FACTORY_NAMES = {
    "_kernel", "_dense_kernel", "_kernel_cached",
    "_dense_kernel_cached", "_flags_batch_fn", "_closure_fn",
    "dedup_fn", "_mk_digest", "_sharded_runner",
}

GUARD_NAMES = {"guarded_device_get"}
SYNC_BUILTINS = {"int", "float", "bool"}
NP_ROOTS = {"np", "numpy"}


class _FunctionTaint(ast.NodeVisitor):
    """One pass over a function body: track device-tainted local
    names, flag unguarded syncs."""

    def __init__(self, sf: SourceFile, findings: list[Finding]):
        self.sf = sf
        self.findings = findings
        self.tainted: set[str] = set()
        self.jit_callables: set[str] = set()

    # -- taint predicates ---------------------------------------------------

    def _is_device_call(self, call: ast.Call) -> bool:
        name = attr_name(call)
        if name in GUARD_NAMES:
            return False
        root = call_root(call.func)
        if root in GUARD_NAMES:
            return False
        if isinstance(call.func, ast.Attribute) and name in ENTRY_NAMES:
            return True
        if isinstance(call.func, ast.Name) \
                and call.func.id in self.jit_callables:
            return True
        if root == "jnp":
            return True
        if root == "jax" and name in {"device_put", "device_get"}:
            return True
        # jax.vmap(...)(args), jax.jit(...)(args)
        if isinstance(call.func, ast.Call):
            inner = call.func
            if call_root(inner.func) == "jax" \
                    and attr_name(inner) in {"vmap", "pmap", "jit"}:
                return True
        return False

    def _tainted_expr(self, node: ast.AST) -> bool:
        """Does this expression evaluate to (or contain) a device
        value? Calls are boundaries: a device call taints, any other
        call is *opaque* — its result is not assumed device-typed
        just because a device value went in (guarded_device_get and
        host helpers would otherwise poison everything downstream)."""
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            return self._is_device_call(node)
        return any(self._tainted_expr(c)
                   for c in ast.iter_child_nodes(node))

    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._taint_target(el)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    def _untaint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._untaint_target(el)

    def _is_guard_call(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and (attr_name(node) in GUARD_NAMES))

    # -- visitors -----------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        v = node.value
        if isinstance(v, ast.Call) and attr_name(v) in FACTORY_NAMES:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.jit_callables.add(t.id)
            return
        if self._is_guard_call(v):
            for t in node.targets:
                self._untaint_target(t)
            return
        if self._tainted_expr(v):
            for t in node.targets:
                self._taint_target(t)
        else:
            for t in node.targets:
                self._untaint_target(t)

    def visit_For(self, node: ast.For) -> None:
        if self._tainted_expr(node.iter):
            self._taint_target(node.target)
        self.generic_visit(node)

    def visit_comprehension_targets(self, node) -> None:
        for gen in node.generators:
            if self._tainted_expr(gen.iter):
                self._taint_target(gen.target)

    def visit_ListComp(self, node) -> None:
        self.visit_comprehension_targets(node)
        self.generic_visit(node)

    visit_SetComp = visit_GeneratorExp = visit_ListComp

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        name = attr_name(node)
        root = call_root(node.func)
        if name == "device_get" and root != "_platform":
            self.findings.append(Finding(
                self.sf.rel, node.lineno, "JTS101",
                "raw jax.device_get bypasses the sync watchdog and "
                "fault classifier; route through "
                "_platform.guarded_device_get"))
            return
        if name == "block_until_ready":
            self.findings.append(Finding(
                self.sf.rel, node.lineno, "JTS102",
                ".block_until_ready() is an unguarded blocking sync; "
                "route through _platform.guarded_device_get"))
            return
        implicit = (root in NP_ROOTS and name in {"asarray", "array"}) \
            or (isinstance(node.func, ast.Name)
                and node.func.id in SYNC_BUILTINS)
        if implicit and any(self._tainted_expr(a) for a in node.args):
            self.findings.append(Finding(
                self.sf.rel, node.lineno, "JTS103",
                f"{name}() over a device value is an implicit "
                "unguarded sync; fetch via "
                "_platform.guarded_device_get first"))


class DeviceSyncAnalyzer(Analyzer):
    name = "device-sync"
    codes = ("JTS101", "JTS102", "JTS103")

    def scope(self, sf: SourceFile) -> bool:
        return (sf.rel.startswith("jepsen_tpu/checker/")
                or sf.rel == "jepsen_tpu/service.py")

    def check_file(self, sf: SourceFile) -> list[Finding]:
        if sf.tree is None:
            return []
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                ft = _FunctionTaint(sf, findings)
                for stmt in node.body:
                    ft.visit(stmt)
        # dedup: nested defs are visited by both their own walk entry
        # and the enclosing function's body visit
        return sorted(set(findings),
                      key=lambda f: (f.line, f.code, f.message))
