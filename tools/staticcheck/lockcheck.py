"""Lock-discipline analyzer (JTS20x): statically verify that
annotated shared state is only touched under its lock.

The daemon modules (`service.py`, `telemetry.py`, `store.py`,
`trace.py`) share mutable state across threads; PR 8 already shipped
one such race (`Journal.subscribe`'s async unsubscribe). No test
exhaustively pins lock discipline — so it is *declared* and checked:

  * ``self.attr = ...  # guarded-by: <lock>`` on the attribute's
    initialisation declares that every later read/write of
    ``self.attr`` in that class must be lexically inside a
    ``with self.<lock>:`` block, inside ``__init__``/``__new__``
    (single-threaded construction), or inside a method annotated
    ``def m(...):  # holds: <lock>`` (callers own the lock).
  * Module-level ``NAME = ...  # guarded-by: <lock>`` does the same
    for module globals under a module-level ``with <lock>:``.

  JTS201  annotated attribute accessed without its lock
  JTS202  lock-order inversion: `with A: with B:` somewhere and
          `with B: with A:` somewhere else in the same module
  JTS203  annotation names a lock the class/module never assigns

Known lexical limits (documented in doc/static_analysis.md): accesses
through a different object (``child.value`` from the registry) and
closures that escape their ``with`` block are not checked; deliberate
lock-free fast paths carry an explanatory ``# noqa: JTS201``."""

from __future__ import annotations

import ast
import re

from .base import Analyzer, Finding, SourceFile

GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)")
INIT_METHODS = {"__init__", "__new__"}


def _outermost_functions(tree: ast.AST):
    """Function defs not nested inside another function (module-level
    defs and class methods at any class depth)."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        else:
            stack.extend(ast.iter_child_nodes(node))


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.guarded: dict[str, tuple[str, int]] = {}  # attr -> (lock, line)
        self.assigned_attrs: set[str] = set()


def _holds_for(sf: SourceFile, fn: ast.FunctionDef) -> set[str]:
    """Locks a `# holds:` annotation declares for a def — on the def
    line itself or the comment line directly above it."""
    for ln in (fn.lineno, fn.lineno - 1):
        if 1 <= ln <= len(sf.lines):
            m = HOLDS_RE.search(sf.lines[ln - 1])
            if m:
                return {t.strip() for t in m.group(1).split(",")}
    return set()


class _Walker:
    """Lexical walk of one function, tracking held annotated locks."""

    def __init__(self, analyzer: "LockAnalyzer", sf: SourceFile,
                 cls: _ClassInfo | None, fn: ast.FunctionDef,
                 findings: list[Finding]):
        self.a = analyzer
        self.sf = sf
        self.cls = cls
        self.fn = fn
        self.findings = findings
        self.holds = _holds_for(sf, fn)
        self.held: list[tuple[str, str]] = []   # (owner, lock)

    def _owner(self) -> str:
        return self.cls.name if self.cls else "<module>"

    def walk(self, node: ast.AST) -> None:
        if isinstance(node, ast.With):
            entered = []
            for item in node.items:
                ctx = item.context_expr
                lock = _self_attr(ctx)
                is_lock = (lock is not None and self.cls is not None
                           and lock in self.a.class_locks.get(
                               self.cls.name, set()))
                if not is_lock:
                    # a with-item that is NOT a lock acquisition is an
                    # ordinary access (`with self._fh:`) — check it
                    # under the locks held so far (items acquire
                    # left-to-right)
                    for sub in ast.walk(ctx):
                        self._check_access(sub)
                owner = None
                if is_lock:
                    owner = self.cls.name
                elif isinstance(ctx, ast.Name) \
                        and ctx.id in self.a.module_locks:
                    owner, lock = "<module>", ctx.id
                if owner is not None:
                    for prev in self.held:
                        self.a.order_pairs.setdefault(
                            (prev, (owner, lock)), node.lineno)
                    self.held.append((owner, lock))
                    entered.append((owner, lock))
            for child in node.body:
                self.walk(child)
            for _ in entered:
                self.held.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not self.fn:
            # nested def: checked in its own right by the caller; its
            # body inherits the lexical with-state (closures that run
            # later are a documented limit)
            for child in node.body:
                self.walk(child)
            return
        self._check_access(node)
        for child in ast.iter_child_nodes(node):
            self.walk(child)

    def _check_access(self, node: ast.AST) -> None:
        if self.cls is not None:
            attr = _self_attr(node)
            if attr is not None and attr in self.cls.guarded:
                lock, _ = self.cls.guarded[attr]
                if self.fn.name in INIT_METHODS:
                    return
                if lock in self.holds:
                    return
                if (self.cls.name, lock) in self.held:
                    return
                self.findings.append(Finding(
                    self.sf.rel, node.lineno, "JTS201",
                    f"'{self.cls.name}.{attr}' is guarded by "
                    f"'self.{lock}' but accessed outside it (wrap in "
                    f"'with self.{lock}:' or annotate the method "
                    f"'# holds: {lock}')"))
        if isinstance(node, ast.Name) \
                and node.id in self.a.module_guarded:
            lock = self.a.module_guarded[node.id][0]
            if lock in self.holds or ("<module>", lock) in self.held:
                return
            if node.id == lock:
                return
            self.findings.append(Finding(
                self.sf.rel, node.lineno, "JTS201",
                f"module global '{node.id}' is guarded by '{lock}' "
                f"but accessed outside 'with {lock}:'"))


class LockAnalyzer(Analyzer):
    name = "locks"
    codes = ("JTS201", "JTS202", "JTS203")

    def check_file(self, sf: SourceFile) -> list[Finding]:
        if sf.tree is None:
            return []
        findings: list[Finding] = []
        self.module_guarded: dict[str, tuple[str, int]] = {}
        self.module_locks: set[str] = set()
        self.class_locks: dict[str, set[str]] = {}
        self.order_pairs: dict[tuple, int] = {}
        classes: list[tuple[ast.ClassDef, _ClassInfo]] = []

        # -- collect annotations --------------------------------------------
        for node in ast.iter_child_nodes(sf.tree):
            if isinstance(node, ast.ClassDef):
                ci = _ClassInfo(node.name)
                for sub in ast.walk(node):
                    tgts = []
                    if isinstance(sub, ast.Assign):
                        tgts = sub.targets
                    elif isinstance(sub, ast.AnnAssign):
                        tgts = [sub.target]
                    for t in tgts:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        ci.assigned_attrs.add(attr)
                        m = GUARD_RE.search(
                            sf.lines[sub.lineno - 1]) \
                            if sub.lineno <= len(sf.lines) else None
                        if m:
                            ci.guarded[attr] = (m.group(1), sub.lineno)
                classes.append((node, ci))
                self.class_locks[node.name] = {
                    lock for lock, _ in ci.guarded.values()}
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                tgt = node.targets[0] if isinstance(node, ast.Assign) \
                    else node.target
                if isinstance(tgt, ast.Name) \
                        and node.lineno <= len(sf.lines):
                    m = GUARD_RE.search(sf.lines[node.lineno - 1])
                    if m:
                        self.module_guarded[tgt.id] = (m.group(1),
                                                       node.lineno)

        # same-module inheritance: a subclass inherits the base's
        # guarded-attr declarations and lock assignments (telemetry's
        # _Child hierarchy declares `value # guarded-by: _lock` once)
        by_name = {node.name: (node, ci) for node, ci in classes}
        for node, ci in classes:
            seen_bases: set[str] = set()
            stack = [b.id for b in node.bases
                     if isinstance(b, ast.Name)]
            while stack:
                bname = stack.pop()
                if bname in seen_bases or bname not in by_name:
                    continue
                seen_bases.add(bname)
                bnode, bci = by_name[bname]
                for attr, ann in bci.guarded.items():
                    ci.guarded.setdefault(attr, ann)
                ci.assigned_attrs |= bci.assigned_attrs
                stack.extend(b.id for b in bnode.bases
                             if isinstance(b, ast.Name))
            self.class_locks[node.name] = {
                lock for lock, _ in ci.guarded.values()}

        self.module_locks = {lock for lock, _
                             in self.module_guarded.values()}
        module_names = {t.id for n in ast.iter_child_nodes(sf.tree)
                        if isinstance(n, ast.Assign)
                        for t in n.targets if isinstance(t, ast.Name)}

        # -- JTS203: annotation sanity --------------------------------------
        for _, ci in classes:
            for attr, (lock, line) in ci.guarded.items():
                if lock not in ci.assigned_attrs:
                    findings.append(Finding(
                        sf.rel, line, "JTS203",
                        f"'# guarded-by: {lock}' on "
                        f"'{ci.name}.{attr}' but the class never "
                        f"assigns 'self.{lock}'"))
        for name, (lock, line) in self.module_guarded.items():
            if lock not in module_names:
                findings.append(Finding(
                    sf.rel, line, "JTS203",
                    f"'# guarded-by: {lock}' on module global "
                    f"'{name}' but the module never assigns "
                    f"'{lock}'"))

        # -- access + ordering walk -----------------------------------------
        walked: set[int] = set()
        for node, ci in classes:
            if not ci.guarded:
                continue
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    walked.add(id(sub))
                    w = _Walker(self, sf, ci, sub, findings)
                    for stmt in sub.body:
                        w.walk(stmt)
        if self.module_guarded:
            # outermost functions only: _Walker descends into nested
            # defs itself, so walking every FunctionDef from ast.walk
            # would double-report accesses inside closures. Guarded-
            # class methods were walked above with class context (that
            # walk checks module globals too) — walking them again
            # would double-report those.
            for node in _outermost_functions(sf.tree):
                if id(node) in walked:
                    continue
                w = _Walker(self, sf, None, node, findings)
                for stmt in node.body:
                    w.walk(stmt)

        # -- JTS202: inversions ---------------------------------------------
        reported = set()
        for (a, b), line in sorted(self.order_pairs.items(),
                                   key=lambda kv: kv[1]):
            if (b, a) in self.order_pairs and (b, a) not in reported:
                reported.add((a, b))
                findings.append(Finding(
                    sf.rel, max(line, self.order_pairs[(b, a)]),
                    "JTS202",
                    f"lock-order inversion: {a[0]}.{a[1]} -> "
                    f"{b[0]}.{b[1]} here but {b[0]}.{b[1]} -> "
                    f"{a[0]}.{a[1]} at line "
                    f"{min(line, self.order_pairs[(b, a)])}"))
        return findings
