"""Retrace-hazard analyzer (JTS30x): keep every jitted kernel's trace
signature stable.

The checking kernels are compiled once per *shape bucket* (`_bucket`
rounds capacities to powers of two) and cached — the persistent
compile cache, `wgl.select_engine`'s cost model, and the PR 10
chunk-latency telemetry all assume a dispatch is a cache hit. A
retrace hazard silently violates that: the chunk histogram measures a
recompile, the cost model prices a kernel that is being rebuilt, and
the pin-hot assumption behind the daemon dies.

Scope: ``jepsen_tpu/checker/`` (the kernel-bearing modules named by
doc/static_analysis.md: wgl.py, wgl_dedup.py, elle/kernels.py,
streaming.py, plus their siblings).

  JTS301  jit-captured mutable module state: a ``@jax.jit`` function
          reads a module global that is reassigned somewhere (via a
          ``global`` statement or multiple module-level bindings) —
          the traced value is frozen at first compile, later writes
          are silently ignored (or force retraces via closure
          invalidation).
  JTS302  Python branch on a traced value: ``if``/``while`` on a
          parameter of a jit function (static properties —
          ``.shape``/``.dtype``/``.ndim``/``len()``/``isinstance``
          — are exempt).
  JTS303  unstable scalar signature: a call to a kernel entry
          (``k.check`` / ``check_stream_chunk`` / ... or a callable
          bound from a kernel factory) passing a bare Python numeric
          literal or ``int(...)``/``len(...)`` result where the
          repo's convention is a ``jnp.int32(...)``-wrapped operand —
          weak-type promotion gives the bare scalar a *different*
          trace signature, so one entry compiles twice.
  JTS304  unbucketed batch stack: an ``np.stack``/``np.concatenate``
          batch assembled from a dynamic-length list reaches a jit
          dispatch without its leading dimension passing through
          ``_bucket`` padding — every distinct batch count is a fresh
          XLA compile."""

from __future__ import annotations

import ast

from .base import (Analyzer, Finding, SourceFile, attr_name, call_root,
                   names_in)
from .devicesync import ENTRY_NAMES, FACTORY_NAMES
from .lockcheck import _outermost_functions

STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "weak_type"}
STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr"}
#: functions known to forward their array arguments straight into a
#: jit dispatch (extends the entry/factory sets for JTS304)
TRACED_SINKS = {"_classify_batches"}
BUCKET_FNS = {"_bucket", "table_size"}


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for d in fn.decorator_list:
        if isinstance(d, ast.Attribute) and d.attr == "jit":
            return True
        if isinstance(d, ast.Name) and d.id == "jit":
            return True
        if isinstance(d, ast.Call) and attr_name(d) in {"jit"}:
            return True
    return False


def _mutated_globals(tree: ast.AST) -> set[str]:
    """Module-level names that are mutable state: rebound via a
    ``global`` statement, bound more than once at module level, or
    augmented-assigned at module level."""
    declared_global: set[str] = set()
    counts: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
    for node in ast.iter_child_nodes(tree):
        tgts = []
        if isinstance(node, ast.Assign):
            tgts = [t for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            tgts = [node.target]
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name):
            declared_global.add(node.target.id)
        for t in tgts:
            counts[t.id] = counts.get(t.id, 0) + 1
    multi = {n for n, c in counts.items() if c > 1}
    return declared_global | multi


def _params(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class _Parented(ast.NodeVisitor):
    def __init__(self):
        self.parent: dict[int, ast.AST] = {}

    def generic_visit(self, node):
        for c in ast.iter_child_nodes(node):
            self.parent[id(c)] = node
        super().generic_visit(node)


def _traced_name_used(test: ast.AST, traced: set[str]) -> bool:
    """A traced name is *used as a value* in the test — not merely via
    a static property (x.shape, len(x), isinstance(x, ...))."""
    p = _Parented()
    p.visit(test)
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in traced):
            continue
        par = p.parent.get(id(node))
        if isinstance(par, ast.Attribute) and par.attr in STATIC_ATTRS:
            continue
        if isinstance(par, ast.Call) and node in par.args \
                and isinstance(par.func, ast.Name) \
                and par.func.id in STATIC_CALLS:
            continue
        return True
    return False


def _scalar_hazard(arg: ast.AST) -> bool:
    """A bare Python scalar expression (weak-typed under tracing)."""
    if isinstance(arg, ast.Constant) \
            and isinstance(arg.value, (int, float)) \
            and not isinstance(arg.value, bool):
        return True
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) \
            and arg.func.id in {"int", "len"}:
        return True
    if isinstance(arg, ast.UnaryOp):
        return _scalar_hazard(arg.operand)
    return False


class RetraceAnalyzer(Analyzer):
    name = "retrace"
    codes = ("JTS301", "JTS302", "JTS303", "JTS304")

    def scope(self, sf: SourceFile) -> bool:
        return sf.rel.startswith("jepsen_tpu/checker/")

    def check_file(self, sf: SourceFile) -> list[Finding]:
        if sf.tree is None:
            return []
        findings: list[Finding] = []
        mutated = _mutated_globals(sf.tree)
        for fn in [n for n in ast.walk(sf.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if _is_jit_decorated(fn):
                self._check_jit_fn(sf, fn, mutated, findings)
        # call-site checks walk nested defs themselves (their assigns
        # maps need the enclosing scope), so run them only on
        # outermost functions — else nested-def calls report twice
        for fn in _outermost_functions(sf.tree):
            self._check_call_sites(sf, fn, findings)
        return findings

    # -- JTS301 / JTS302 ----------------------------------------------------

    def _check_jit_fn(self, sf: SourceFile, fn: ast.FunctionDef,
                      mutated: set[str], findings: list[Finding]) -> None:
        local = _params(fn) | {
            t.id for n in ast.walk(fn) if isinstance(n, ast.Assign)
            for t in n.targets if isinstance(t, ast.Name)}
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in mutated and node.id not in local:
                findings.append(Finding(
                    sf.rel, node.lineno, "JTS301",
                    f"jit function '{fn.name}' closes over mutable "
                    f"module state '{node.id}' — the traced value is "
                    f"frozen at first compile; pass it as an "
                    f"argument or resolve it outside the kernel "
                    f"cache"))
        traced = _params(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and _traced_name_used(node.test, traced):
                findings.append(Finding(
                    sf.rel, node.lineno, "JTS302",
                    f"Python branch on traced value inside jit "
                    f"function '{fn.name}' — use lax.cond/jnp.where "
                    f"(or branch on a static property)"))

    # -- JTS303 / JTS304 ----------------------------------------------------

    def _check_call_sites(self, sf: SourceFile, fn: ast.FunctionDef,
                          findings: list[Finding]) -> None:
        assigns: dict[str, list[ast.AST]] = {}
        sub_assigns: dict[str, list[ast.AST]] = {}
        jit_callables: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name):
                # `padded += [pad] * (_bucket(...) - len(padded))` is
                # how the dispatch sites bucket their batch axis
                assigns.setdefault(node.target.id,
                                   []).append(node.value)
                continue
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)
                    if isinstance(node.value, ast.Call) \
                            and attr_name(node.value) in FACTORY_NAMES:
                        jit_callables.add(t.id)
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name):
                    sub_assigns.setdefault(t.value.id,
                                           []).append(node.value)

        # names whose value is derived from a _bucket(...) result
        buckety: set[str] = set()
        changed = True
        while changed:
            changed = False
            for name, rhss in assigns.items():
                if name in buckety:
                    continue
                for rhs in rhss:
                    if self._bucket_derived(rhs, buckety):
                        buckety.add(name)
                        changed = True
                        break

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            is_entry = (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ENTRY_NAMES) \
                or (isinstance(node.func, ast.Name)
                    and node.func.id in jit_callables)
            is_sink = is_entry or (isinstance(node.func, ast.Name)
                                   and node.func.id in TRACED_SINKS)
            if is_entry:
                for arg in node.args:
                    if _scalar_hazard(arg):
                        findings.append(Finding(
                            sf.rel, node.lineno, "JTS303",
                            f"bare Python scalar at jit entry "
                            f"'{attr_name(node)}' — wrap in "
                            f"jnp.int32(...) (weak-type promotion "
                            f"gives this call its own trace "
                            f"signature)"))
            if is_sink:
                self._check_stacks(sf, node, assigns, sub_assigns,
                                   buckety, findings)

    def _bucket_derived(self, expr: ast.AST, buckety: set[str]) -> bool:
        for c in ast.walk(expr):
            if isinstance(c, ast.Call) and attr_name(c) in BUCKET_FNS:
                return True
            if isinstance(c, ast.Name) and c.id in buckety:
                return True
        return False

    #: wrappers a staged batch flows through on its way to a dispatch
    PASSTHROUGH = {"asarray", "device_put", "maybe_corrupt"}

    def _check_stacks(self, sf: SourceFile, call: ast.Call,
                      assigns: dict, sub_assigns: dict,
                      buckety: set[str],
                      findings: list[Finding]) -> None:
        seen: set[str] = set()

        def visit(node: ast.AST, is_root: bool) -> None:
            if isinstance(node, ast.Call):
                if call_root(node.func) in {"np", "numpy", "jnp"} \
                        and attr_name(node) in {"stack",
                                                "concatenate"}:
                    if not self._stack_bucketed(node, assigns,
                                                buckety):
                        findings.append(Finding(
                            sf.rel, node.lineno, "JTS304",
                            f"dynamic {attr_name(node)}() batch "
                            f"reaches a jit dispatch without "
                            f"_bucket padding — every distinct "
                            f"batch count is a fresh XLA compile"))
                    for a in node.args:
                        visit(a, False)
                elif attr_name(node) in self.PASSTHROUGH:
                    for a in node.args:
                        visit(a, False)
                # any other call is opaque: its result's shape is its
                # own business (it re-chunks, re-buckets, or is host)
                return
            if isinstance(node, ast.Name):
                if node.id in seen:
                    return
                seen.add(node.id)
                for rhs in assigns.get(node.id, []):
                    # a sliced result no longer carries the stack's
                    # dynamic length
                    if not isinstance(rhs, ast.Subscript):
                        visit(rhs, False)
                if is_root:
                    for rhs in sub_assigns.get(node.id, []):
                        visit(rhs, False)
                return
            for c in ast.iter_child_nodes(node):
                visit(c, is_root)

        for a in call.args:
            visit(a, True)

    def _stack_bucketed(self, stack: ast.Call, assigns: dict,
                        buckety: set[str]) -> bool:
        """The stacked operand's length is visibly bucket-padded:
        the stack subtree (or the one-step definition of a name it
        references) involves a _bucket-derived value."""
        if self._bucket_derived(stack, buckety):
            return True
        for name in names_in(stack):
            for rhs in assigns.get(name, []):
                if self._bucket_derived(rhs, buckety):
                    return True
        return False
