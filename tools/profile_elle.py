#!/usr/bin/env python
"""Profile the elle list-append checker on a 100k-txn history.

Round-3 recorded 23,157 txns/s against round-2's 27,335 on the same
checker source; this harness exists to attribute that kind of movement
instead of arguing about it.  It reports:

  * a wall-clock breakdown of check()'s phases (history indexing,
    host graph build, device SCC/closure kernels, certificate
    reconstruction) — by re-running the phases the way check() composes
    them (`jepsen_tpu/checker/elle/list_append.py:243-274`);
  * best/median/worst of N full check() calls (run-to-run variance is
    the first suspect for a sub-10% delta);
  * optionally a jax.profiler trace (--trace DIR) for op-level
    attribution in TensorBoard/XProf.

Usage:
  python tools/profile_elle.py [--n 100000] [--repeat 5] [--trace DIR]
Writes a JSON summary to stdout (one line, like bench.py sections).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jepsen_tpu._platform import honor_platform_env  # noqa: E402

honor_platform_env()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--trace", default=None,
                    help="directory for a jax.profiler trace of one run")
    args = ap.parse_args()

    import jax

    from jepsen_tpu.checker import synth
    from jepsen_tpu.checker.elle import kernels, list_append
    from jepsen_tpu.history import history as as_history

    out = {"n_txns": args.n,
           "platform": jax.devices()[0].platform,
           "device_kind": getattr(jax.devices()[0], "device_kind", "?")}

    t0 = time.monotonic()
    eh = synth.append_history(args.n, seed=45100)
    out["synth_s"] = round(time.monotonic() - t0, 3)

    # warm: compile every kernel shape this history exercises
    r = list_append.check(eh)
    assert r["valid?"] is True, r

    # ---- phase breakdown (mirrors check()'s composition) ----
    phases = {}
    t0 = time.monotonic()
    hist = as_history(eh).index()
    phases["index_history_s"] = round(time.monotonic() - t0, 3)

    t0 = time.monotonic()
    txns, edges, a, incompatible = list_append.graph(hist)
    phases["graph_build_s"] = round(time.monotonic() - t0, 3)

    t0 = time.monotonic()
    a.g1a_cases(), a.g1b_cases(), list_append.internal_cases(a.hist)
    phases["read_write_cases_s"] = round(time.monotonic() - t0, 3)

    t0 = time.monotonic()
    cyc = kernels.analyze_edges(len(txns), edges)
    phases["device_scc_closure_s"] = round(time.monotonic() - t0, 3)

    t0 = time.monotonic()
    kernels.certificates(txns, edges, cyc)
    phases["certificates_s"] = round(time.monotonic() - t0, 3)
    out["phases"] = phases
    out["edge_count"] = (int(edges.shape[0])
                         if hasattr(edges, "shape") else len(edges))

    # ---- full-call variance ----
    times = []
    for _ in range(args.repeat):
        t0 = time.monotonic()
        r = list_append.check(eh)
        times.append(time.monotonic() - t0)
        assert r["valid?"] is True
    out["check_s"] = {
        "best": round(min(times), 3),
        "median": round(statistics.median(times), 3),
        "worst": round(max(times), 3),
        "spread_pct": round(100 * (max(times) - min(times)) / min(times),
                            1),
    }
    out["txns_per_s_best"] = round(args.n / min(times), 1)

    if args.trace:
        with jax.profiler.trace(args.trace):
            list_append.check(eh)
        out["trace_dir"] = args.trace

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
