# CI gate (the reference gates pushes on lint + unit tests,
# `.travis.yml:1-11`: lein eastwood + lein test).  `make check` is the
# one command to run before pushing.

PY ?= python

.PHONY: lint staticcheck test tier0 tier1 check chaos-smoke chaos-soak

# the full static gate: style/imports + metric naming + device-sync
# (JTS1xx) + lock discipline (JTS2xx) + retrace hazards (JTS3xx) on
# one driver, baselined — see doc/static_analysis.md. Subsumes the
# old tools/lint.py + tools/lint_metrics.py (kept as shims).
lint:
	$(PY) -m tools.staticcheck
	$(PY) -m compileall -q jepsen_tpu tests tools bench.py __graft_entry__.py

# the AST-only analyzers (no module imports, runs in ~a second) —
# the tier0 pre-gate slice; `make lint` adds the registry-import
# metrics pass and compileall on top.
staticcheck:
	$(PY) -m tools.staticcheck --only style,device-sync,locks,retrace

test:
	$(PY) -m pytest tests/ -q

# fast pre-gate: staticcheck plus the tier-1 screen + ABFT attestation
# suites, the telemetry registry/exposition suite, and the adaptive
# overload-control suite (seconds, no kernel compiles beyond the small
# fault matrices) — run before the full tier-1 sweep so a broken
# invariant/observability/structural/scheduling layer fails in the
# first minute, not the fortieth. CI runs this first. The search smoke
# excludes the A/B acceptance demo and the service round trip (both
# run in tier1); the rest of tests/test_search.py is seconds. The
# kill-and-recover smoke SIGKILLs a service daemon mid-stream and
# asserts recover() reproduces the solo verdicts byte-for-byte — the
# crash-consistency contract gates here even though the test carries
# the slow marker (tier1 filters it out; tier0 names it explicitly).
# The chaos line runs the harness unit tests plus the pinned
# guided-vs-random A/B (slow-marked, named here like the sigkill
# smoke); the corrupt-manifest recover pin stays in the slow tier.
tier0: staticcheck
	$(PY) -m pytest tests/test_screen.py tests/test_attest.py \
		tests/test_telemetry.py tests/test_staticcheck.py \
		tests/test_adaptive.py -q
	$(PY) -m pytest tests/test_search.py -q \
		-k 'not ab_demo and not service_escalation'
	$(PY) -m pytest tests/test_chaos.py -q -k 'not corrupt_manifest'
	$(PY) -m pytest tests/test_service_crash.py -q -k 'sigkill'

# the driver's tier-1 gate: everything not marked slow (the slow tier
# holds the larger shape sweeps, e.g. the pallas dedup parity sweep).
# Device-fault recovery is covered deterministically here via the
# fault-injection shim (tests/test_recovery.py): set
# JEPSEN_TPU_FAULT_INJECT=kind@site:n (kind ∈ oom|device-lost|
# compile|wedged; site ∈ offline|batch|sharded|stream-chunk) to
# reproduce any bucket by hand against a live entry — e.g.
#   JEPSEN_TPU_FAULT_INJECT=oom@stream-chunk:3 make tier1
# exercises the OOM backpressure rung under the whole suite.
tier1:
	$(PY) -m pytest tests/ -q -m 'not slow'

# tier-0 self-chaos gate: 20 guided fault schedules against the live
# pipeline on CPU, every run held to the five oracles (verdict
# byte-identity vs an uninjected solo, violation-missed, watchdog,
# resource-leak, stamp-consistency) — doc/robustness.md `Self-chaos`.
# Exits non-zero if any oracle fires; a found failure is shrunk to a
# minimal schedule and printed in the JSON result.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m jepsen_tpu.cli chaos \
		--budget 20 --ops 128 --seed 23

# open-ended soak: a long guided campaign with a generous deadline —
# run overnight (or on real hardware, where the recovery rungs hit
# actual device resets) and keep the chaos.json/coverage.bin corpus.
chaos-soak:
	JAX_PLATFORMS=cpu $(PY) -m jepsen_tpu.cli chaos \
		--budget 400 --ops 256 --seed 45100 --deadline-s 600 \
		--store-dir scratch/chaos-soak

check: lint test chaos-smoke
