# CI gate (the reference gates pushes on lint + unit tests,
# `.travis.yml:1-11`: lein eastwood + lein test).  `make check` is the
# one command to run before pushing.

PY ?= python

.PHONY: lint test check

lint:
	$(PY) tools/lint.py
	$(PY) -m compileall -q jepsen_tpu tests tools bench.py __graft_entry__.py

test:
	$(PY) -m pytest tests/ -q

check: lint test
