# CI gate (the reference gates pushes on lint + unit tests,
# `.travis.yml:1-11`: lein eastwood + lein test).  `make check` is the
# one command to run before pushing.

PY ?= python

.PHONY: lint test tier0 tier1 check

lint:
	$(PY) tools/lint.py
	$(PY) tools/lint_metrics.py
	$(PY) -m compileall -q jepsen_tpu tests tools bench.py __graft_entry__.py

test:
	$(PY) -m pytest tests/ -q

# fast pre-gate: the tier-1 screen + ABFT attestation suites plus the
# telemetry registry/exposition suite (seconds, no kernel compiles
# beyond the small fault matrices) — run before the full tier-1 sweep
# so a broken screen/attestation/observability layer fails in the
# first minute, not the fortieth. CI runs this first.
tier0:
	$(PY) -m pytest tests/test_screen.py tests/test_attest.py \
		tests/test_telemetry.py -q

# the driver's tier-1 gate: everything not marked slow (the slow tier
# holds the larger shape sweeps, e.g. the pallas dedup parity sweep).
# Device-fault recovery is covered deterministically here via the
# fault-injection shim (tests/test_recovery.py): set
# JEPSEN_TPU_FAULT_INJECT=kind@site:n (kind ∈ oom|device-lost|
# compile|wedged; site ∈ offline|batch|sharded|stream-chunk) to
# reproduce any bucket by hand against a live entry — e.g.
#   JEPSEN_TPU_FAULT_INJECT=oom@stream-chunk:3 make tier1
# exercises the OOM backpressure rung under the whole suite.
tier1:
	$(PY) -m pytest tests/ -q -m 'not slow'

check: lint test
