# CI gate (the reference gates pushes on lint + unit tests,
# `.travis.yml:1-11`: lein eastwood + lein test).  `make check` is the
# one command to run before pushing.

PY ?= python

.PHONY: lint test tier1 check

lint:
	$(PY) tools/lint.py
	$(PY) -m compileall -q jepsen_tpu tests tools bench.py __graft_entry__.py

test:
	$(PY) -m pytest tests/ -q

# the driver's tier-1 gate: everything not marked slow (the slow tier
# holds the larger shape sweeps, e.g. the pallas dedup parity sweep)
tier1:
	$(PY) -m pytest tests/ -q -m 'not slow'

check: lint test
