"""History/op model tests (reference test strategy: literal op vectors in,
derived structure out — jepsen/test/jepsen/ style)."""

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu import txn


def mk(type, f, value, process, time=0):
    return h.op(type, f, value, process, time)


def test_index():
    hist = h.History([mk("invoke", "read", None, 0),
                      mk("ok", "read", 1, 0)])
    idx = hist.index()
    assert [o["index"] for o in idx] == [0, 1]


def test_pairing_basic():
    hist = h.History([
        mk("invoke", "write", 1, 0),
        mk("invoke", "read", None, 1),
        mk("ok", "write", 1, 0),
        mk("ok", "read", 1, 1),
    ])
    p = hist.pair_index()
    assert p == {0: 2, 2: 0, 1: 3, 3: 1}
    assert hist.completion(0)["type"] == "ok"


def test_pairing_pending_and_nemesis():
    hist = h.History([
        mk("invoke", "write", 1, 0),
        mk("info", "start-partition", None, "nemesis"),
        mk("info", "write", 1, 0),          # crashed
        mk("invoke", "write", 2, 0),        # process reused after info? no —
    ])
    p = hist.pair_index()
    assert p[0] == 2 and p[2] == 0
    assert 1 not in p          # nemesis doesn't pair
    assert 3 not in p          # pending invoke


def test_without_failures():
    hist = h.History([
        mk("invoke", "cas", (1, 2), 0),
        mk("fail", "cas", (1, 2), 0),
        mk("invoke", "write", 3, 1),
        mk("ok", "write", 3, 1),
    ])
    out = hist.without_failures()
    assert len(out) == 2
    assert all(o["f"] == "write" for o in out)


def test_filters():
    hist = h.History([
        mk("invoke", "read", None, 0),
        mk("ok", "read", 5, 0),
        mk("invoke", "write", 1, 1),
        mk("info", "write", 1, 1),
        mk("info", "kill", None, "nemesis"),
    ])
    assert len(hist.oks()) == 1
    assert len(hist.infos()) == 2
    assert len(hist.client_ops()) == 4
    assert len(hist.filter_f("write")) == 2


def test_encode_ops_register():
    hist = h.History([
        mk("invoke", "write", 1, 0, 10),
        mk("ok", "write", 1, 0, 20),
        mk("invoke", "read", None, 1, 15),
        mk("ok", "read", 1, 1, 25),
        mk("invoke", "cas", (1, 2), 0, 30),
        mk("fail", "cas", (1, 2), 0, 40),      # dropped: fail
        mk("invoke", "write", 9, 2, 35),
        mk("info", "write", 9, 2, 45),         # kept: pending write
        mk("invoke", "read", None, 3, 36),     # dropped: pending read
    ]).index()
    ops = h.encode_ops(hist)
    assert len(ops) == 3
    # write op
    assert ops.f[0] == h.F_WRITE and ops.a[0] == 1
    assert ops.kind[0] == h.KIND_OK
    assert ops.inv[0] == 0 and ops.ret[0] == 1
    # read op: completion value is authoritative
    assert ops.f[1] == h.F_READ and ops.a[1] == 1
    # pending write
    assert ops.kind[2] == h.KIND_INFO
    assert ops.ret[2] == h.PENDING_RET
    assert ops.process.dtype == np.int32
    assert ops.inv.dtype == np.int32 and ops.ret.dtype == np.int32
    # PENDING_RET must survive an int32 cast (TPU has no int64)
    assert np.int32(h.PENDING_RET) == h.PENDING_RET > 2**30


def test_encode_ops_cas_values():
    hist = h.History([
        mk("invoke", "cas", (3, 4), 0),
        mk("ok", "cas", (3, 4), 0),
    ]).index()
    ops = h.encode_ops(hist)
    assert ops.f[0] == h.F_CAS and ops.a[0] == 3 and ops.b[0] == 4


# -- txn ---------------------------------------------------------------------

def test_ext_reads():
    assert txn.ext_reads([["r", "x", 1], ["w", "y", 2], ["r", "y", 3]]) \
        == {"x": 1}
    assert txn.ext_reads([["r", "x", 1], ["r", "x", 2]]) == {"x": 1}
    assert txn.ext_reads([["w", "x", 1], ["r", "x", 1]]) == {}


def test_ext_writes():
    assert txn.ext_writes([["w", "x", 1], ["w", "x", 2], ["r", "y", 3]]) \
        == {"x": 2}
    assert txn.ext_writes([["r", "x", 1]]) == {}


def test_int_write_mops():
    assert txn.int_write_mops([["w", "x", 1], ["w", "x", 2], ["w", "y", 3]]) \
        == {"x": [["w", "x", 1]]}
    assert txn.int_write_mops([["w", "x", 1]]) == {}


def test_reduce_mops_and_op_mops():
    hist = [{"value": [["r", "x", 1], ["w", "y", 2]]},
            {"value": [["w", "x", 3]]}]
    total = txn.reduce_mops(lambda acc, op, mop: acc + 1, 0, hist)
    assert total == 3
    assert len(list(txn.op_mops(hist))) == 3
