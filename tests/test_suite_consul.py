"""Consul suite tests: DB command generation, the index-CAS client
against an in-process fake consul KV over real HTTP, and a hermetic
suite run."""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import consul, suite


class FakeConsul:
    """/v1/kv/<key>: GET returns [{Value: b64, ModifyIndex}], PUT with
    ?cas=<index> succeeds iff index matches (0 = create)."""

    def __init__(self):
        self.kv: dict[str, tuple[str, int]] = {}
        self.index = 0
        self.lock = threading.Lock()
        self.server = None

    def start(self) -> int:
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):  # noqa: N802
                key = self.path.split("?")[0][len("/v1/kv/"):]
                with fake.lock:
                    if key not in fake.kv:
                        self.send_response(404)
                        self.end_headers()
                        return
                    val, idx = fake.kv[key]
                body = json.dumps([{
                    "Key": key,
                    "Value": base64.b64encode(val.encode()).decode(),
                    "ModifyIndex": idx,
                }]).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                key = path[len("/v1/kv/"):]
                n = int(self.headers.get("Content-Length", 0))
                val = self.rfile.read(n).decode()
                cas = None
                if query.startswith("cas="):
                    cas = int(query[4:])
                with fake.lock:
                    cur_idx = fake.kv.get(key, (None, 0))[1]
                    ok = cas is None or cas == cur_idx
                    if ok:
                        fake.index += 1
                        fake.kv[key] = (val, fake.index)
                body = b"true" if ok else b"false"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        return self.server.server_address[1]

    def stop(self):
        if self.server:
            self.server.shutdown()


@pytest.fixture
def fake():
    f = FakeConsul()
    f.port = f.start()
    yield f
    f.stop()


def test_registry():
    assert suite("consul") is consul


def test_db_commands():
    log = []
    remote = dummy.remote(
        log=log, responses={r"ls -A \.": "consul"})
    test = {"nodes": ["n1", "n2"],
            "tarball": "file:///tmp/consul.zip"}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            consul.db().setup(test, "n1")
            log_cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
            assert "-bootstrap" in log_cmds      # n1 is primary
            log.clear()
        sess2 = control.session("n2")
        with control.with_session("n2", sess2):
            consul.db().start(test, "n2")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "-retry-join n1" in cmds


def test_client_cas_semantics(fake):
    t = {"consul-url-fn": lambda n: f"http://127.0.0.1:{fake.port}"}
    c = consul.ConsulClient().open(t, "n1")
    r = c.invoke(t, {"f": "read", "process": 0})
    assert r["type"] == "ok" and r["value"] is None
    assert c.invoke(t, {"f": "write", "value": 3,
                        "process": 0})["type"] == "ok"
    assert c.invoke(t, {"f": "cas", "value": [3, 4],
                        "process": 0})["type"] == "ok"
    assert c.invoke(t, {"f": "cas", "value": [3, 1],
                        "process": 0})["type"] == "fail"
    assert c.invoke(t, {"f": "read", "process": 0})["value"] == 4


def test_client_refused_is_fail():
    t = {"consul-url-fn": lambda n: "http://127.0.0.1:1"}
    c = consul.ConsulClient(timeout_s=0.2).open(t, "n1")
    assert c.invoke(t, {"f": "write", "value": 1,
                        "process": 0})["type"] == "fail"


def test_hermetic_suite_run(tmp_path, fake):
    import jepsen_tpu.db
    import jepsen_tpu.nemesis
    import jepsen_tpu.os_
    t = consul.consul_test({
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 3,
        "ssh": {"dummy": True},
        "rate": 100,
        "time-limit": 2,
        "store-dir": str(tmp_path / "store"),
    })
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["nemesis"] = jepsen_tpu.nemesis.noop
    t["consul-url-fn"] = lambda n: f"http://127.0.0.1:{fake.port}"
    done = core.run(t)
    assert done["results"]["valid?"] is True, done["results"]
    assert len(done["history"]) > 10
